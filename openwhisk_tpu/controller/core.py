"""Controller assembly: wires stores, balancer, entitlement, APIs.

Rebuild of core/controller/.../controller/Controller.scala:74-166 — boots the
HTTP service, resolves the SPIs (load balancer, entitlement, authentication,
stores), ensures bus topics, exposes /invokers and /metrics. Rule status
lives on the trigger document exactly as in the reference (Rules.scala).
"""
from __future__ import annotations

from typing import Optional

from aiohttp import web

from .. import spi
from ..containerpool.logstore import ContainerLogStore
from ..core.entity import (ACTIVE, ControllerInstanceId, INACTIVE, ReducedRule)
from ..database import (ArtifactActivationStore, AuthStore, EntityStore,
                        MemoryArtifactStore, NoDocumentException,
                        RemoteCacheInvalidation)
from ..utils.logging import Logging, MetricEmitter
from .api import ControllerApi
from .cors import CorsSettings
from .loadbalancer.base import LoadBalancer
from .authentication import BasicAuthenticationProvider
from .entitlement import LocalEntitlementProvider
from .invoke import ActionInvoker
from .routemgmt import ApiRouteManager
from .sequences import SequenceInvoker
from .triggers_service import TriggerService
from .web_actions import WebActionsApi


class Controller:
    def __init__(self, instance: ControllerInstanceId, messaging_provider,
                 artifact_store=None, logger: Optional[Logging] = None,
                 load_balancer=None, entitlement=None,
                 action_sequence_limit: int = 50,
                 invocations_per_minute: int = 60,
                 concurrent_invocations: int = 30,
                 fires_per_minute: int = 60,
                 log_store=None, extra_routes=None):
        self.instance = instance
        self.provider = messaging_provider
        self.logger = logger or Logging()
        self.metrics = self.logger.metrics
        store = artifact_store if artifact_store is not None else MemoryArtifactStore()
        self.artifact_store = store
        self.cache_invalidation = RemoteCacheInvalidation(
            messaging_provider, instance.as_string, logger=self.logger)
        self.entity_store = EntityStore(
            store, on_invalidate=lambda key: self.cache_invalidation
            .notify_other_instances("whisks", key))
        self.cache_invalidation.register("whisks", self.entity_store.cache)
        self.auth_store = AuthStore(store)
        self.activation_store = ArtifactActivationStore(store)
        self.authenticator = BasicAuthenticationProvider(self.auth_store)
        self.load_balancer = load_balancer
        self.entitlement = entitlement or LocalEntitlementProvider(
            load_balancer, invocations_per_minute, concurrent_invocations,
            fires_per_minute, metrics=self.metrics,
            event_producer=messaging_provider.get_producer())
        self.action_sequence_limit = action_sequence_limit
        self.invoker = ActionInvoker(self.entity_store, self.activation_store,
                                     load_balancer, instance, self.logger)
        self.sequencer = SequenceInvoker(self.entity_store, self.activation_store,
                                         self.invoker, instance,
                                         action_sequence_limit)
        from .conductors import ConductorInvoker
        self.conductor = ConductorInvoker(self.entity_store, self.activation_store,
                                          self.invoker, action_sequence_limit)
        self.trigger_service = TriggerService(self.entity_store,
                                              self.activation_store,
                                              self.invoker, self.sequencer,
                                              self.conductor)
        # sequences route conductor components through the composition loop
        self.sequencer.conductor = self.conductor
        self.cors = CorsSettings.from_env()
        self.web_actions = WebActionsApi(self)
        self.log_store = log_store if log_store is not None \
            else ContainerLogStore()
        self.route_manager = ApiRouteManager(store)
        self.api = ControllerApi(self)
        self._runner: Optional[web.AppRunner] = None
        self.membership = None
        # (method, path, handler) triples mounted beside /api/v1 at start —
        # the seam the standalone playground UI plugs into. These are
        # operator-mounted dev/ops pages, served without platform auth (the
        # playground page authenticates its own API calls)
        self.extra_routes = list(extra_routes or [])
        self.public_extra_paths = {path for _, path, _ in self.extra_routes}
        # resources an assembler (e.g. standalone) co-locates with this
        # controller; each must expose an async stop()
        self.owned_resources: list = []
        # HA failover (loadbalancer/membership.py leadership): assemblers
        # set these BEFORE start() to run the epoch-fenced active/standby
        # protocol on the membership heartbeats. on_leadership(epoch,
        # active) may be async (promotion restores snapshot+journal).
        self.ha_failover = False
        self.on_leadership = None
        # Active/active partitioned controllers (loadbalancer/partitions
        # .py): assemblers set the ring + the partition-transition
        # callback BEFORE start(). on_partitions(gained, lost) may be
        # async (a gain absorbs the previous owner's journal tail).
        # spillover_receiver (loadbalancer/spillover.py) is started/
        # stopped with the controller when attached.
        self.ha_partition_ring = None
        self.on_partitions = None
        self.spillover_receiver = None
        # admission funnel (loadbalancer/funnel.py, ISSUE 20): the
        # balancer-role assembler attaches a FunnelReceiver BEFORE
        # start(); started/stopped with the controller like spillover.
        # None (the default and the --role all path) keeps today's
        # single-process behavior bit-exact.
        self.funnel_receiver = None
        # fleet observatory (ISSUE 16): resolved once at assembly; start()
        # wires the admin-address announcement, the identity block and the
        # ctrlevents publisher only when enabled, so disabled stays a TRUE
        # no-op (byte-exact heartbeats, no topic, endpoints 404)
        from ..utils.eventlog import fleet_config
        self.fleet_config = fleet_config()
        self.fleet_events = None

    # -- rule status handling (status lives on the trigger doc) ------------
    async def rule_status(self, rule) -> str:
        try:
            trigger = await self.entity_store.get_trigger(str(rule.trigger))
            reduced = trigger.rules.get(rule.docid)
            return reduced.status if reduced else INACTIVE
        except NoDocumentException:
            return INACTIVE

    async def set_rule_status(self, rule_doc_id: str, status: str) -> None:
        rule = await self.entity_store.get_rule(rule_doc_id)
        trigger = await self.entity_store.get_trigger(str(rule.trigger))
        trigger.add_rule(rule_doc_id, ReducedRule(rule.action, status))
        await self.entity_store.put(trigger)

    async def delete_rule(self, rule_doc_id: str) -> dict:
        rule = await self.entity_store.get_rule(rule_doc_id)
        try:
            trigger = await self.entity_store.get_trigger(str(rule.trigger))
            trigger.remove_rule(rule_doc_id)
            await self.entity_store.put(trigger)
        except NoDocumentException:
            pass
        await self.entity_store.delete(rule)
        return rule.to_json()

    # -- lifecycle ---------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 3233) -> None:
        # fleet observatory identity: who this process is in every
        # snapshot the federation merges (partitions resolve live from
        # the balancer so the block tracks ownership changes)
        from ..utils.eventlog import GLOBAL_EVENT_LOG, set_identity
        fleet_on = self.fleet_config.enabled
        # an armed incident recorder (utils/blackbox.py) forces the event
        # log on — its structural-distress triggers arrive through it —
        # so a fleet-off deployment must not disarm it here
        from ..utils.blackbox import GLOBAL_INCIDENTS
        incidents_armed = GLOBAL_INCIDENTS.stats()["installed"]
        GLOBAL_EVENT_LOG.enabled = fleet_on or incidents_armed
        if fleet_on:
            lb_ = self.load_balancer

            def owned_parts():
                if getattr(lb_, "partition_ring", None) is not None:
                    return [p["partition"] for p in lb_.partitions_json()
                            if p["role"] == "active"]
                return []

            set_identity(instance=self.instance.instance, role="controller",
                         partitions_fn=owned_parts)
        admin_url = f"http://{host}:{port}" if fleet_on else None
        # host hot-loop observatory (utils/hostprof.py): event-loop lag,
        # GC pauses, task churn/serde accounting and the sampling profiler
        # arm on THIS controller's loop; the renderer joins this
        # controller's /metrics page. install() is a refused no-op when
        # CONFIG_whisk_hostProfiling_enabled=false or another controller
        # in this process already owns the observatory.
        from ..utils.hostprof import GLOBAL_HOST_OBSERVATORY, tune_gc
        self._host_observatory_owner = GLOBAL_HOST_OBSERVATORY.install(
            metrics=self.metrics)
        # opt-in GC tuning (CONFIG_whisk_host_gc_enabled): freeze the
        # boot-time permanent heap out of the collector and raise the
        # thresholds — full gen-2 scans were measured at 100-250 ms event
        # loop stalls under load (utils/hostprof.py GcTuningConfig)
        tuned = tune_gc()
        if tuned is not None:
            self.logger.info("controller",
                             f"gc tuned: froze {tuned['frozen']} objects, "
                             f"thresholds {tuned['thresholds']}",
                             "Controller")
        self.cache_invalidation.start()
        if hasattr(self.load_balancer, "start"):
            await self.load_balancer.start()
        if hasattr(self.load_balancer, "prepare_health_test_action"):
            # system test action for probing unhealthy invokers
            # (ref InvokerPool.prepare, InvokerSupervision.scala:239-252)
            await self.load_balancer.prepare_health_test_action(self.entity_store)
        lb_cls = type(self.load_balancer) if self.load_balancer else None
        if lb_cls is not None and \
                lb_cls.update_cluster is not LoadBalancer.update_cluster:
            # clustering balancer: join the membership protocol so joins /
            # crashes of peer controllers re-shard capacity at runtime
            # (replaces Akka Cluster events,
            # ShardingContainerPoolBalancer.scala:217-250)
            from .loadbalancer.membership import ControllerMembership
            lb = self.load_balancer

            def load_hint() -> float:
                # the spillover plane's least-loaded ranking: in-flight
                # activations + what is queued for the device
                return (lb.total_active_activations
                        + len(getattr(lb, "_pending", ())))

            self.membership = ControllerMembership(
                self.provider, self.instance, self.load_balancer,
                logger=self.logger, ha=self.ha_failover,
                on_leadership=self.on_leadership,
                ring=self.ha_partition_ring,
                on_partitions=self.on_partitions,
                load_hint=(load_hint if self.ha_partition_ring is not None
                           else None),
                admin_url=admin_url)
            self.membership.start()
        if fleet_on:
            # structural events -> ctrlevents topic, peers' frames folded
            # for the merged /admin/fleet/timeline
            from .fleet import FleetEvents
            self.fleet_events = FleetEvents(
                self.provider, self.instance.instance,
                config=self.fleet_config, logger=self.logger)
            self.fleet_events.start()
        if self.spillover_receiver is not None:
            self.spillover_receiver.start()
        if self.funnel_receiver is not None:
            self.funnel_receiver.start()
        app = self.api.make_app()
        for method, path, handler in self.extra_routes:
            app.router.add_route(method, path, handler)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.logger.info("controller", f"controller listening on {host}:{port}",
                         "Controller")

    async def stop(self) -> None:
        if getattr(self, "_host_observatory_owner", False):
            from ..utils.hostprof import GLOBAL_HOST_OBSERVATORY
            GLOBAL_HOST_OBSERVATORY.uninstall()
            self._host_observatory_owner = False
        if self._runner:
            await self._runner.cleanup()
        if self.membership is not None:
            await self.membership.stop()  # sends the graceful leave
        if self.fleet_events is not None:
            await self.fleet_events.stop()
            self.fleet_events = None
        if self.spillover_receiver is not None:
            await self.spillover_receiver.stop()
        if self.funnel_receiver is not None:
            await self.funnel_receiver.stop()
        for resource in self.owned_resources:
            await resource.stop()
        if hasattr(self.entitlement, "close"):
            # sharded front end: stop the admission worker loops
            await self.entitlement.close()
        if self.load_balancer is not None:
            await self.load_balancer.close()
        await self.cache_invalidation.stop()
        await self.artifact_store.close()

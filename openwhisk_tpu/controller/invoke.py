"""The primitive invoke path: entity resolution -> ActivationMessage ->
load balancer -> wait for the active ack (with DB-poll fallback).

Rebuild of core/controller/.../actions/PrimitiveActions.scala:152-206
(invokeSimpleAction: message construction, publish, blocking wait) and
:592-658 (waitForActivationResponse: promise first, activation-store poll as
the fallback when acks are lost, 202 on timeout), plus the package/binding
parameter resolution of Packages.scala (`mergePackageWithBinding`).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ..core.entity import (ActivationId, Identity, Parameters, WhiskAction,
                           WhiskActivation, WhiskPackage)
from ..core.entity.names import FullyQualifiedEntityName
from ..database import EntityStore, NoDocumentException
from ..messaging.message import ActivationMessage
from ..utils.transaction import TransactionId

MAX_BLOCKING_WAIT = 65.0  # ref controller maxWaitForBlockingActivation ~ 60 s

#: activation-store poll cadence while a blocking invoke waits: start fast
#: (acks are usually only *slightly* late), back off exponentially to the cap
#: (ref pollActivation schedules polls until the deadline,
#: PrimitiveActions.scala:592-658). The cap bounds read amplification on the
#: healthy-ack path: a 60 s blocking invoke issues ~15 polls total, not one
#: per second.
POLL_INTERVAL_MIN = 0.1
POLL_INTERVAL_MAX = 5.0


@dataclass
class InvokeOutcome:
    activation: Optional[WhiskActivation]
    activation_id: ActivationId
    accepted: bool  # True -> 202 (no result within the wait window)


async def resolve_action(entity_store: EntityStore, fqn: FullyQualifiedEntityName,
                         identity: Identity) -> Tuple[WhiskAction, Parameters]:
    """Resolve an action reference through packages/bindings, returning the
    action and the merged package-level parameters (provider < binding).
    Ref: WhiskPackage.mergePackageWithBinding + Actions resolution."""
    segments = fqn.path.segments
    if len(segments) <= 1:
        action = await entity_store.get_action(str(fqn))
        return action, Parameters()
    pkg_id = f"{segments[0]}/{segments[1]}"
    package = await entity_store.get_package(pkg_id)
    params = package.parameters
    provider_path = package.namespace.add(package.name)
    if package.binding is not None:
        provider = await entity_store.get_package(str(package.binding.fqn))
        params = provider.parameters.merge(package.parameters)
        provider_path = provider.namespace.add(provider.name)
    action = await entity_store.get_action(f"{provider_path}/{fqn.name}")
    return action, params


class ActionInvoker:
    def __init__(self, entity_store: EntityStore, activation_store,
                 load_balancer, controller_instance, logger=None):
        self.entity_store = entity_store
        self.activation_store = activation_store
        self.load_balancer = load_balancer
        self.controller = controller_instance
        self.logger = logger
        # batch-shaped publish (ISSUE 14): when the balancer runs the
        # batched SPI, concurrent invokes in one event-loop sweep hand
        # the balancer ONE publish_many batch instead of N publish
        # coroutines. None (knob off / CPU balancers without the SPI)
        # keeps the serial publish call bit-exact.
        from .loadbalancer.base import maybe_batch_publish
        self._publish_batcher = maybe_batch_publish(load_balancer)

    async def invoke(self, identity: Identity, action: WhiskAction,
                     package_params: Parameters, payload: Optional[Dict[str, Any]],
                     blocking: bool, transid: Optional[TransactionId] = None,
                     wait_override: Optional[float] = None,
                     cause: Optional[ActivationId] = None,
                     waterfall_ctx: Optional[list] = None) -> InvokeOutcome:
        """invokeSimpleAction (:152-206): parameters merge left-to-right as
        package < action < payload; the message carries only the payload-
        merged arguments. `waterfall_ctx` is the REST handler's stage
        vector (api_accept/entitle/throttle already stamped); direct
        callers (triggers, sequences) get a fresh vector anchored here so
        every activation carries a waterfall regardless of entry path."""
        transid = transid or TransactionId()
        from ..utils.tracing import GLOBAL_TRACER, trace_id_of
        from ..utils.waterfall import GLOBAL_WATERFALL
        span = GLOBAL_TRACER.start_span("controller_activation", transid)
        args = package_params.merge(action.parameters).merge(
            Parameters.from_arguments(payload or {}))
        msg = ActivationMessage(
            transid=transid,
            action=FullyQualifiedEntityName(action.namespace, action.name),
            revision=action.rev.rev,
            user=identity,
            activation_id=ActivationId.generate(),
            root_controller_index=self.controller,
            blocking=blocking,
            content=args.to_arguments(),
            cause=cause,
            trace_context=GLOBAL_TRACER.get_trace_context(transid),
        )
        # the activation id exists now: the stage vector becomes reachable
        # for every later layer (balancer, bus, invoker, pool, batcher)
        if waterfall_ctx is None:
            waterfall_ctx = GLOBAL_WATERFALL.open()
        GLOBAL_WATERFALL.adopt(msg.activation_id.asString, waterfall_ctx,
                               trace_id=trace_id_of(msg.trace_context))
        try:
            try:
                if self._publish_batcher is not None:
                    promise = await self._publish_batcher.publish(action, msg)
                else:
                    promise = await self.load_balancer.publish(action, msg)
            except (Exception, asyncio.CancelledError):
                # rejected before entering the pipeline (throttle, no
                # invokers) or the client went away mid-publish
                # (CancelledError is BaseException, a bare `except
                # Exception` would miss it): never completes, so never
                # finishes — drop the vector instead of leaking it until
                # eviction pushes out a live activation's
                GLOBAL_WATERFALL.discard(msg.activation_id.asString)
                raise
            if not blocking:
                return InvokeOutcome(None, msg.activation_id, accepted=True)
            wait = min(wait_override or MAX_BLOCKING_WAIT,
                       action.limits.timeout.seconds + 60.0)
            return await self._wait_for_response(identity, msg, promise, wait)
        finally:
            GLOBAL_TRACER.finish_span(
                transid, {"action": str(action.fully_qualified_name),
                          "activationId": msg.activation_id.asString,
                          "proc": f"controller{self.controller.name}"},
                span=span)

    async def _wait_for_response(self, identity: Identity, msg: ActivationMessage,
                                 promise: asyncio.Future, wait: float
                                 ) -> InvokeOutcome:
        """waitForActivationResponse (:592-658): the result promise raced
        against repeated activation-store polls until the wait window closes.
        Acks travel at-most-once, so a lost ack plus a slow activation write
        must still produce a 200 as long as the record lands in time — a
        single poll (the reference explicitly schedules polls to the
        deadline) would return 202 for that case."""
        deadline = time.monotonic() + wait
        interval = POLL_INTERVAL_MIN
        promise_live = True
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            if promise_live:
                try:
                    activation = await asyncio.wait_for(
                        asyncio.shield(promise), min(interval, remaining))
                    return InvokeOutcome(activation, msg.activation_id,
                                         accepted=False)
                except asyncio.TimeoutError:
                    pass
                except Exception:  # noqa: BLE001 — forced timeout etc: polls remain
                    promise_live = False
            else:
                await asyncio.sleep(min(interval, remaining))
            if time.monotonic() >= deadline:
                break  # the post-loop poll is the single final one
            try:
                activation = await self.activation_store.get(
                    str(identity.namespace.name), msg.activation_id)
                return InvokeOutcome(activation, msg.activation_id,
                                     accepted=False)
            except NoDocumentException:
                pass
            interval = min(interval * 2, POLL_INTERVAL_MAX)
        # window closed: one last poll, then hand back the activation id (202)
        try:
            activation = await self.activation_store.get(
                str(identity.namespace.name), msg.activation_id)
            return InvokeOutcome(activation, msg.activation_id, accepted=False)
        except NoDocumentException:
            return InvokeOutcome(None, msg.activation_id, accepted=True)

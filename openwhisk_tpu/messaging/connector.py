"""Messaging abstractions: producer, consumer, feed.

Rebuild of common/scala/.../core/connector/{MessagingProvider,MessageConsumer}
.scala. The `MessageFeed` reproduces the reference's double-buffered pull
pipeline (MessageConsumer.scala:93-247): it long-polls the consumer for up to
`maximum_handler_capacity` messages, commits the offset immediately after the
peek (at-most-once hand-off, :179-190), dispatches to the handler, and only
refills as the handler signals `processed()` — so a slow handler backpressures
the bus instead of ballooning memory.
"""
from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, List, Optional, Tuple

from ..utils.hostprof import GLOBAL_HOST_OBSERVATORY
from ..utils.transaction import TransactionId
from ..utils.waterfall import GLOBAL_WATERFALL, STAGE_PRODUCE

#: serde hop labels by message class (by NAME, so this module needs no
#: import of messaging/message.py): the controller->invoker dispatch and
#: the invoker->controller ack are the two hot hops; pings/events are the
#: background chatter that should NOT hide inside them
_SERDE_HOPS = {
    "ActivationMessage": "activation",
    "CompletionMessage": "completion_ack",
    "ResultMessage": "completion_ack",
    "CombinedCompletionAndResultMessage": "completion_ack",
    "PingMessage": "health_ping",
    "EventMessage": "event",
}


def hop_of(msg) -> str:
    return _SERDE_HOPS.get(type(msg).__name__, "other")


def encode_message(msg, hop: Optional[str] = None) -> bytes:
    """Serialize a bus message with host-observatory serde accounting
    (`openwhisk_host_serde_*_total{hop,direction="serialize"}`): the
    byte+wall-time cost of every encode on the caller's turn becomes a
    measured number instead of loop noise. Bytes pass through untouched;
    with host profiling disabled this is a plain `msg.serialize()`."""
    if isinstance(msg, (bytes, bytearray)):
        return msg
    obs = GLOBAL_HOST_OBSERVATORY
    if not obs.serde_active:
        return msg.serialize()
    t0 = time.perf_counter_ns()
    payload = msg.serialize()
    obs.serde_observe(hop if hop is not None else hop_of(msg), "serialize",
                      len(payload), time.perf_counter_ns() - t0)
    return payload


def decode_message(parse, raw, hop: str):
    """`parse(raw)` with the matching deserialize-side accounting (the
    invoker's ActivationMessage.parse, the balancer's ack parse)."""
    obs = GLOBAL_HOST_OBSERVATORY
    if not obs.serde_active:
        return parse(raw)
    t0 = time.perf_counter_ns()
    msg = parse(raw)
    obs.serde_observe(hop, "deserialize", len(raw),
                      time.perf_counter_ns() - t0)
    return msg


def encode_batch(family: str, msgs: list,
                 lazy_results: bool = False) -> Tuple[bytes, object]:
    """ONE serialize for a whole same-family micro-batch (the columnar
    batch wire, messaging/columnar.py). Returns (payload, batch_message);
    the host observatory books the batch's bytes + wall time under the
    SAME hop label as N serial encodes would have used — so the serde
    counters stay comparable across the knob, and the per-hop byte totals
    measure the dedup win directly. `lazy_results` selects the ISSUE 14
    lazy ack frame (opaque response-bytes column) for ack batches."""
    from .columnar import batch_hop_of, make_batch
    batch_msg = make_batch(family, msgs, lazy_results=lazy_results)
    obs = GLOBAL_HOST_OBSERVATORY
    if not obs.serde_active:
        return batch_msg.serialize(), batch_msg
    t0 = time.perf_counter_ns()
    payload = batch_msg.serialize()
    obs.serde_observe(batch_hop_of(family), "serialize", len(payload),
                      time.perf_counter_ns() - t0)
    return payload, batch_msg


def decode_batch(raw):
    """Decode one batch payload -> (kind, [messages]) with the matching
    deserialize-side accounting (one observe for the whole frame)."""
    from .columnar import batch_hop_of, parse_batch
    obs = GLOBAL_HOST_OBSERVATORY
    if not obs.serde_active:
        return parse_batch(raw)
    t0 = time.perf_counter_ns()
    kind, msgs = parse_batch(raw)
    obs.serde_observe(batch_hop_of(kind), "deserialize", len(raw),
                      time.perf_counter_ns() - t0)
    return kind, msgs


def stamp_produce(msg) -> None:
    """Waterfall `produce` edge, shared by every bus backend's producer:
    first-wins, so only the controller->invoker hand-off sets it (the
    completion ack also carries an activation_id but lands second, and
    cross-process peers stamp into an empty map — a no-op). Batch wire
    records carry `activation_ids` and stamp the whole batch at one
    shared timestamp."""
    aids = getattr(msg, "activation_ids", None)
    if aids is not None:
        GLOBAL_WATERFALL.stamp_many(aids, STAGE_PRODUCE)
        return
    aid = getattr(msg, "activation_id", None)
    if aid is not None:
        GLOBAL_WATERFALL.stamp(aid.asString, STAGE_PRODUCE)


class MessageProducer:
    async def send(self, topic: str, msg) -> None:
        """Send a Message (or raw bytes) to a topic."""
        raise NotImplementedError

    async def send_batch(self, topic: str, msgs) -> None:
        """Send a wave of messages to ONE topic. The CoalescingProducer
        overrides this task-free (one await for the whole wave); the
        default keeps serial semantics."""
        for m in msgs:
            await self.send(topic, m)

    async def send_many(self, items) -> None:
        """Ship a pre-serialized micro-batch `[(topic, payload_bytes, msg)]`
        (msg is the original Message for waterfall stamping, or None).
        Backends with a native batch op (one frame + one ack for N
        messages: the TCP bus `pubN`, Kafka's client-side batching)
        override this; the default degrades to sequential sends — serial
        semantics, so the CoalescingProducer is safe over any provider."""
        for topic, payload, msg in items:
            await self.send(topic, msg if msg is not None else payload)

    @property
    def sent_count(self) -> int:
        return 0

    async def close(self) -> None:
        pass


class MessageConsumer:
    """A consumer bound to one topic (ref MessageConsumer.scala:32-56)."""

    max_peek: int = 128

    async def peek(self, max_messages: int, timeout: float = 0.5
                   ) -> List[Tuple[str, int, int, bytes]]:
        """Long-poll up to max_messages; returns (topic, partition, offset, payload)."""
        raise NotImplementedError

    def commit(self) -> None:
        """Commit offsets of the last peek (at-most-once hand-off)."""
        raise NotImplementedError

    async def close(self) -> None:
        pass


class MessagingProvider:
    """SPI: build producers/consumers (ref MessagingProvider.scala:34-46)."""

    def get_producer(self) -> MessageProducer:
        raise NotImplementedError

    def get_consumer(self, topic: str, group_id: str, max_peek: int = 128,
                     from_latest: bool = False) -> MessageConsumer:
        """from_latest: start a NEW group at the stream head instead of the
        retained backlog — for ephemeral streams (health pings) where replay
        would resurrect stale state."""
        raise NotImplementedError

    def ensure_topic(self, topic: str, partitions: int = 1,
                     retention_bytes: Optional[int] = None) -> None:
        raise NotImplementedError


#: the invoker ping stream: smallest retention of any topic (ref gives the
#: health topic its tightest retention) and consumed from_latest
HEALTH_TOPIC = "health"
HEALTH_RETENTION_BYTES = 512 * 1024

Handler = Callable[[bytes], Awaitable[None]]


class MessageFeed:
    """Backpressured pull pipeline from a MessageConsumer to a handler.

    The handler receives raw payload bytes and MUST call `processed()` when
    it has freed its capacity (mirrors sending `MessageFeed.Processed` to the
    feed actor in the reference).
    """

    def __init__(self, description: str, consumer: MessageConsumer,
                 maximum_handler_capacity: int, handler: Handler,
                 logger=None, long_poll_timeout: float = 0.5,
                 auto_start: bool = False):
        self.description = description
        self.consumer = consumer
        self.capacity = maximum_handler_capacity
        self.handler = handler
        self.logger = logger
        self.long_poll_timeout = long_poll_timeout
        self._free = maximum_handler_capacity
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._running = False
        if auto_start:
            self.start()

    @property
    def free_capacity(self) -> int:
        return self._free

    def start(self) -> "MessageFeed":
        if not self._running:
            self._running = True
            self._task = asyncio.get_event_loop().create_task(
                self._pump(), name=f"feed-{self.description}")
        return self

    def processed(self) -> None:
        """Handler signals one unit of capacity is free again."""
        self._free += 1
        self._wake.set()

    def consume_extra(self, n: int) -> None:
        """A handler discovered its ONE payload carries `1 + n` logical
        messages (a columnar batch frame): book the extra capacity so the
        feed's backpressure still counts messages, not frames. Each
        logical message then releases via processed() as it completes.
        May drive _free negative under a large frame — the pump simply
        waits until enough releases land, which is the intended
        backpressure."""
        if n > 0:
            self._free -= n

    async def _pump(self) -> None:
        try:
            while self._running:
                if self._free <= 0:
                    self._wake.clear()
                    if self._free <= 0:
                        await self._wake.wait()
                    continue
                batch = await self.consumer.peek(self._free, self.long_poll_timeout)
                if not batch:
                    continue
                # commit BEFORE handling: at-most-once hand-off, exactly as
                # the reference (MessageConsumer.scala:179-190).
                self.consumer.commit()
                for _topic, _part, _offset, payload in batch:
                    self._free -= 1
                    try:
                        await self.handler(payload)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — feed must survive handler errors
                        self._free += 1
                        if self.logger:
                            self.logger.error(TransactionId.SYSTEM,
                                              f"feed {self.description} handler error: {e!r}")
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.consumer.close()

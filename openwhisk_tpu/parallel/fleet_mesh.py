"""Production fleet mesh: speculate-and-repair over a `('fleet',)` axis.

`sharded_state.py` proved the plumbing (the scan schedule with a per-step
all_gather election); this module promotes the invoker axis to a
PRODUCTION device mesh the balancer can run at 100k-1M invokers:

  * `make_fleet_mesh`        — the `('fleet',)` mesh (power-of-two shard
                               count so pow2 invoker pads always divide).
  * `make_fleet_repair_schedule`
                             — the speculate-and-repair kernel shard_map'd
                               over the mesh. Each round, every shard
                               speculates its LOCAL [B, n_local] probe
                               slice, one tiny all_gather per round elects
                               the global winners, and a psum-masked
                               exchange reads the winning cells' occupancy
                               (free_mb / conc permits) from their owner
                               shards — the "global-occupancy exchange".
                               The conflict rules are THE shared
                               `repair_commit_masks` (one copy with the
                               XLA and Pallas kernels, so the three
                               production kernels cannot drift); they run
                               replicated in B-space on every shard, so
                               pending/round control flow stays identical
                               across shards and to the single-device
                               kernel — bit-exact decisions, books, AND
                               round counts (the parity fuzz asserts it).
  * `make_fleet_release_vector`
                             — the vectorized release fold, owner-masked:
                               every shard runs the replicated group-by
                               math and applies only the rows whose
                               invoker it owns. Same-invoker rows always
                               land on one shard, so the sequential
                               semantics argument of `release_batch_vector`
                               carries over unchanged. No collectives.
  * `fleet_pair`             — the (schedule, release, resolved) selector
                               mirroring `_xla_pair`: scan | repair |
                               auto (per-bucket static hybrid), so the
                               placementKernel knob means the same thing
                               on a mesh as on one device.

Why the collectives are cheap: per repair round the wire traffic is ONE
[B, 2] all_gather (winner election) plus three [B] psums (occupancy
exchange) — a few KB riding ICI — while the [B, n_local] probe math stays
shard-local. Fleet capacity therefore scales with chips; the single
device's HBM bounds only n_local = n_pad / n_shards.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.placement import (PlacementState, RequestBatch, _mulmod,
                             flat_prims, release_batch_vector,
                             repair_commit_masks)
from .sharded_state import (make_mesh, make_sharded_release,
                            make_sharded_schedule, shard_map, shard_state)

#: the production mesh axis name (sharded_state's prototype used "inv")
FLEET_AXIS = "fleet"


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def make_fleet_mesh(n_shards: Optional[int] = None,
                    axis: str = FLEET_AXIS) -> Mesh:
    """Mesh over the `('fleet',)` axis. `n_shards=None` takes every
    visible device, rounded DOWN to a power of two: the balancer pads the
    invoker axis to powers of two, and `shard_state` needs the pad to
    divide evenly over the shards — a 6-device mesh would make every pow2
    pad indivisible. Falls back to the virtual CPU devices
    (--xla_force_host_platform_device_count) exactly like `make_mesh`."""
    avail = len(jax.devices())
    if not n_shards:  # None OR 0 both mean "all devices, pow2-floored"
        want = _pow2_floor(max(1, avail))
    elif _pow2_floor(n_shards) != n_shards:
        raise ValueError(f"fleet shard count must be a power of two "
                         f"(pow2 invoker pads must divide evenly), "
                         f"got {n_shards}")
    else:
        want = n_shards
    return make_mesh(want, axis=axis)


def mesh_axis(mesh: Mesh) -> str:
    return mesh.axis_names[0]


def mesh_shards(mesh: Mesh) -> int:
    return int(mesh.shape[mesh_axis(mesh)])


def mesh_topology(mesh: Optional[Mesh]) -> dict:
    """The topology record stamped into the journal / snapshot / admin
    planes (a replayer on a different topology must cold-start, not
    silently mis-shard)."""
    if mesh is None:
        return {"n_shards": 1, "axis": None}
    return {"n_shards": mesh_shards(mesh), "axis": mesh_axis(mesh),
            "platform": mesh.devices.flat[0].platform}


def make_fleet_repair_schedule(mesh: Mesh, axis: Optional[str] = None,
                               penalized: bool = False):
    """The speculate-and-repair schedule over the fleet mesh — bit-exact
    `schedule_batch_repair` semantics (state, chosen, forced, rounds) with
    the [B, N] probe sharded to [B, n_local] per device.

    Exactness argument, per round:
      * speculation — each shard computes its local slice of exactly the
        arrays the single-device kernel computes ([B, n_local] eligibility
        and ranks over the same loop-invariant geometry); the all_gather
        election picks the lexicographic (key, global index) minimum,
        which IS what a single-device argmin (first index achieving the
        min) returns over the concatenated axis. The forced-placement
        candidate is elected once, outside the loop, the same way.
      * occupancy exchange — `free_mb[sel]` and the conc permit at
        (sel, slot) live on exactly one owner shard; a psum of the
        owner-masked value (zeros elsewhere) reproduces the single-device
        gather bit-for-bit (integer psum, one non-zero term). `col_conc`
        (any consumable permit on my column) is a psum-of-any over the
        local slices.
      * conflict rules — `repair_commit_masks` consumes only replicated
        [B]-space vectors, so every shard derives identical safe/commit
        masks; `pending` evolves identically on all shards and identically
        to the single-device kernel, which is why round counts match and
        the while_loop stays coherent across the mesh.
      * commit — owner-masked scatter-adds (zero deltas elsewhere; a
        zero add at a clipped index is a no-op).

    `penalized=True` builds the counterfactual variant: the returned fn
    takes a third argument, a global int32[N] penalty vector (sharded like
    the books), folded into the loop-invariant geometry as one probe-ring
    lap per level — the same seam the XLA/Pallas kernels thread, so all
    three families penalize identically. The sentinel grows to 2^30
    because augmented ranks can exceed n_total + 2.
    """
    axis = axis or mesh_axis(mesh)
    n_shards = mesh_shards(mesh)

    def _sharded(state: PlacementState, batch: RequestBatch, penalty=None):
        b = batch.valid.shape[0]
        prims = flat_prims(b)
        n_local = state.free_mb.shape[0]
        n_total = n_local * n_shards
        a_slots = state.conc_free.shape[1]
        off = jax.lax.axis_index(axis).astype(jnp.int32) * n_local
        big = jnp.int32(n_total + 2) if penalty is None else jnp.int32(1 << 30)

        # loop-invariant LOCAL geometry: this shard's slice of the
        # [B, N] rank/partition math (ops.placement._probe_geometry)
        gidx = off + jnp.arange(n_local, dtype=jnp.int32)
        local = gidx[None, :] - batch.offset[:, None]        # [B, n_local]
        size_col = batch.size[:, None]
        in_part = (local >= 0) & (local < size_col)
        size_safe = jnp.maximum(size_col, 1)
        rank = _mulmod(local - batch.home[:, None], batch.step_inv[:, None],
                       size_safe)
        if penalty is not None:
            rank = rank + penalty[None, :] * size_safe
        usable = in_part & state.health[None, :]

        def _elect(key_loc):
            """Local [B, n_local] keys -> globally elected (min key,
            owning global index) per request: local argmin, then ONE
            [B, 2] all_gather and a lexicographic (key, index) min —
            the single-device first-index-of-min semantics."""
            a = jnp.argmin(key_loc, axis=1)
            my_key = jnp.take_along_axis(key_loc, a[:, None], 1)[:, 0]
            my_idx = off + a.astype(jnp.int32)
            allv = jax.lax.all_gather(
                jnp.stack([my_key, my_idx], axis=-1), axis)  # [S, B, 2]
            kmin = jnp.min(allv[:, :, 0], axis=0)
            idx = jnp.min(jnp.where(allv[:, :, 0] == kmin[None, :],
                                    allv[:, :, 1], big), axis=0)
            return kmin, idx

        # the forced path is loop-invariant (capacity-blind, health fixed
        # inside a batch): elect the global forced candidate once
        fkey = jnp.where(usable, jnp.mod(local - batch.rand[:, None],
                                         size_safe), big)
        fmin, fbest = _elect(fkey)
        have_usable = fmin < big
        simple = batch.max_conc <= 1

        def cond(carry):
            _, _, pending, _, _, rounds = carry
            return jnp.any(pending) & (rounds <= b)

        def body(carry):
            free, conc, pending, chosen, forced_acc, rounds = carry
            conc_bn = conc[:, batch.conc_slot].T             # [B, n_local]
            has_conc = conc_bn > 0
            eligible = usable & (has_conc
                                 | (free[None, :] >= batch.need_mb[:, None]))
            kmin, choice = _elect(jnp.where(eligible, rank, big))
            found = kmin < big
            sel = jnp.where(found, choice, fbest)
            placed = batch.valid & (found | have_usable)
            forced = batch.valid & ~found & have_usable

            # global-occupancy exchange: the winning cell's books live on
            # one owner shard — psum the owner-masked reads
            lsel = jnp.clip(sel - off, 0, n_local - 1)
            mine = (sel >= off) & (sel < off + n_local)
            conc_at_sel = jax.lax.psum(
                jnp.where(mine,
                          jnp.take_along_axis(conc_bn, lsel[:, None],
                                              1)[:, 0], 0), axis)
            free_at_sel = jax.lax.psum(jnp.where(mine, free[lsel], 0), axis)
            use_conc = placed & (conc_at_sel > 0)
            take_mem = placed & ~use_conc
            col_conc = jax.lax.psum(
                jnp.any(usable & has_conc, axis=1).astype(jnp.int32),
                axis) > 0

            # THE shared conflict rules (ops.placement.repair_commit_masks)
            # over replicated [B] vectors: identical on every shard
            safe, commit = repair_commit_masks(
                prims, pending=pending, placed=placed, forced=forced,
                sel=sel, take_mem=take_mem, use_conc=use_conc,
                simple=simple, need_mb=batch.need_mb,
                conc_slot=batch.conc_slot, free_at_sel=free_at_sel,
                col_conc=col_conc, n=n_total, a_slots=a_slots)

            # owner-masked commit (zero adds elsewhere are no-ops)
            dmem = jnp.where(commit & take_mem & mine, batch.need_mb, 0)
            free = free.at[lsel].add(-dmem.astype(jnp.int32))
            conc_delta = jnp.where(
                commit & use_conc & mine, -1,
                jnp.where(commit & take_mem & ~simple & mine,
                          batch.max_conc - 1, 0))
            conc = conc.at[lsel, batch.conc_slot].add(
                conc_delta.astype(jnp.int32))
            chosen = jnp.where(safe, jnp.where(placed, sel, jnp.int32(-1)),
                               chosen)
            forced_acc = forced_acc | (safe & forced)
            return (free, conc, pending & ~safe, chosen, forced_acc,
                    rounds + 1)

        free, conc, _, chosen, forced, rounds = jax.lax.while_loop(
            cond, body,
            (state.free_mb, state.conc_free, batch.valid,
             jnp.full((b,), -1, jnp.int32), jnp.zeros((b,), bool),
             jnp.int32(0)))
        return PlacementState(free, conc, state.health), chosen, forced, \
            rounds

    state_spec = PlacementState(P(axis), P(axis, None), P(axis))
    batch_spec = RequestBatch(*([P()] * 9))
    if penalized:
        fn = shard_map(_sharded, mesh=mesh,
                       in_specs=(state_spec, batch_spec, P(axis)),
                       out_specs=(state_spec, P(), P(), P()),
                       check_vma=False)
    else:
        fn = shard_map(lambda s, b: _sharded(s, b), mesh=mesh,
                       in_specs=(state_spec, batch_spec),
                       out_specs=(state_spec, P(), P(), P()),
                       check_vma=False)
    return jax.jit(fn)


def make_fleet_release_vector(mesh: Mesh, axis: Optional[str] = None):
    """Owner-masked `release_batch_vector` over the mesh. Each shard runs
    the full (replicated) group-by-(invoker, slot) math with rows it does
    not own masked invalid; a group's rows all share one invoker, hence
    one shard, so within-group batch order — the only order that matters
    (see release_batch_vector's exactness argument) — is preserved
    locally. The heterogeneous-conflation residue loop runs per shard
    over its own rows only (no collectives in the body, so divergent
    trip counts across shards are fine)."""
    axis = axis or mesh_axis(mesh)

    def _sharded(state: PlacementState, inv, slot, need_mb, max_conc, valid):
        n_local = state.free_mb.shape[0]
        off = jax.lax.axis_index(axis).astype(jnp.int32) * n_local
        mine = valid & (inv >= off) & (inv < off + n_local)
        linv = jnp.clip(inv - off, 0, n_local - 1)
        return release_batch_vector(state, linv, slot, need_mb, max_conc,
                                    mine)

    state_spec = PlacementState(P(axis), P(axis, None), P(axis))
    fn = shard_map(_sharded, mesh=mesh,
                   in_specs=(state_spec, P(), P(), P(), P(), P()),
                   out_specs=state_spec, check_vma=False)
    return jax.jit(fn)


def fleet_pair(mesh: Mesh, placement_kernel: str,
               repair_min_batch: int = 32, axis: Optional[str] = None):
    """(schedule_fn, release_fn, resolved_kernel) for the fleet mesh,
    honoring the placement-kernel knob exactly like `_xla_pair`: "repair"
    pins the sharded speculate-and-repair pair, "scan" keeps the
    prototype scan pair (sharded_state — the bit-exact legacy mesh path),
    "auto" resolves PER BUCKET at trace time (scan below
    `repair_min_batch`, repair at and above it — batch/release widths are
    static per jit signature). All pairs are bit-exact with each other
    and with the single-device kernels, so the knob moves only cost."""
    axis = axis or mesh_axis(mesh)
    sched_scan = make_sharded_schedule(mesh, axis=axis)
    rel_scan = make_sharded_release(mesh, axis=axis)
    if placement_kernel == "scan":
        return sched_scan, rel_scan, "scan"
    sched_repair = make_fleet_repair_schedule(mesh, axis=axis)
    rel_repair = make_fleet_release_vector(mesh, axis=axis)
    if placement_kernel == "repair":
        return sched_repair, rel_repair, "repair"
    threshold = repair_min_batch

    def auto_schedule(state, batch):
        # both shapes are static at trace time
        if batch.valid.shape[0] >= threshold:
            return sched_repair(state, batch)
        return sched_scan(state, batch)

    def auto_release(state, inv, slot, need_mb, max_conc, valid):
        if inv.shape[0] >= threshold:
            return rel_repair(state, inv, slot, need_mb, max_conc, valid)
        return rel_scan(state, inv, slot, need_mb, max_conc, valid)

    auto_schedule._placement_hybrid = True
    auto_release._placement_hybrid = True
    return auto_schedule, auto_release, "repair"


__all__ = ["FLEET_AXIS", "make_fleet_mesh", "mesh_axis", "mesh_shards",
           "mesh_topology", "make_fleet_repair_schedule",
           "make_fleet_release_vector", "fleet_pair", "shard_state",
           "make_mesh"]

"""Container plane tests: stub-driven pool/proxy behavior (mirrors reference
ContainerPoolTests/ContainerProxyTests with stub containers + factories) and
one real subprocess (action proxy) end-to-end run."""
import asyncio
import time

import pytest

from openwhisk_tpu.core.entity import (ActivationId, CodeExec,
                                       ControllerInstanceId, EntityName,
                                       EntityPath, ExecutableWhiskAction,
                                       FullyQualifiedEntityName, Identity,
                                       MB, ActionLimits, MemoryLimit,
                                       ConcurrencyLimit, TimeLimit)
from openwhisk_tpu.core.entity.ids import DocRevision
from openwhisk_tpu.containerpool import (Container, ContainerPool,
                                         ContainerPoolConfig, ContainerProxy,
                                         ProcessContainerFactory, Run)
from openwhisk_tpu.containerpool.logstore import ContainerLogStore
from openwhisk_tpu.messaging.message import ActivationMessage
from openwhisk_tpu.utils.transaction import TransactionId


# ---------------------------------------------------------------------------
# stubs (reference pattern: tests/.../containerpool/test stub factories)
# ---------------------------------------------------------------------------

class StubContainer(Container):
    def __init__(self, cid="stub", behavior=None):
        super().__init__(cid, ("127.0.0.1", 0))
        self.behavior = behavior or {}
        self.initialized = False
        self.runs = []
        self.suspended = False
        self.destroyed = False

    async def initialize(self, init_payload, timeout=60.0):
        if self.behavior.get("init_fail"):
            from openwhisk_tpu.containerpool import InitializationError
            raise InitializationError("Initialization has failed: boom")
        self.initialized = True
        await asyncio.sleep(self.behavior.get("init_delay", 0))
        return 7

    async def run(self, args, environment, timeout=60.0):
        from openwhisk_tpu.containerpool.container import RunResult
        self.runs.append(args)
        await asyncio.sleep(self.behavior.get("run_delay", 0))
        start = time.time()
        if self.behavior.get("run_timeout"):
            return RunResult(start, time.time(), {"error": "timeout"}, ok=False,
                             timed_out=True)
        if self.behavior.get("run_error"):
            return RunResult(start, time.time(),
                             {"error": "An error has occurred while running the action."},
                             ok=False)
        return RunResult(start, time.time(), {"echo": args}, ok=True)

    async def suspend(self):
        self.suspended = True

    async def resume(self):
        self.suspended = False

    async def destroy(self):
        await super().destroy()
        self.destroyed = True

    async def logs(self, limit_bytes=10 * 1024 * 1024, wait_for_sentinel=True):
        return ["stdout: hello-log"]


class StubFactory:
    def __init__(self, behavior=None):
        self.behavior = behavior or {}
        self.created = []

    async def create_container(self, transid, name, image, memory, cpu_shares=0,
                               action=None):
        if self.behavior.get("create_fail"):
            raise RuntimeError("no resources")
        c = StubContainer(cid=f"stub-{len(self.created)}", behavior=self.behavior)
        self.created.append(c)
        return c


class AckRecorder:
    def __init__(self):
        self.acks = []
        self.stored = []
        self.event = asyncio.Event()

    async def active_ack(self, transid, activation, blocking, controller, user, kind):
        self.acks.append((kind, activation))
        if kind in ("completion", "combined"):
            self.event.set()

    async def store_activation(self, transid, activation, user):
        self.stored.append(activation)


def make_action(name="hello", memory=256, concurrency=1, kind="python:3"):
    old_max = ConcurrencyLimit.MAX
    ConcurrencyLimit.MAX = max(concurrency, 1)
    try:
        limits = ActionLimits(TimeLimit(10_000), MemoryLimit(MB(memory)), None,
                              ConcurrencyLimit(concurrency))
    finally:
        ConcurrencyLimit.MAX = old_max
    a = ExecutableWhiskAction(EntityPath("guest"), EntityName(name),
                              CodeExec(kind=kind, code="def main(a): return a"),
                              limits=limits)
    a.rev = DocRevision("1-test")
    return a


def make_msg(action, blocking=True, content=None):
    ident = Identity.generate("guest")
    return ActivationMessage(
        TransactionId(), action.fully_qualified_name, action.rev.rev, ident,
        ActivationId.generate(), ControllerInstanceId("0"), blocking,
        content or {"name": "world"})


def make_proxy(factory, recorder, config=None):
    config = config or ContainerPoolConfig(pause_grace=0.02, idle_container_timeout=5)
    logstore = ContainerLogStore()
    return ContainerProxy(factory, recorder.active_ack, recorder.store_activation,
                          logstore.collect_logs, instance=0, pool_config=config)


def make_pool(factory, recorder, user_memory_mb=1024, prewarm=None):
    config = ContainerPoolConfig(user_memory=MB(user_memory_mb), pause_grace=0.02,
                                 idle_container_timeout=5)
    return ContainerPool(lambda: make_proxy(factory, recorder, config), config,
                         prewarm_config=prewarm or [])


# ---------------------------------------------------------------------------
# ContainerProxy lifecycle
# ---------------------------------------------------------------------------

class TestContainerProxy:
    def test_cold_start_run_ack_store(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            proxy = make_proxy(factory, rec)
            action, msg = make_action(), None
            msg = make_msg(action)
            await proxy.run(action, msg)
            return factory, rec, proxy

        factory, rec, proxy = asyncio.run(go())
        kinds = [k for k, _ in rec.acks]
        assert kinds == ["result", "completion"]  # blocking: fast result, then completion
        final = rec.acks[1][1]
        assert final.response.is_success
        assert final.response.result == {"echo": {"name": "world"}}
        assert final.logs == ["stdout: hello-log"]
        assert len(rec.stored) == 1
        assert rec.stored[0].annotations.get("initTime") == 7
        assert rec.stored[0].annotations.get("kind") == "python:3"
        assert proxy.data.action_id is not None

    def test_nonblocking_sends_combined(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action, blocking=False))
            return rec

        rec = asyncio.run(go())
        assert [k for k, _ in rec.acks] == ["combined"]

    def test_warm_run_skips_init(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action))
            await proxy.run(action, make_msg(action))
            return factory, rec

        factory, rec = asyncio.run(go())
        assert len(factory.created) == 1           # one container, two runs
        assert len(factory.created[0].runs) == 2
        second = rec.stored[1]
        assert second.annotations.get("initTime") is None

    def test_init_failure_is_developer_error_and_destroys(self):
        async def go():
            factory = StubFactory({"init_fail": True})
            rec = AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action))
            return factory, rec, proxy

        factory, rec, proxy = asyncio.run(go())
        assert rec.stored[0].response.status == "action developer error"
        assert factory.created[0].destroyed
        assert proxy._destroyed

    def test_create_failure_is_whisk_error(self):
        async def go():
            factory = StubFactory({"create_fail": True})
            rec = AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action))
            return rec

        rec = asyncio.run(go())
        assert rec.stored[0].response.is_whisk_error

    def test_timeout_destroys_container(self):
        async def go():
            factory = StubFactory({"run_timeout": True})
            rec = AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action))
            return factory, rec

        factory, rec = asyncio.run(go())
        assert rec.stored[0].response.status == "action developer error"
        assert rec.stored[0].annotations.get("timeout") is True
        assert factory.created[0].destroyed

    def test_action_error_keeps_container_warm(self):
        async def go():
            factory = StubFactory({"run_error": True})
            rec = AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action))
            return factory, rec, proxy

        factory, rec, proxy = asyncio.run(go())
        assert rec.stored[0].response.is_app_error
        assert not factory.created[0].destroyed
        assert not proxy._destroyed

    def test_pause_after_grace_and_resume_on_next_run(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action))
            await asyncio.sleep(0.08)  # > pause_grace
            assert factory.created[0].suspended
            await proxy.run(action, make_msg(action))
            return factory

        factory = asyncio.run(go())
        assert not factory.created[0].suspended

    def test_prewarmed_container_inits_on_first_job(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            proxy = make_proxy(factory, rec)
            await proxy.prestart("python:3", "action-python-v3", 256)
            assert proxy.data.kind == "python:3"
            action = make_action()
            await proxy.run(action, make_msg(action))
            return factory, rec

        factory, rec = asyncio.run(go())
        assert len(factory.created) == 1
        assert factory.created[0].initialized
        assert rec.stored[0].response.is_success


# ---------------------------------------------------------------------------
# ContainerPool scheduling
# ---------------------------------------------------------------------------

class TestContainerPool:
    def test_warm_reuse(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            pool = make_pool(factory, rec)
            action = make_action()
            pool.run(Run(action, make_msg(action)))
            await asyncio.sleep(0.05)
            pool.run(Run(action, make_msg(action)))
            await asyncio.sleep(0.05)
            return factory

        factory = asyncio.run(go())
        assert len(factory.created) == 1

    def test_different_actions_get_different_containers(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            pool = make_pool(factory, rec)
            a1, a2 = make_action("one"), make_action("two")
            pool.run(Run(a1, make_msg(a1)))
            pool.run(Run(a2, make_msg(a2)))
            await asyncio.sleep(0.1)
            return factory

        factory = asyncio.run(go())
        assert len(factory.created) == 2

    def test_memory_pressure_buffers_jobs(self):
        async def go():
            factory = StubFactory({"run_delay": 0.2})
            rec = AckRecorder()
            pool = make_pool(factory, rec, user_memory_mb=256)  # one 256MB slot
            a1, a2 = make_action("one"), make_action("two")
            pool.run(Run(a1, make_msg(a1)))
            await asyncio.sleep(0.05)
            pool.run(Run(a2, make_msg(a2)))
            await asyncio.sleep(0.02)
            buffered = len(pool.run_buffer)
            await asyncio.sleep(0.6)
            return factory, buffered, rec

        factory, buffered, rec = asyncio.run(go())
        assert buffered == 1         # second job waited
        assert len(rec.stored) == 2  # ...but ran eventually (eviction freed room)

    def test_eviction_frees_idle_containers(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            pool = make_pool(factory, rec, user_memory_mb=256)
            a1 = make_action("one")
            pool.run(Run(a1, make_msg(a1)))
            await asyncio.sleep(0.05)  # a1 done, container idle
            a2 = make_action("two")
            pool.run(Run(a2, make_msg(a2)))
            await asyncio.sleep(0.1)
            return factory, rec

        factory, rec = asyncio.run(go())
        assert len(rec.stored) == 2
        assert factory.created[0].destroyed  # evicted to make room

    def test_prewarm_pool_used_and_backfilled(self):
        async def go():
            factory, rec = StubFactory(), AckRecorder()
            pool = make_pool(factory, rec, user_memory_mb=1024,
                             prewarm=[("python:3", "action-python-v3", 256, 1)])
            await pool.start()
            assert len(pool.prewarmed) == 1
            created_before = len(factory.created)
            action = make_action()
            pool.run(Run(action, make_msg(action)))
            await asyncio.sleep(0.1)
            return factory, rec, pool, created_before

        factory, rec, pool, created_before = asyncio.run(go())
        assert created_before == 1
        assert rec.stored[0].response.is_success
        # stem cell consumed and backfilled
        assert len(pool.prewarmed) == 1
        assert len(factory.created) == 2

    def test_intra_container_concurrency(self):
        async def go():
            factory = StubFactory({"run_delay": 0.1})
            rec = AckRecorder()
            pool = make_pool(factory, rec, user_memory_mb=256)
            action = make_action(concurrency=4)
            for _ in range(4):
                pool.run(Run(action, make_msg(action)))
                await asyncio.sleep(0.02)
            await asyncio.sleep(0.3)
            return factory, rec

        factory, rec = asyncio.run(go())
        assert len(factory.created) == 1  # all four shared one container
        assert len(rec.stored) == 4


# ---------------------------------------------------------------------------
# real subprocess container (the in-repo action proxy)
# ---------------------------------------------------------------------------

class TestProcessContainer:
    def test_end_to_end_python_action(self):
        async def go():
            factory = ProcessContainerFactory()
            rec = AckRecorder()
            config = ContainerPoolConfig(pause_grace=10, idle_container_timeout=60)
            logstore = ContainerLogStore()
            proxy = ContainerProxy(factory, rec.active_ack, rec.store_activation,
                                   logstore.collect_logs, instance=0,
                                   pool_config=config)
            action = ExecutableWhiskAction(
                EntityPath("guest"), EntityName("pyhello"),
                CodeExec(kind="python:3",
                         code="def main(args):\n"
                              "    print('log line from action')\n"
                              "    return {'greeting': 'Hello ' + args.get('name', '?')}\n"))
            action.rev = DocRevision("1-e2e")
            msg = make_msg(action, content={"name": "TPU"})
            try:
                await proxy.run(action, msg)
            finally:
                await factory.cleanup()
            return rec

        rec = asyncio.run(go())
        final = rec.stored[0]
        assert final.response.is_success, final.response.to_json()
        assert final.response.result == {"greeting": "Hello TPU"}
        assert any("log line from action" in l for l in final.logs)

    def test_action_exception_is_application_error(self):
        async def go():
            factory = ProcessContainerFactory()
            rec = AckRecorder()
            config = ContainerPoolConfig(pause_grace=10, idle_container_timeout=60)
            logstore = ContainerLogStore()
            proxy = ContainerProxy(factory, rec.active_ack, rec.store_activation,
                                   logstore.collect_logs, instance=0,
                                   pool_config=config)
            action = ExecutableWhiskAction(
                EntityPath("guest"), EntityName("bad"),
                CodeExec(kind="python:3", code="def main(args):\n    raise ValueError('nope')\n"))
            action.rev = DocRevision("1-e2e")
            try:
                await proxy.run(action, make_msg(action))
            finally:
                await factory.cleanup()
            return rec

        rec = asyncio.run(go())
        final = rec.stored[0]
        assert final.response.is_app_error
        assert any("ValueError" in l for l in final.logs)


class TestConnectionFailureHandling:
    def test_run_connection_failure_is_whisk_error_and_destroys(self):
        """A transport-level /run failure must produce a whisk error and
        destroy the container — a wedged sandbox must not keep serving
        failures to every subsequent warm invoke."""
        class DisconnectingContainer(StubContainer):
            async def run(self, args, environment, timeout=60.0):
                from openwhisk_tpu.containerpool.container import RunResult
                t = time.time()
                return RunResult(t, t, {"error": "connection to container "
                                                 "stub failed: boom"},
                                 ok=False, connection_failed=True)

        class F:
            def __init__(self):
                self.created = []

            async def create_container(self, transid, name, image, memory,
                                       cpu_shares=0, action=None):
                c = DisconnectingContainer(cid=f"dc-{len(self.created)}")
                self.created.append(c)
                return c

        async def go():
            factory = F()
            rec = AckRecorder()
            proxy = make_proxy(factory, rec)
            action = make_action()
            await proxy.run(action, make_msg(action))
            await asyncio.wait_for(rec.event.wait(), 5)
            return rec.stored[0], factory.created[0]

        activation, container = asyncio.run(go())
        assert activation.response.is_whisk_error
        assert container.destroyed, \
            "state-unknown container must be destroyed, not reused"

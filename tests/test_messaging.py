"""Messaging tests (mirrors reference MessageFeedTests + TestConnector use)."""
import asyncio
import json

import pytest

from openwhisk_tpu.core.entity import (ActivationId, ControllerInstanceId,
                                       EntityName, EntityPath,
                                       FullyQualifiedEntityName, Identity,
                                       InvokerInstanceId, Subject,
                                       ActivationResponse, WhiskActivation)
from openwhisk_tpu.messaging import (ActivationMessage,
                                     CombinedCompletionAndResultMessage,
                                     CompletionMessage, MemoryMessagingProvider,
                                     MessageFeed, PingMessage, ResultMessage,
                                     parse_ack)
from openwhisk_tpu.utils.transaction import TransactionId


def _identity():
    return Identity.generate("guest")


def _activation_message(blocking=True):
    return ActivationMessage(
        TransactionId(), FullyQualifiedEntityName.parse("guest/hello"),
        "1-abc", _identity(), ActivationId.generate(),
        ControllerInstanceId("0"), blocking, {"payload": "x"})


class TestMessageSerde:
    def test_activation_message_roundtrip(self):
        m = _activation_message()
        r = ActivationMessage.parse(m.serialize())
        assert r.activation_id == m.activation_id
        assert str(r.action) == "guest/hello"
        assert r.blocking
        assert r.content == {"payload": "x"}

    def test_ack_roundtrips(self):
        act = WhiskActivation(EntityPath("guest"), EntityName("hello"),
                              Subject("guest-user"), ActivationId.generate(),
                              1.0, 2.0, ActivationResponse.success({"a": 1}))
        inv = InvokerInstanceId(3)
        for msg in (CompletionMessage(TransactionId(), act.activation_id, False, inv),
                    ResultMessage(TransactionId(), act),
                    CombinedCompletionAndResultMessage(TransactionId(), act, inv)):
            r = parse_ack(msg.serialize())
            assert type(r) is type(msg)
            assert r.activation_id == act.activation_id
        c = parse_ack(CombinedCompletionAndResultMessage(TransactionId(), act, inv).serialize())
        assert c.is_slot_free and c.invoker.instance == 3
        assert c.activation.response.result == {"a": 1}
        res = parse_ack(ResultMessage(TransactionId(), act).serialize())
        assert not res.is_slot_free

    def test_ping(self):
        p = PingMessage.parse(PingMessage(InvokerInstanceId(7)).serialize())
        assert p.instance.instance == 7


class TestMemoryBus:
    def test_produce_consume(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("t", "g")
            await prod.send("t", b"m1")
            await prod.send("t", b"m2")
            batch = await cons.peek(10)
            cons.commit()
            return [p for (_, _, _, p) in batch]

        assert asyncio.run(run()) == [b"m1", b"m2"]

    def test_messages_before_subscribe_are_retained(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            await prod.send("t", b"early")
            cons = prov.get_consumer("t", "g")
            batch = await cons.peek(10)
            return [p for (_, _, _, p) in batch]

        assert asyncio.run(run()) == [b"early"]

    def test_competing_consumers_split_messages(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            c1 = prov.get_consumer("t", "g")
            c2 = prov.get_consumer("t", "g")
            for i in range(4):
                await prod.send("t", f"m{i}".encode())
            b1 = await c1.peek(2)
            b2 = await c2.peek(2)
            return len(b1) + len(b2)

        assert asyncio.run(run()) == 4


class TestMessageFeed:
    def test_backpressure_and_delivery(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("activations", "invoker0")
            received = []
            feeds = {}

            async def handler(payload: bytes):
                received.append(payload)
                # simulate async completion later
                async def done():
                    await asyncio.sleep(0.01)
                    feeds["f"].processed()
                asyncio.get_event_loop().create_task(done())

            feed = MessageFeed("test", cons, maximum_handler_capacity=2,
                               handler=handler, long_poll_timeout=0.05)
            feeds["f"] = feed
            feed.start()
            for i in range(6):
                await prod.send("activations", f"m{i}".encode())
            await asyncio.sleep(0.3)
            await feed.stop()
            return received

        received = asyncio.run(run())
        assert received == [f"m{i}".encode() for i in range(6)]

    def test_capacity_limits_inflight(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("t", "g")
            inflight = {"now": 0, "max": 0}
            feeds = {}

            async def handler(payload: bytes):
                inflight["now"] += 1
                inflight["max"] = max(inflight["max"], inflight["now"])

                async def done():
                    await asyncio.sleep(0.02)
                    inflight["now"] -= 1
                    feeds["f"].processed()
                asyncio.get_event_loop().create_task(done())

            feed = MessageFeed("test", cons, maximum_handler_capacity=3,
                               handler=handler, long_poll_timeout=0.05)
            feeds["f"] = feed
            feed.start()
            for i in range(12):
                await prod.send("t", f"m{i}".encode())
            await asyncio.sleep(0.4)
            await feed.stop()
            return inflight["max"]

        assert asyncio.run(run()) <= 3

    def test_handler_error_does_not_kill_feed(self):
        async def run():
            prov = MemoryMessagingProvider()
            prod = prov.get_producer()
            cons = prov.get_consumer("t", "g")
            good = []
            feeds = {}

            async def handler(payload: bytes):
                if payload == b"bad":
                    raise RuntimeError("boom")
                good.append(payload)
                feeds["f"].processed()

            feed = MessageFeed("test", cons, maximum_handler_capacity=2,
                               handler=handler, long_poll_timeout=0.05)
            feeds["f"] = feed
            feed.start()
            await prod.send("t", b"bad")
            await prod.send("t", b"ok")
            await asyncio.sleep(0.2)
            await feed.stop()
            return good

        assert asyncio.run(run()) == [b"ok"]


class TestRetentionAndFromLatest:
    def test_orphan_group_queue_is_bounded(self):
        """A group nobody drains (retired controller) must not grow without
        bound: retention drops oldest, like Kafka."""
        async def go():
            from openwhisk_tpu.messaging.memory import MemoryMessagingProvider
            provider = MemoryMessagingProvider()
            provider.ensure_topic("health", retention_bytes=128 * 100)  # cap 100
            orphan = provider.get_consumer("health", "health-controller9")
            producer = provider.get_producer()
            for i in range(500):
                await producer.send("health", f"ping{i}".encode())
            q = provider.bus.topic("health").groups["health-controller9"]
            assert len(q) == 100
            # oldest dropped, newest retained
            batch = await orphan.peek(1000, timeout=0.1)
            return [p.decode() for (_t, _p, _o, p) in batch]

        msgs = asyncio.run(go())
        assert msgs[0] == "ping400" and msgs[-1] == "ping499"

    def test_from_latest_group_skips_backlog(self):
        """A new from_latest group (per-controller health view) starts at the
        stream head: no replay of retained pings."""
        async def go():
            from openwhisk_tpu.messaging.memory import MemoryMessagingProvider
            provider = MemoryMessagingProvider()
            producer = provider.get_producer()
            for i in range(50):
                await producer.send("health", f"stale{i}".encode())
            fresh = provider.get_consumer("health", "health-controller1",
                                          from_latest=True)
            await producer.send("health", b"live")
            batch = await fresh.peek(100, timeout=0.2)
            return [p for (_t, _p, _o, p) in batch]

        assert asyncio.run(go()) == [b"live"]

    def test_from_latest_over_tcp_bus(self):
        async def go():
            from openwhisk_tpu.messaging.tcp import (TcpBusServer,
                                                     TcpMessagingProvider)
            server = TcpBusServer("127.0.0.1", 0)
            await server.start()
            port = server._server.sockets[0].getsockname()[1]
            provider = TcpMessagingProvider("127.0.0.1", port)
            producer = provider.get_producer()
            for i in range(20):
                await producer.send("health", f"stale{i}".encode())
            fresh = provider.get_consumer("health", "health-c1",
                                          from_latest=True)
            # first peek creates the latest-positioned group server-side
            first = await fresh.peek(100, timeout=0.2)
            await producer.send("health", b"live")
            second = await fresh.peek(100, timeout=1.0)
            await fresh.close()
            await producer.close()
            await server.stop()
            return first, [p for (_t, _pp, _o, p) in second]

        first, second = asyncio.run(go())
        assert first == []
        assert second == [b"live"]

    def test_from_latest_reattach_resumes_backlog(self):
        """from_latest applies only to a NEW group (Kafka offset-reset
        semantics): re-attaching — e.g. after a TCP blip recreates the
        server-side consumer — must resume the buffered backlog, not drop
        it."""
        async def go():
            from openwhisk_tpu.messaging.memory import MemoryMessagingProvider
            provider = MemoryMessagingProvider()
            producer = provider.get_producer()
            c1 = provider.get_consumer("health", "health-c0", from_latest=True)
            await producer.send("health", b"p1")
            await producer.send("health", b"p2")
            # reconnect: same group, new consumer object
            c2 = provider.get_consumer("health", "health-c0", from_latest=True)
            batch = await c2.peek(10, timeout=0.2)
            return [p for (_t, _pp, _o, p) in batch]

        assert asyncio.run(go()) == [b"p1", b"p2"]


class TestProviderForBus:
    def test_default_is_tcp(self):
        from openwhisk_tpu.messaging import provider_for_bus
        from openwhisk_tpu.messaging.tcp import TcpMessagingProvider
        p = provider_for_bus("127.0.0.1:4555")
        assert isinstance(p, TcpMessagingProvider)

    def test_spi_binding_overrides(self, monkeypatch):
        """CONFIG_whisk_spi_MessagingProvider selects the backend for the
        service mains (the Kafka runbook's mechanism); the implementation
        receives the --bus address as its bootstrap argument."""
        from openwhisk_tpu.messaging import provider_for_bus

        monkeypatch.setenv(
            "CONFIG_whisk_spi_MessagingProvider",
            "openwhisk_tpu.messaging.memory:MemoryMessagingProvider")
        from openwhisk_tpu import spi
        spi.reset()
        try:
            from openwhisk_tpu.messaging import MemoryMessagingProvider
            p = provider_for_bus("broker:9092")
            # Memory takes no bootstrap: signature inspection skips the addr
            assert isinstance(p, MemoryMessagingProvider)
        finally:
            spi.reset()

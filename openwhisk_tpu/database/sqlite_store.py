"""Durable ArtifactStore on sqlite3 — the single-node CouchDB-equivalent.

The reference persists entities in CouchDB via an HTTP client
(CouchDbRestStore.scala, 564 LoC); the portable durability story here is
sqlite in WAL mode with the same revisioned-document semantics
(rev "N-<hash>"; conflict on mismatched rev) and the same views (query by
collection/namespace/updated). Blocking sqlite calls run in a thread executor
so the asyncio control plane never stalls on fsync.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import sqlite3
import threading
from typing import Any, Dict, List, Optional, Tuple

from .store import (ArtifactStore, DocumentConflict, NoDocumentException,
                    match_query, sort_key)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS documents (
  id TEXT PRIMARY KEY,
  rev TEXT NOT NULL,
  collection TEXT NOT NULL,
  namespace TEXT NOT NULL,
  name TEXT,
  updated REAL NOT NULL,
  body TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_docs_view ON documents (collection, namespace, updated);
CREATE TABLE IF NOT EXISTS attachments (
  doc_id TEXT NOT NULL,
  name TEXT NOT NULL,
  content_type TEXT NOT NULL,
  data BLOB NOT NULL,
  PRIMARY KEY (doc_id, name)
);
"""


_memdb_counter = 0


class SqliteArtifactStore(ArtifactStore):
    def __init__(self, path: str = ":memory:"):
        global _memdb_counter
        if path == ":memory:":
            # plain :memory: would give every executor thread its own empty
            # database; a named shared-cache URI makes them one database.
            _memdb_counter += 1
            path = f"file:owtpu_mem_{_memdb_counter}?mode=memory&cache=shared"
        self.path = path
        self._uri = path.startswith("file:")
        self._local = threading.local()
        self._init_lock = threading.Lock()
        self._conns: list = []
        self._anchor = self._conn()  # keeps shared in-memory DBs alive

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, check_same_thread=False, uri=self._uri)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            with self._init_lock:
                conn.executescript(_SCHEMA)  # idempotent (IF NOT EXISTS)
                self._conns.append(conn)
            self._local.conn = conn
        return conn

    async def _run(self, fn, *args):
        return await asyncio.get_event_loop().run_in_executor(None, fn, *args)

    # -- CRUD --------------------------------------------------------------
    def _put_sync(self, doc_id: str, doc: Dict[str, Any], rev: Optional[str]) -> str:
        conn = self._conn()
        body = json.dumps(doc)
        digest = hashlib.sha1(body.encode()).hexdigest()[:10]
        with self._init_lock, conn:
            row = conn.execute("SELECT rev FROM documents WHERE id=?", (doc_id,)).fetchone()
            if row is not None:
                cur = row[0]
                if rev is None or rev != cur:
                    raise DocumentConflict(f"document {doc_id!r} update conflict")
                gen = int(cur.split("-")[0]) + 1
            else:
                if rev is not None:
                    raise DocumentConflict(f"document {doc_id!r} does not exist at rev {rev}")
                gen = 1
            new_rev = f"{gen}-{digest}"
            stored = dict(doc)
            stored["_id"] = doc_id
            stored["_rev"] = new_rev
            conn.execute(
                "INSERT OR REPLACE INTO documents (id, rev, collection, namespace, name, updated, body)"
                " VALUES (?,?,?,?,?,?,?)",
                (doc_id, new_rev, doc.get("entityType", ""), str(doc.get("namespace", "")),
                 doc.get("name"), sort_key(doc), json.dumps(stored)))
            return new_rev

    async def put(self, doc_id: str, doc: Dict[str, Any],
                  rev: Optional[str] = None) -> str:
        return await self._run(self._put_sync, doc_id, doc, rev)

    def _get_sync(self, doc_id: str) -> Dict[str, Any]:
        row = self._conn().execute("SELECT body FROM documents WHERE id=?", (doc_id,)).fetchone()
        if row is None:
            raise NoDocumentException(doc_id)
        return json.loads(row[0])

    async def get(self, doc_id: str) -> Dict[str, Any]:
        return await self._run(self._get_sync, doc_id)

    def _delete_sync(self, doc_id: str, rev: Optional[str]) -> bool:
        conn = self._conn()
        with self._init_lock, conn:
            row = conn.execute("SELECT rev FROM documents WHERE id=?", (doc_id,)).fetchone()
            if row is None:
                raise NoDocumentException(doc_id)
            if rev is not None and row[0] != rev:
                raise DocumentConflict(f"document {doc_id!r} delete conflict")
            conn.execute("DELETE FROM documents WHERE id=?", (doc_id,))
            conn.execute("DELETE FROM attachments WHERE doc_id=?", (doc_id,))
            return True

    async def delete(self, doc_id: str, rev: Optional[str] = None) -> bool:
        return await self._run(self._delete_sync, doc_id, rev)

    # -- views -------------------------------------------------------------
    def _query_sync(self, collection, namespace, name, since, upto, skip, limit,
                    descending) -> List[Dict[str, Any]]:
        sql = "SELECT body FROM documents WHERE collection=?"
        args: list = [collection]
        if namespace is not None:
            # escape LIKE wildcards: '_' is a valid namespace character and
            # must not match arbitrary characters (cross-namespace leakage)
            escaped = namespace.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")
            sql += " AND (namespace=? OR namespace LIKE ? ESCAPE '\\')"
            args += [namespace, escaped + "/%"]
        if name is not None:
            sql += " AND name=?"
            args.append(name)
        if since is not None:
            sql += " AND updated>=?"
            args.append(since)
        if upto is not None:
            sql += " AND updated<=?"
            args.append(upto)
        sql += f" ORDER BY updated {'DESC' if descending else 'ASC'}"
        if limit:
            sql += " LIMIT ?"
            args.append(limit)
            if skip:
                sql += " OFFSET ?"
                args.append(skip)
        elif skip:
            sql += " LIMIT -1 OFFSET ?"
            args.append(skip)
        rows = self._conn().execute(sql, args).fetchall()
        return [json.loads(r[0]) for r in rows]

    async def query(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None,
                    skip: int = 0, limit: int = 0,
                    descending: bool = True) -> List[Dict[str, Any]]:
        return await self._run(
            lambda: self._query_sync(collection, namespace, name, since, upto,
                                     skip, limit, descending))

    def _count_sync(self, collection, namespace, name, since, upto) -> int:
        sql = "SELECT COUNT(*) FROM documents WHERE collection=?"
        args: list = [collection]
        if namespace is not None:
            escaped = namespace.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")
            sql += " AND (namespace=? OR namespace LIKE ? ESCAPE '\\')"
            args += [namespace, escaped + "/%"]
        if name is not None:
            sql += " AND name=?"
            args.append(name)
        if since is not None:
            sql += " AND updated>=?"
            args.append(since)
        if upto is not None:
            sql += " AND updated<=?"
            args.append(upto)
        return self._conn().execute(sql, args).fetchone()[0]

    async def count(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None
                    ) -> int:
        return await self._run(
            lambda: self._count_sync(collection, namespace, name, since, upto))

    # -- attachments -------------------------------------------------------
    async def attach(self, doc_id: str, name: str, content_type: str,
                     data: bytes) -> None:
        if self.attachment_store is not None:
            return await self.attachment_store.attach(doc_id, name,
                                                      content_type, data)
        def go():
            with self._conn() as conn:
                conn.execute(
                    "INSERT OR REPLACE INTO attachments (doc_id, name, content_type, data)"
                    " VALUES (?,?,?,?)", (doc_id, name, content_type, data))
        await self._run(go)

    async def read_attachment(self, doc_id: str, name: str) -> Tuple[str, bytes]:
        if self.attachment_store is not None:
            return await self.attachment_store.read_attachment(doc_id, name)
        def go():
            row = self._conn().execute(
                "SELECT content_type, data FROM attachments WHERE doc_id=? AND name=?",
                (doc_id, name)).fetchone()
            if row is None:
                raise NoDocumentException(f"attachment {doc_id}/{name}")
            return row[0], bytes(row[1])
        return await self._run(go)

    async def delete_attachments(self, doc_id: str,
                                 except_name: Optional[str] = None) -> None:
        if self.attachment_store is not None:
            return await self.attachment_store.delete_attachments(
                doc_id, except_name=except_name)
        def go():
            with self._conn() as conn:
                if except_name is None:
                    conn.execute("DELETE FROM attachments WHERE doc_id=?",
                                 (doc_id,))
                else:
                    conn.execute(
                        "DELETE FROM attachments WHERE doc_id=? AND name<>?",
                        (doc_id, except_name))
        await self._run(go)

    async def close(self) -> None:
        await super().close()
        with self._init_lock:
            for c in self._conns:
                try:
                    c.close()
                except sqlite3.Error:
                    pass
            self._conns.clear()


class SqliteArtifactStoreProvider:
    @staticmethod
    def make_store(name: str = "whisks", path: Optional[str] = None, **kwargs
                   ) -> SqliteArtifactStore:
        return SqliteArtifactStore(path or f"./{name}.db")

"""Kafka backend (gated).

Rebuild of the reference's connector/kafka (KafkaMessagingProvider /
KafkaConsumerConnector / KafkaProducerConnector): topics with per-topic
retention, long-poll peek, commit-after-peek. Requires `aiokafka` (or
`kafka-python`), which is not part of this image — the provider raises a
clear error when the client library is absent; deployments with Kafka
install the client and select this provider via the MessagingProvider SPI
(CONFIG_whisk_spi_MessagingProvider=openwhisk_tpu.messaging.kafka:KafkaMessagingProvider).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from .connector import MessageConsumer, MessageProducer, MessagingProvider

try:
    import aiokafka  # type: ignore[import-not-found]
    HAVE_KAFKA = True
except ImportError:
    aiokafka = None
    HAVE_KAFKA = False

# payload cap mirrors the reference: 1 MB + serdes overhead
# (application.conf:337-366)
MAX_REQUEST_SIZE = 1024 * 1024 + 6144


def _require_kafka() -> None:
    if not HAVE_KAFKA:
        raise RuntimeError(
            "Kafka backend selected but no kafka client library is installed "
            "(need aiokafka). Use the TCP bus (openwhisk_tpu.messaging.tcp) "
            "or the in-memory bus instead.")


class KafkaProducer(MessageProducer):
    def __init__(self, bootstrap: str):
        _require_kafka()
        self._producer = aiokafka.AIOKafkaProducer(
            bootstrap_servers=bootstrap, max_request_size=MAX_REQUEST_SIZE,
            acks="all")
        self._started = False
        self._sent = 0

    @property
    def sent_count(self) -> int:
        return self._sent

    async def send(self, topic: str, msg) -> None:
        if not self._started:
            await self._producer.start()
            self._started = True
        payload = msg if isinstance(msg, (bytes, bytearray)) else msg.serialize()
        await self._producer.send_and_wait(topic, bytes(payload))
        self._sent += 1
        from .connector import stamp_produce
        stamp_produce(msg)  # waterfall produce edge (broker-acknowledged)

    async def send_many(self, items) -> None:
        """Coalesced produce: enqueue the whole micro-batch into the
        client's accumulator first, then await the acks together — the
        client packs them into shared produce requests (its batching is
        record-level), so N messages cost one round of broker round trips
        instead of N sequential send_and_wait barriers."""
        if not self._started:
            await self._producer.start()
            self._started = True
        futs = [await self._producer.send(topic, bytes(payload))
                for (topic, payload, _m) in items]
        import asyncio
        await asyncio.gather(*futs)
        self._sent += len(items)
        from .connector import stamp_produce
        for _topic, _payload, m in items:
            if m is not None:
                stamp_produce(m)  # produce edge per message, acks gathered

    async def close(self) -> None:
        if self._started:
            await self._producer.stop()


class KafkaConsumer(MessageConsumer):
    def __init__(self, bootstrap: str, topic: str, group: str, max_peek: int = 128,
                 from_latest: bool = False):
        _require_kafka()
        self.topic = topic
        self.max_peek = max_peek
        # from_latest: ephemeral streams (health pings) must not replay the
        # retained backlog when a new per-controller group first appears
        self._consumer = aiokafka.AIOKafkaConsumer(
            topic, bootstrap_servers=bootstrap, group_id=group,
            enable_auto_commit=False,
            auto_offset_reset="latest" if from_latest else "earliest")
        self._started = False

    async def peek(self, max_messages: int, timeout: float = 0.5
                   ) -> List[Tuple[str, int, int, bytes]]:
        if not self._started:
            await self._consumer.start()
            self._started = True
        batches = await self._consumer.getmany(
            timeout_ms=int(timeout * 1000),
            max_records=min(max_messages, self.max_peek))
        out = []
        for tp, records in batches.items():
            for r in records:
                out.append((r.topic, r.partition, r.offset, r.value))
        return out

    def commit(self):
        """Fire-and-forget offset commit (the base contract); returns the
        spawned task so callers needing commit-before-handoff ordering
        (e.g. the integration suite) can await it."""
        if self._started:
            from ..utils.tasks import spawn
            return spawn(self._consumer.commit(), name="kafka-commit")
        return None

    async def close(self) -> None:
        if self._started:
            await self._consumer.stop()


class KafkaMessagingProvider(MessagingProvider):
    def __init__(self, bootstrap: str = "localhost:9092"):
        _require_kafka()
        self.bootstrap = bootstrap

    def get_producer(self) -> KafkaProducer:
        return KafkaProducer(self.bootstrap)

    def get_consumer(self, topic: str, group_id: str, max_peek: int = 128,
                     from_latest: bool = False) -> KafkaConsumer:
        return KafkaConsumer(self.bootstrap, topic, group_id, max_peek,
                             from_latest=from_latest)

    def ensure_topic(self, topic: str, partitions: int = 1,
                     retention_bytes: Optional[int] = None):
        """Best-effort topic creation with retention.bytes (the reference
        creates topics with per-topic retention configs,
        KafkaMessagingProvider.ensureTopic). Falls back to broker
        auto-create when no admin client is importable or the broker
        rejects the call — retention is then operator-managed. Returns the
        spawned admin task (or None) so callers that need create-before-
        produce ordering can await it; the base contract ignores it."""
        from ..utils.tasks import spawn
        try:
            from aiokafka.admin import (  # type: ignore[import-not-found]
                AIOKafkaAdminClient, NewTopic)
        except ImportError:
            return

        async def create():
            admin = AIOKafkaAdminClient(bootstrap_servers=self.bootstrap)
            await admin.start()
            try:
                configs = {}
                if retention_bytes is not None:
                    configs["retention.bytes"] = str(retention_bytes)
                await admin.create_topics([NewTopic(
                    name=topic, num_partitions=partitions,
                    replication_factor=1, topic_configs=configs)])
            except Exception:  # noqa: BLE001 — exists/unsupported: broker wins
                pass
            finally:
                await admin.close()

        try:
            import asyncio
            if asyncio.get_event_loop().is_running():
                return spawn(create(), name=f"kafka-ensure-{topic}")
        except RuntimeError:
            pass
        return None

"""System tests: drive the standalone server over real HTTP (mirrors the
reference's system/basic Wsk*Tests driven against a deployed system)."""
import asyncio
import base64

import aiohttp
import pytest

from openwhisk_tpu.standalone import GUEST_KEY, GUEST_UUID, make_standalone

AUTH = "Basic " + base64.b64encode(f"{GUEST_UUID}:{GUEST_KEY}".encode()).decode()
HDRS = {"Authorization": AUTH, "Content-Type": "application/json"}

PORT = 13233
BASE = f"http://127.0.0.1:{PORT}/api/v1"

HELLO_CODE = """
def main(args):
    name = args.get('name', 'stranger')
    print('hello was called with', name)
    return {'greeting': 'Hello ' + name + '!'}
"""

FAIL_CODE = "def main(args):\n    return {'error': 'deliberate failure'}\n"

STEP_CODE = "def main(args):\n    return {'n': args.get('n', 0) + 1}\n"


async def _serve(coro_fn):
    controller = await make_standalone(port=PORT)
    try:
        async with aiohttp.ClientSession() as session:
            return await coro_fn(session)
    finally:
        await controller.stop()


def run_system(coro_fn):
    return asyncio.run(_serve(coro_fn))


class TestStandaloneSystem:
    def test_full_action_lifecycle(self):
        async def go(s: aiohttp.ClientSession):
            out = {}
            # unauthenticated
            async with s.get(f"{BASE}/namespaces") as r:
                out["unauth"] = r.status
            # create
            async with s.put(f"{BASE}/namespaces/_/actions/hello", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": HELLO_CODE}}) as r:
                out["create"] = (r.status, await r.json())
            # conflict without overwrite
            async with s.put(f"{BASE}/namespaces/_/actions/hello", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": HELLO_CODE}}) as r:
                out["conflict"] = r.status
            # update with overwrite bumps version
            async with s.put(f"{BASE}/namespaces/_/actions/hello?overwrite=true",
                             headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": HELLO_CODE}}) as r:
                out["update"] = (await r.json())["version"]
            # get
            async with s.get(f"{BASE}/namespaces/_/actions/hello", headers=HDRS) as r:
                out["get"] = (await r.json())["exec"]["kind"]
            # list
            async with s.get(f"{BASE}/namespaces/_/actions", headers=HDRS) as r:
                lst = await r.json()
                out["list"] = [a["name"] for a in lst]
                out["list_has_code"] = "code" in lst[0].get("exec", {})
            # blocking invoke
            async with s.post(f"{BASE}/namespaces/_/actions/hello?blocking=true",
                              headers=HDRS, json={"name": "TPU"}) as r:
                body = await r.json()
                out["invoke"] = (r.status, body["response"]["result"],
                                 body["response"]["success"], body["activationId"])
            # blocking invoke with ?result=true
            async with s.post(f"{BASE}/namespaces/_/actions/hello?blocking=true&result=true",
                              headers=HDRS, json={"name": "Whisk"}) as r:
                out["result_only"] = await r.json()
            # non-blocking
            async with s.post(f"{BASE}/namespaces/_/actions/hello",
                              headers=HDRS, json={}) as r:
                out["nonblocking"] = (r.status, "activationId" in await r.json())
            # activation record + logs (the ack races the async record
            # write: poll the by-id GET until the record lands)
            aid = out["invoke"][3]
            for _ in range(40):
                async with s.get(f"{BASE}/namespaces/_/activations/{aid}",
                                 headers=HDRS) as r:
                    if r.status == 200:
                        act = await r.json()
                        out["activation"] = (act["response"]["result"],
                                             act["logs"])
                        break
                await asyncio.sleep(0.25)
            async with s.get(f"{BASE}/namespaces/_/activations/{aid}/logs",
                             headers=HDRS) as r:
                out["logs"] = (await r.json())["logs"]
            # activation records land asynchronously after the blocking
            # ack: poll the list until both invokes are visible
            out["act_list"] = 0
            for _ in range(40):
                async with s.get(f"{BASE}/namespaces/_/activations?limit=10",
                                 headers=HDRS) as r:
                    out["act_list"] = len(await r.json())
                if out["act_list"] >= 2:
                    break
                await asyncio.sleep(0.25)
            # delete
            async with s.delete(f"{BASE}/namespaces/_/actions/hello", headers=HDRS) as r:
                out["delete"] = r.status
            async with s.get(f"{BASE}/namespaces/_/actions/hello", headers=HDRS) as r:
                out["gone"] = r.status
            return out

        out = run_system(go)
        assert out["unauth"] == 401
        assert out["create"][0] == 200
        assert out["conflict"] == 409
        assert out["update"] == "0.0.2"
        assert out["get"] == "python:3"
        assert out["list"] == ["hello"]
        assert not out["list_has_code"]
        status, result, success, _aid = out["invoke"]
        assert (status, success) == (200, True)
        assert result == {"greeting": "Hello TPU!"}
        assert out["result_only"] == {"greeting": "Hello Whisk!"}
        assert out["nonblocking"] == (202, True)
        assert out["activation"][0] == {"greeting": "Hello TPU!"}
        assert any("hello was called with TPU" in l for l in out["logs"])
        assert out["act_list"] >= 2
        assert out["delete"] == 200
        assert out["gone"] == 404

    def test_application_error_returns_502(self):
        async def go(s):
            async with s.put(f"{BASE}/namespaces/_/actions/failer", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": FAIL_CODE}}):
                pass
            async with s.post(f"{BASE}/namespaces/_/actions/failer?blocking=true",
                              headers=HDRS, json={}) as r:
                return r.status, await r.json()

        status, body = run_system(go)
        assert status == 502
        assert body["response"]["result"] == {"error": "deliberate failure"}
        assert body["response"]["status"] == "application error"

    def test_unknown_kind_and_missing_action(self):
        async def go(s):
            out = {}
            async with s.put(f"{BASE}/namespaces/_/actions/x", headers=HDRS,
                             json={"exec": {"kind": "cobol:1959", "code": ""}}) as r:
                out["bad_kind"] = r.status
            async with s.post(f"{BASE}/namespaces/_/actions/nothere?blocking=true",
                              headers=HDRS, json={}) as r:
                out["missing"] = r.status
            return out

        out = run_system(go)
        assert out["bad_kind"] == 400
        assert out["missing"] == 404

    def test_sequences_chain_results(self):
        async def go(s):
            async with s.put(f"{BASE}/namespaces/_/actions/step", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": STEP_CODE}}):
                pass
            async with s.put(f"{BASE}/namespaces/_/actions/seq", headers=HDRS,
                             json={"exec": {"kind": "sequence",
                                            "components": ["_/step", "_/step", "_/step"]}}) as r:
                assert r.status == 200, await r.text()
            async with s.post(f"{BASE}/namespaces/_/actions/seq?blocking=true",
                              headers=HDRS, json={"n": 10}) as r:
                body = await r.json()
            # component activations are recorded in the logs
            return body

        body = run_system(go)
        assert body["response"]["result"] == {"n": 13}
        assert len(body["logs"]) == 3

    def test_triggers_and_rules_fire_actions(self):
        async def go(s):
            async with s.put(f"{BASE}/namespaces/_/actions/hello", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": HELLO_CODE}}):
                pass
            async with s.put(f"{BASE}/namespaces/_/triggers/t1", headers=HDRS,
                             json={"parameters": [{"key": "name", "value": "Trigger"}]}) as r:
                assert r.status == 200
            async with s.put(f"{BASE}/namespaces/_/rules/r1", headers=HDRS,
                             json={"trigger": "_/t1", "action": "_/hello"}) as r:
                assert r.status == 200, await r.text()
            async with s.post(f"{BASE}/namespaces/_/triggers/t1", headers=HDRS,
                              json={}) as r:
                fire = (r.status, await r.json())
            # the fired rule produces an action activation (cold start: poll)
            acts = []
            for _ in range(20):
                await asyncio.sleep(0.25)
                async with s.get(f"{BASE}/namespaces/_/activations?name=hello",
                                 headers=HDRS) as r:
                    acts = await r.json()
                if acts:
                    break
            # deactivate rule -> fire produces no new activation
            async with s.post(f"{BASE}/namespaces/_/rules/r1", headers=HDRS,
                              json={"status": "inactive"}) as r:
                assert r.status == 200
            async with s.post(f"{BASE}/namespaces/_/triggers/t1", headers=HDRS,
                              json={}) as r:
                fire2 = r.status
            return fire, acts, fire2

        (fire_status, fire_body), acts, fire2 = run_system(go)
        assert fire_status == 202 and "activationId" in fire_body
        assert len(acts) >= 1
        assert fire2 == 204  # no active rules -> NoContent, like the reference

    def test_packages_with_parameters(self):
        async def go(s):
            async with s.put(f"{BASE}/namespaces/_/packages/utils", headers=HDRS,
                             json={"parameters": [{"key": "name", "value": "FromPkg"}]}) as r:
                assert r.status == 200
            async with s.put(f"{BASE}/namespaces/_/actions/utils/phello", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": HELLO_CODE}}) as r:
                assert r.status == 200, await r.text()
            # invoke through the package: package parameter applies
            async with s.post(f"{BASE}/namespaces/_/actions/utils/phello?blocking=true",
                              headers=HDRS, json={}) as r:
                body = await r.json()
            async with s.get(f"{BASE}/namespaces/_/packages/utils", headers=HDRS) as r:
                pkg = await r.json()
            return body, pkg

        body, pkg = run_system(go)
        assert body["response"]["result"] == {"greeting": "Hello FromPkg!"}
        assert pkg["actions"] == [{"name": "phello", "version": "0.0.1"}]

    def test_web_action(self):
        async def go(s):
            code = ("def main(args):\n"
                    "    return {'greeting': 'Hi ' + args.get('who', 'web'),"
                    " 'method': args.get('__ow_method')}\n")
            async with s.put(f"{BASE}/namespaces/_/actions/webhello", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": code},
                                   "annotations": [{"key": "web-export", "value": True}]}):
                pass
            out = {}
            async with s.get(f"http://127.0.0.1:{PORT}/api/v1/web/guest/default/webhello.json?who=You") as r:
                out["web"] = (r.status, await r.json())
            # action without web-export is 404 via web path
            async with s.put(f"{BASE}/namespaces/_/actions/notweb", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": HELLO_CODE}}):
                pass
            async with s.get(f"http://127.0.0.1:{PORT}/api/v1/web/guest/default/notweb.json") as r:
                out["notweb"] = r.status
            return out

        out = run_system(go)
        status, body = out["web"]
        assert status == 200
        assert body == {"greeting": "Hi You", "method": "get"}
        assert out["notweb"] == 404

    def test_throttling_rejects_excess(self):
        async def go(s):
            # a fresh controller: drop the invocation rate to 3/min via the
            # entitlement override on the running server object is not
            # reachable over HTTP; use repeated fires against default 60 is
            # slow — instead assert the 429 shape via many rapid invokes of a
            # tiny limit by patching is out of scope here; covered in unit
            # tests. Here just verify sustained invokes stay 200.
            async with s.put(f"{BASE}/namespaces/_/actions/hello", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": HELLO_CODE}}):
                pass
            statuses = []
            for _ in range(3):
                async with s.post(f"{BASE}/namespaces/_/actions/hello?blocking=true",
                                  headers=HDRS, json={}) as r:
                    statuses.append(r.status)
            return statuses

        assert run_system(go) == [200, 200, 200]


class TestWebActionAuth:
    def test_require_whisk_auth_annotation(self):
        """ref WebActions: a secret-valued require-whisk-auth annotation
        demands the matching X-Require-Whisk-Auth header; boolean true
        demands valid platform credentials."""
        async def go(s):
            code = "def main(args):\n    return {'ok': True}\n"
            async with s.put(f"{BASE}/namespaces/_/actions/sec", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": code},
                                   "annotations": [
                                       {"key": "web-export", "value": True},
                                       {"key": "require-whisk-auth",
                                        "value": "shhh"}]}):
                pass
            async with s.put(f"{BASE}/namespaces/_/actions/auth", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": code},
                                   "annotations": [
                                       {"key": "web-export", "value": True},
                                       {"key": "require-whisk-auth",
                                        "value": True}]}):
                pass
            url = f"http://127.0.0.1:{PORT}/api/v1/web/guest/default"
            out = {}
            async with s.get(f"{url}/sec.json") as r:
                out["no_header"] = r.status
            async with s.get(f"{url}/sec.json",
                             headers={"X-Require-Whisk-Auth": "wrong"}) as r:
                out["bad_header"] = r.status
            async with s.get(f"{url}/sec.json",
                             headers={"X-Require-Whisk-Auth": "shhh"}) as r:
                out["good_header"] = (r.status, await r.json())
            async with s.get(f"{url}/auth.json") as r:
                out["anon"] = r.status
            async with s.get(f"{url}/auth.json",
                             headers={"Authorization": HDRS["Authorization"]}) as r:
                out["authed"] = r.status
            return out

        out = run_system(go)
        assert out["no_header"] == 401
        assert out["bad_header"] == 401
        assert out["good_header"] == (200, {"ok": True})
        assert out["anon"] == 401
        assert out["authed"] == 200


class TestWskApiCli:
    def test_api_create_list_delete(self, capsys):
        """wsk api create/list/delete against the standalone server
        (reference: wsk api + core/routemgmt)."""
        from openwhisk_tpu.standalone import GUEST_KEY, GUEST_UUID
        from openwhisk_tpu.tools import wsk

        async def serve():
            controller = await make_standalone(port=PORT)
            try:
                import functools
                loop = asyncio.get_event_loop()

                def cli(*argv):
                    return wsk.main([
                        "--apihost", f"http://127.0.0.1:{PORT}",
                        "--auth", f"{GUEST_UUID}:{GUEST_KEY}", *argv])

                # wsk.main runs its own asyncio.run -> execute in a thread
                create = await loop.run_in_executor(None, functools.partial(
                    cli, "api", "create", "/books", "/list",
                    "--verb", "get", "--action", "webhello"))
                lst = await loop.run_in_executor(None, functools.partial(
                    cli, "api", "list"))
                delete = await loop.run_in_executor(None, functools.partial(
                    cli, "api", "delete", "/books"))
                return create, lst, delete
            finally:
                await controller.stop()

        create, lst, delete = asyncio.run(serve())
        out = capsys.readouterr().out
        assert create == 0 and delete == 0 and lst == 0
        # the list output is the swagger view: basePath, the verb key under
        # paths["/list"], and the backend URL with "_" RESOLVED to the real
        # namespace (a literal "_" backend would 404 at invocation time)
        assert '"basePath": "/books"' in out
        assert '"/list"' in out and '"get"' in out
        assert "/api/v1/web/guest/" in out
        assert "/api/v1/web/_/" not in out


class TestBinaryActionEndToEnd:
    def test_zip_action_invokes(self):
        """binary (base64-zip) action through the full stack: PUT with
        binary exec -> cold start -> /init extracts the zip -> /run."""
        import base64 as _b64
        import io as _io
        import zipfile as _zip

        buf = _io.BytesIO()
        with _zip.ZipFile(buf, "w") as z:
            z.writestr("__main__.py",
                       "from util import stamp\n"
                       "def main(args):\n"
                       "    return {'stamped': stamp(args.get('v', 0))}\n")
            z.writestr("util.py", "def stamp(v):\n    return v * 10\n")
        code = _b64.b64encode(buf.getvalue()).decode()

        async def go(s):
            async with s.put(f"{BASE}/namespaces/_/actions/zipact",
                             headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": code,
                                            "binary": True}}) as r:
                assert r.status == 200, await r.text()
            async with s.post(
                    f"{BASE}/namespaces/_/actions/zipact?blocking=true&result=true",
                    headers=HDRS, json={"v": 7}) as r:
                return r.status, await r.json()

        status, body = run_system(go)
        assert (status, body) == (200, {"stamped": 70})

    def test_require_whisk_auth_zero_secret_still_enforced(self):
        """The numeric secret 0 must not read as boolean False and disable
        the check (0 == False in Python)."""
        async def go(s):
            code = "def main(args):\n    return {'ok': True}\n"
            async with s.put(f"{BASE}/namespaces/_/actions/zsec", headers=HDRS,
                             json={"exec": {"kind": "python:3", "code": code},
                                   "annotations": [
                                       {"key": "web-export", "value": True},
                                       {"key": "require-whisk-auth",
                                        "value": 0}]}):
                pass
            url = f"http://127.0.0.1:{PORT}/api/v1/web/guest/default/zsec.json"
            async with s.get(url) as r:
                anon = r.status
            async with s.get(url, headers={"X-Require-Whisk-Auth": "0"}) as r:
                good = r.status
            return anon, good

        anon, good = run_system(go)
        assert anon == 401
        assert good == 200


class TestApiDocs:
    def test_swagger_served_unauthenticated(self):
        async def go(s):
            async with s.get(f"{BASE}/api-docs") as r:
                return r.status, await r.json()

        status, doc = run_system(go)
        assert status == 200
        assert doc["swagger"] == "2.0"
        paths = doc["paths"]
        assert "/api/v1/namespaces/{ns}/actions/{name}" in paths
        assert "post" in paths["/api/v1/namespaces/{ns}/actions/{name}"]
        assert "/api/v1/namespaces/{ns}/apis" in paths

    def test_swagger_ui_page_and_docs_redirect(self):
        """ref RestAPIs.scala:50-81: the swagger UI page is served
        unauthenticated (self-contained — no CDN assets) and /docs
        redirects to it."""
        async def go(s):
            out = {}
            async with s.get(f"{BASE}/api-docs/ui") as r:
                out["ui"] = (r.status, r.headers["Content-Type"],
                             await r.text())
            async with s.get(f"http://127.0.0.1:{PORT}/docs") as r:
                out["redirect"] = (r.status, str(r.url))
            return out

        out = run_system(go)
        status, ctype, html = out["ui"]
        assert status == 200 and "text/html" in ctype
        assert "OpenWhisk-TPU REST API" in html
        assert "fetch('/api/v1/api-docs')" in html  # the JSON, same-origin
        # strictly self-contained: no external URLs at all (must render
        # in air-gapped deployments)
        assert "http://" not in html and "https://" not in html
        r_status, r_url = out["redirect"]
        assert r_status == 200 and r_url.endswith("/api/v1/api-docs/ui")


class TestPackageBindings:
    def test_invoke_through_binding_merges_parameters(self):
        """ref Packages.scala bindings: a binding references a provider
        package; invoking <binding>/<action> resolves the provider's action
        with parameter precedence provider < binding < invoke args."""
        CODE = ("def main(args):\n"
                "    return {'who': args.get('who'), 'tier': args.get('tier')}\n")

        async def go(s):
            # provider package with params + an action inside it
            async with s.put(f"{BASE}/namespaces/_/packages/prov", headers=HDRS,
                             json={"parameters": [
                                 {"key": "who", "value": "provider"},
                                 {"key": "tier", "value": "base"}]}) as r:
                assert r.status == 200
            async with s.put(f"{BASE}/namespaces/_/actions/prov/whoami",
                             headers=HDRS,
                             json={"exec": {"kind": "python:3",
                                            "code": CODE}}) as r:
                assert r.status == 200, await r.text()
            # binding overriding one param
            async with s.put(f"{BASE}/namespaces/_/packages/bnd", headers=HDRS,
                             json={"binding": {"namespace": "guest",
                                               "name": "prov"},
                                   "parameters": [
                                       {"key": "who", "value": "binding"}]}) as r:
                assert r.status == 200, await r.text()
            out = {}
            # invoke through the binding: binding param wins over provider's
            async with s.post(
                    f"{BASE}/namespaces/_/actions/bnd/whoami?blocking=true&result=true",
                    headers=HDRS, json={}) as r:
                out["bound"] = (r.status, await r.json())
            # invoke args beat both
            async with s.post(
                    f"{BASE}/namespaces/_/actions/bnd/whoami?blocking=true&result=true",
                    headers=HDRS, json={"who": "caller"}) as r:
                out["args"] = await r.json()
            # binding document lists the provider reference
            async with s.get(f"{BASE}/namespaces/_/packages/bnd",
                             headers=HDRS) as r:
                out["doc"] = await r.json()
            return out

        out = run_system(go)
        assert out["bound"] == (200, {"who": "binding", "tier": "base"})
        assert out["args"] == {"who": "caller", "tier": "base"}
        assert out["doc"]["binding"] == {"namespace": "guest", "name": "prov"}


class TestPlaygroundAndPreflight:
    def test_playground_served_with_auth_wired(self):
        async def go(s: aiohttp.ClientSession):
            async with s.get(f"http://127.0.0.1:{PORT}/playground") as r:
                html = await r.text()
                assert r.status == 200
                assert "text/html" in r.headers["Content-Type"]
            # root redirects to the playground
            async with s.get(f"http://127.0.0.1:{PORT}/") as r2:
                assert r2.status == 200 and str(r2.url).endswith("/playground")
            return html
        html = run_system(go)
        assert "OpenWhisk-TPU playground" in html
        # the page carries working guest credentials for its fetch calls
        expected = base64.b64encode(f"{GUEST_UUID}:{GUEST_KEY}".encode()).decode()
        assert expected in html

    def test_no_ui_leaves_playground_unrouted(self):
        async def serve():
            controller = await make_standalone(port=PORT, ui=False)
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"http://127.0.0.1:{PORT}/playground") as r:
                        return r.status
            finally:
                await controller.stop()
        # without the UI the path is not public: the auth middleware
        # rejects it before routing (401), and it is not routed anyway
        assert asyncio.run(serve()) in (401, 404)

    def test_preflight_checks(self):
        import socket

        from openwhisk_tpu.standalone.__main__ import preflight

        assert preflight(PORT + 600) is True
        with socket.socket() as s:
            s.bind(("127.0.0.1", PORT + 601))
            s.listen(1)
            assert preflight(PORT + 601) is False


class TestActivationDocsParam:
    def test_docs_true_returns_full_records(self):
        async def go(s: aiohttp.ClientSession):
            async with s.put(f"{BASE}/namespaces/_/actions/hello", headers=HDRS,
                             json={"exec": {"kind": "python:3",
                                            "code": HELLO_CODE}}):
                pass
            async with s.post(f"{BASE}/namespaces/_/actions/hello?blocking=true",
                              headers=HDRS, json={"name": "Docs"}):
                pass
            # the blocking ack races the asynchronous record write: poll
            summaries = []
            for _ in range(40):
                async with s.get(f"{BASE}/namespaces/_/activations",
                                 headers=HDRS) as r:
                    summaries = await r.json()
                if summaries:
                    break
                await asyncio.sleep(0.25)
            async with s.get(f"{BASE}/namespaces/_/activations?docs=true",
                             headers=HDRS) as r:
                full = await r.json()
            return summaries, full

        summaries, full = run_system(go)
        assert summaries and "response" not in summaries[0]
        # ?docs=true returns the complete record (ref Activations.scala)
        assert full and full[0]["response"]["result"] == \
            {"greeting": "Hello Docs!"}
        assert "logs" in full[0]


class TestManifestFlag:
    def test_custom_manifest_gates_kinds(self, tmp_path):
        import json as _json
        import subprocess
        import sys

        from openwhisk_tpu.core.entity import ExecManifest

        manifest = {"runtimes": {"python": [
            {"kind": "python:3", "image": {"name": "action-python-v3"},
             "default": True}]}}
        path = tmp_path / "runtimes.json"
        path.write_text(_json.dumps(manifest))
        # preflight validates the parsed dict and prints its kinds
        out = subprocess.run(
            [sys.executable, "-c",
             "import json; "
             "from openwhisk_tpu.standalone.__main__ import preflight; "
             f"m = json.load(open({str(path)!r})); import sys; "
             f"sys.exit(0 if preflight(13987, manifest=m, "
             f"manifest_path={str(path)!r}) else 1)"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "python:3" in out.stdout and "nodejs" not in out.stdout
        # a structurally-wrong manifest FAILs cleanly (no traceback)
        out = subprocess.run(
            [sys.executable, "-c",
             "from openwhisk_tpu.standalone.__main__ import preflight; "
             "import sys; sys.exit(0 if preflight("
             "13987, manifest={'runtimes': 'x'}) else 1)"],
            capture_output=True, text=True)
        assert out.returncode == 1, out.stdout + out.stderr
        assert "Traceback" not in out.stderr
        assert "[FAIL]" in out.stdout
        # unreadable file: the CLI exits 1 before boot
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        out = subprocess.run(
            [sys.executable, "-m", "openwhisk_tpu.standalone",
             "--manifest", str(bad), "--port", "13989"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 1
        assert "cannot read manifest" in out.stderr

        # the server built from the manifest rejects unknown kinds
        async def go():
            controller = await make_standalone(port=13988, manifest=manifest)
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.put(
                            "http://127.0.0.1:13988/api/v1/namespaces/_/actions/njs",
                            headers=HDRS,
                            json={"exec": {"kind": "nodejs:14",
                                           "code": "x"}}) as r:
                        return r.status, await r.json()
            finally:
                await controller.stop()

        try:
            status, body = asyncio.run(go())
        finally:
            ExecManifest.initialize(None)  # restore the process singleton
        assert status == 400
        assert "nodejs:14" in body["error"]

"""Multi-chip sharding of the balancer state.

The reference scales its balancer horizontally by giving each controller
JVM 1/clusterSize of every invoker's memory (Akka-Cluster membership,
ShardingContainerPoolBalancer.scala:449-585). The TPU-native equivalent in
this package shards the *invoker axis itself* across a `jax.sharding.Mesh`:
each device owns the capacity/health rows of its invoker shard, probes them
locally, and a single all-gather per scan step elects the global placement —
collectives ride ICI, host code never touches per-invoker state (SURVEY
§2.6 item 8, §5.8).
"""
from .sharded_state import (make_mesh, make_sharded_schedule,
                            make_sharded_release, shard_state)
from .fleet_mesh import (FLEET_AXIS, fleet_pair, make_fleet_mesh,
                         make_fleet_release_vector,
                         make_fleet_repair_schedule, mesh_axis, mesh_shards,
                         mesh_topology)

__all__ = ["make_mesh", "make_sharded_schedule", "make_sharded_release",
           "shard_state", "FLEET_AXIS", "make_fleet_mesh", "fleet_pair",
           "make_fleet_repair_schedule", "make_fleet_release_vector",
           "mesh_axis", "mesh_shards", "mesh_topology"]

"""Invoker supervision: the health protocol.

Rebuild of core/controller/.../loadBalancer/InvokerSupervision.scala:
  - invokers ping the `health` topic at 1 Hz (InvokerReactive.scala:337-342);
  - one FSM per invoker with states Healthy('up') / Unhealthy / Unresponsive
    / Offline('down') (:47-66);
  - a ring buffer of the last 10 invocation outcomes; > 3 system errors ->
    Unhealthy, > 3 timeouts -> Unresponsive (:435-443);
  - Offline after 10 s of ping silence (:294);
  - new invokers register lazily on their first ping (:191-207) and the
    balancer state grows in place — shrinking is by marking Offline only;
  - unhealthy invokers recover via periodic test traffic; here the FSM
    re-opens the error window after a cooldown (the reference posts a system
    test action once per minute — hook `send_test_action` to enable that).
Status changes are pushed to the balancer through `on_status_change`, which
feeds the device health mask in the TPU balancer.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...core.entity import InvokerInstanceId
from ...messaging.connector import MessageFeed, HEALTH_RETENTION_BYTES, HEALTH_TOPIC
from ...messaging.message import PingMessage
from ...utils.ring_buffer import RingBuffer
from ...utils.scheduler import Scheduler
from ...utils.transaction import TransactionId
from .base import HEALTHY, OFFLINE, UNHEALTHY, UNRESPONSIVE, InvokerHealth

SUCCESS = "success"
SYSTEM_ERROR = "system_error"
TIMEOUT = "timeout"

BUFFER_SIZE = 10
ERROR_TOLERANCE = 3
PING_TIMEOUT_S = 10.0
RECOVERY_COOLDOWN_S = 60.0


@dataclass
class InvokerActorState:
    id: InvokerInstanceId
    status: str = OFFLINE
    last_ping: float = 0.0
    buffer: RingBuffer = field(default_factory=lambda: RingBuffer(BUFFER_SIZE))
    # seed one cooldown in the past: the FIRST probe of an unhealthy invoker
    # must fire immediately (time.monotonic() is host uptime — a bare 0.0
    # default would suppress probes on freshly-booted hosts)
    last_recovery_attempt: float = field(
        default_factory=lambda: time.monotonic() - RECOVERY_COOLDOWN_S)

    def classify(self) -> str:
        """Derive the health status from the outcome window (:435-443)."""
        if self.buffer.count(lambda r: r == SYSTEM_ERROR) > ERROR_TOLERANCE:
            return UNHEALTHY
        if self.buffer.count(lambda r: r == TIMEOUT) > ERROR_TOLERANCE:
            return UNRESPONSIVE
        return HEALTHY


class InvokerPool:
    def __init__(self, messaging_provider,
                 on_status_change: Optional[Callable] = None,
                 send_test_action: Optional[Callable] = None,
                 logger=None, ping_timeout: float = PING_TIMEOUT_S,
                 group: str = "health", on_tick: Optional[Callable] = None):
        self.provider = messaging_provider
        self.on_status_change = on_status_change or (lambda inv, status: None)
        self.send_test_action = send_test_action
        #: optional 1 Hz callback riding the watchdog — the balancer hangs
        #: its telemetry burn-rate gauge refresh here so dashboards stay
        #: fresh without a scheduler of their own
        self.on_tick = on_tick
        self.logger = logger
        self.ping_timeout = ping_timeout
        self.group = group
        self.invokers: Dict[int, InvokerActorState] = {}
        #: advisory hints from the anomaly plane (invoker index -> firing
        #: alert name). Observability only: the FSM's status derivation
        #: never reads them — a flagged invoker still takes traffic until
        #: real outcome evidence (the ring buffer) demotes it.
        self.unhealthy_hints: Dict[int, str] = {}
        #: fleet observatory peer directory (ISSUE 16): invoker admin
        #: addresses announced on their health pings. Empty unless
        #: invokers run with the observatory enabled and an address set.
        self.invoker_admin: Dict[int, str] = {}
        self._feed: Optional[MessageFeed] = None
        self._watchdog: Optional[Scheduler] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        # pings are ephemeral: tight retention, and never replay a backlog
        # into a new per-controller group
        self.provider.ensure_topic(HEALTH_TOPIC,
                                   retention_bytes=HEALTH_RETENTION_BYTES)
        consumer = self.provider.get_consumer(HEALTH_TOPIC, self.group,
                                              max_peek=128, from_latest=True)
        box = {}

        async def handle(payload: bytes):
            try:
                ping = PingMessage.parse(payload)
                if ping.admin:
                    self.invoker_admin[ping.instance.instance] = ping.admin
                self.on_ping(ping.instance)
            except (ValueError, KeyError):
                pass
            box["feed"].processed()

        self._feed = MessageFeed("health", consumer, 128, handle, logger=self.logger)
        box["feed"] = self._feed
        self._feed.start()
        self._watchdog = Scheduler(1.0, self._check_offline, name="invoker-watchdog",
                                   logger=self.logger).start()

    async def stop(self) -> None:
        if self._watchdog:
            await self._watchdog.stop()
        if self._feed:
            await self._feed.stop()

    # -- events ------------------------------------------------------------
    def on_ping(self, instance: InvokerInstanceId) -> None:
        st = self.invokers.get(instance.instance)
        if st is None:
            # lazy registration on first ping (:191-207)
            st = InvokerActorState(instance, status=OFFLINE)
            self.invokers[instance.instance] = st
        st.id = instance  # refresh user_memory etc.
        st.last_ping = time.monotonic()
        if st.status == OFFLINE:
            self._transition(st, HEALTHY if st.classify() == HEALTHY else st.classify())
        elif st.status in (UNHEALTHY, UNRESPONSIVE):
            self._maybe_recover(st)

    def on_invocation_finished(self, instance: Optional[InvokerInstanceId],
                               is_system_error: bool, forced: bool) -> None:
        """Fold an invocation outcome into the window (LB feeds this from
        completion acks; forced timeouts count as timeouts)."""
        if instance is None:
            return
        st = self.invokers.get(instance.instance)
        if st is None:
            return
        outcome = SYSTEM_ERROR if is_system_error else (TIMEOUT if forced else SUCCESS)
        st.buffer.add(outcome)
        if st.status != OFFLINE:
            self._transition(st, st.classify())

    async def _check_offline(self) -> None:
        now = time.monotonic()
        for st in self.invokers.values():
            if st.status != OFFLINE and now - st.last_ping > self.ping_timeout:
                self._transition(st, OFFLINE)
        if self.on_tick is not None:
            try:
                self.on_tick()
            except Exception:  # noqa: BLE001 — a gauge refresh must never
                pass           # kill the health watchdog

    def _maybe_recover(self, st: InvokerActorState) -> None:
        now = time.monotonic()
        if now - st.last_recovery_attempt < RECOVERY_COOLDOWN_S:
            return
        st.last_recovery_attempt = now
        if self.send_test_action is not None:
            asyncio.get_event_loop().create_task(self.send_test_action(st.id))
        else:
            # no test-action channel: re-open the window for organic traffic
            st.buffer = RingBuffer(BUFFER_SIZE)
            self._transition(st, HEALTHY)

    def _transition(self, st: InvokerActorState, new_status: str) -> None:
        if new_status != st.status:
            old = st.status
            st.status = new_status
            if self.logger:
                self.logger.info(TransactionId.INVOKER_HEALTH,
                                 f"invoker{st.id.instance} {old} -> {new_status}",
                                 "InvokerPool")
            self.on_status_change(st.id, new_status)

    def set_unhealthy_hints(self, hints: Dict[int, str]) -> None:
        """Replace the advisory hint set (the anomaly plane pushes the full
        current dict every tick when CONFIG_whisk_anomaly_hintUnhealthy is
        on, so recovered invokers shed their hint automatically)."""
        self.unhealthy_hints = dict(hints)

    # -- views -------------------------------------------------------------
    def health(self) -> List[InvokerHealth]:
        return [InvokerHealth(st.id, st.status,
                              hint=self.unhealthy_hints.get(idx))
                for idx, st in sorted(self.invokers.items())]

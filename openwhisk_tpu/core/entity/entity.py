"""WhiskEntity base: common document fields + doc identity.

Ref: common/scala/.../core/entity/WhiskEntity.scala — every persisted entity
has namespace, name, version, publish, annotations, updated timestamp, and a
document id of the form "namespace/name".
"""
from __future__ import annotations

import time
from typing import Optional

from .ids import DocInfo, DocRevision
from .names import EntityName, EntityPath, FullyQualifiedEntityName
from .parameters import Parameters
from .semver import SemVer


class WhiskEntity:
    collection = "entities"

    def __init__(self, namespace: EntityPath, name: EntityName,
                 version: Optional[SemVer] = None, publish: bool = False,
                 annotations: Optional[Parameters] = None,
                 updated: Optional[float] = None):
        self.namespace = namespace
        self.name = name
        self.version = version or SemVer()
        self.publish = publish
        self.annotations = annotations or Parameters()
        self.updated = updated if updated is not None else time.time()
        self.rev = DocRevision()

    @property
    def docid(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def fully_qualified_name(self) -> FullyQualifiedEntityName:
        return FullyQualifiedEntityName(self.namespace, self.name)

    def docinfo(self) -> DocInfo:
        return DocInfo(self.docid, self.rev)

    def revision(self, rev: DocRevision) -> "WhiskEntity":
        self.rev = rev
        return self

    # -- serde -------------------------------------------------------------
    def base_json(self) -> dict:
        return {
            "namespace": self.namespace.to_json(),
            "name": self.name.to_json(),
            "version": self.version.to_json(),
            "publish": self.publish,
            "annotations": self.annotations.to_json(),
            "updated": int(self.updated * 1000),
        }

    def to_json(self) -> dict:
        raise NotImplementedError

    def to_document(self) -> dict:
        """JSON doc as stored, with entityType discriminator for views."""
        j = self.to_json()
        j["entityType"] = self.collection
        return j

"""CLI: run the TCP bus broker. `python -m openwhisk_tpu.messaging [--port]`"""
from __future__ import annotations

import argparse
import asyncio

from .tcp import TcpBusServer
from ..utils.tasks import wait_for_shutdown


def main() -> None:
    parser = argparse.ArgumentParser(description="OpenWhisk-TPU bus broker")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=4222)
    args = parser.parse_args()

    async def run():
        server = TcpBusServer(args.host, args.port)
        await server.start()
        print(f"bus broker listening on {args.host}:{args.port}", flush=True)
        try:
            await wait_for_shutdown()
        finally:
            await server.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()

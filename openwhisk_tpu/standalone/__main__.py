"""CLI: python -m openwhisk_tpu.standalone [--port 3233] [--db PATH]."""
from __future__ import annotations

import argparse
import asyncio

from . import GUEST_KEY, GUEST_UUID, make_standalone
from ..utils.config import honor_jax_platforms_env
from ..utils.tasks import wait_for_shutdown


def main() -> None:
    honor_jax_platforms_env()
    parser = argparse.ArgumentParser(description="Standalone OpenWhisk-TPU server")
    parser.add_argument("--port", type=int, default=3233)
    parser.add_argument("--db", type=str, default=None,
                        help="sqlite path for durable storage (default: in-memory)")
    parser.add_argument("--memory", type=int, default=2048,
                        help="invoker user memory (MB)")
    parser.add_argument("--prewarm", action="store_true",
                        help="start prewarm stem cells from the runtimes manifest")
    parser.add_argument("--balancer", choices=("lean", "tpu"), default="lean",
                        help="load balancer: lean (in-process) or tpu "
                             "(device placement kernel)")
    args = parser.parse_args()

    async def run():
        from ..utils.tracing import maybe_enable_zipkin
        zipkin = maybe_enable_zipkin("standalone")
        controller = None
        try:
            store = None
            if args.db:
                from ..database import open_store
                store = open_store(args.db)
            controller = await make_standalone(port=args.port,
                                               artifact_store=store,
                                               user_memory_mb=args.memory,
                                               prewarm=args.prewarm,
                                               balancer=args.balancer)
            print(f"OpenWhisk-TPU standalone listening on :{args.port} "
                  f"(balancer={args.balancer})")
            print(f"  AUTH     {GUEST_UUID}:{GUEST_KEY}")
            print(f"  API      http://127.0.0.1:{args.port}/api/v1")
            await wait_for_shutdown()
        finally:
            if controller is not None:
                await controller.stop()
            if zipkin is not None:
                await zipkin.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()

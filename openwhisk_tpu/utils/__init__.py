from .transaction import TransactionId, LogMarkerToken
from .logging import Logging, MetricEmitter, PrintLogging
from .semaphores import ForcibleSemaphore, ResizableSemaphore, NestedSemaphore
from .ring_buffer import RingBuffer
from .scheduler import Scheduler
from .config import config_from_env, load_config

__all__ = [
    "TransactionId", "LogMarkerToken", "Logging", "PrintLogging", "MetricEmitter",
    "ForcibleSemaphore", "ResizableSemaphore", "NestedSemaphore",
    "RingBuffer", "Scheduler", "config_from_env", "load_config",
]

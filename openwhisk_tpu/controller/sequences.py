"""Action sequences: chained invocation of component actions.

Rebuild of core/controller/.../actions/SequenceActions.scala:89-249 — a
sequence executes its components in order, each component's result becoming
the next component's payload; the sequence's own activation record
accumulates the component activation ids as logs, sums durations, and adopts
the last component's response (or the first failing one's — execution stops
at the first non-success, :150-249). Components carry `cause` = the sequence
activation id. Nested sequences count against `action_sequence_limit`.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ..core.entity import (ActivationId, ActivationResponse, Identity,
                           Parameters, WhiskAction, WhiskActivation)
from ..core.entity.parameters import ParameterValue
from ..database import NoDocumentException
from ..utils.transaction import TransactionId
from .invoke import ActionInvoker, InvokeOutcome, resolve_action


class TooManyActionsInSequence(Exception):
    pass


class SequenceInvoker:
    def __init__(self, entity_store, activation_store, action_invoker: ActionInvoker,
                 controller_instance, sequence_limit: int = 50,
                 conductor=None):
        self.entity_store = entity_store
        self.activation_store = activation_store
        self.invoker = action_invoker
        self.controller = controller_instance
        self.sequence_limit = sequence_limit
        self.conductor = conductor  # ConductorInvoker, wired by the controller

    async def invoke_sequence(self, identity: Identity, action: WhiskAction,
                              payload: Optional[Dict[str, Any]], blocking: bool,
                              transid: Optional[TransactionId] = None,
                              cause: Optional[ActivationId] = None,
                              components_budget: Optional[Dict[str, int]] = None
                              ) -> InvokeOutcome:
        """`components_budget` is a shared mutable {"left": n} so nested
        sequences deduct from the SAME budget — the reference threads
        atomicActionCnt through SequenceAccounting (SequenceActions.scala:
        248-281) for exactly this runaway-composition guard."""
        transid = transid or TransactionId()
        seq_aid = ActivationId.generate()
        budget = components_budget if components_budget is not None \
            else {"left": self.sequence_limit}
        start = time.time()
        current: Dict[str, Any] = dict(payload or {})
        component_ids = []
        response = ActivationResponse.success({})
        total_duration = 0

        for comp_fqn in action.exec.components:
            if budget["left"] <= 0:
                response = ActivationResponse.application_error(
                    "sequence composition is too long")
                break
            budget["left"] -= 1
            resolved = comp_fqn.resolve(str(identity.namespace.name))
            try:
                comp_action, pkg_params = await resolve_action(
                    self.entity_store, resolved, identity)
            except NoDocumentException:
                response = ActivationResponse.whisk_error(
                    f"Sequence component '{resolved}' does not exist.")
                break
            from .conductors import is_conductor
            if comp_action.is_sequence:
                outcome = await self.invoke_sequence(
                    identity, comp_action, current, blocking=True,
                    transid=transid, cause=seq_aid,
                    components_budget=budget)  # shared: nested use counts
            elif self.conductor is not None and is_conductor(comp_action):
                # conductor components drive the composition loop, sharing
                # this sequence's budget so nesting stays bounded
                outcome = await self.conductor.invoke_composition(
                    identity, comp_action, current, blocking=True,
                    transid=transid, cause=seq_aid,
                    package_params=pkg_params, budget=budget)
            else:
                outcome = await self.invoker.invoke(
                    identity, comp_action, pkg_params, current, blocking=True,
                    transid=transid, cause=seq_aid)
            if outcome.accepted or outcome.activation is None:
                response = ActivationResponse.whisk_error(
                    "Sequence component did not complete in time.")
                break
            activation = outcome.activation
            component_ids.append(activation.activation_id.asString)
            total_duration += activation.duration or 0
            response = activation.response
            if not activation.response.is_success:
                break  # stop at first failure (ref :150-249)
            current = activation.response.result if isinstance(
                activation.response.result, dict) else {}

        end = time.time()
        seq_activation = WhiskActivation(
            namespace=identity.namespace_path, name=action.name,
            subject=identity.subject, activation_id=seq_aid,
            start=start, end=end, response=response,
            logs=component_ids, duration=total_duration, cause=cause,
            version=action.version,
            annotations=Parameters({
                "topmost": ParameterValue(cause is None),
                "kind": ParameterValue("sequence"),
                "path": ParameterValue(str(action.fully_qualified_name)),
            }))
        await self.activation_store.store(seq_activation, context=identity)
        if blocking:
            return InvokeOutcome(seq_activation, seq_aid, accepted=False)
        return InvokeOutcome(None, seq_aid, accepted=True)

"""The JAX_PLATFORMS contract for spawned services (the round-3 flagship
hermeticity failure): some PJRT plugins register regardless of the env var,
so services apply it through the config API at boot
(utils.config.honor_jax_platforms_env). If this regresses, every
multi-controller chaos/deploy test starts contending for the one tunneled
TPU chip again."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_env_var_is_honored_through_config_api():
    """A fresh process with JAX_PLATFORMS=cpu must resolve the CPU backend
    after the boot hook — never an accelerator. (No unpinned variant: a
    subprocess without the pin would initialize and grab the one tunneled
    chip, recreating the exact contention this contract prevents.)"""
    code = (
        "from openwhisk_tpu.utils.config import honor_jax_platforms_env\n"
        "honor_jax_platforms_env()\n"
        "import jax\n"
        "print(jax.default_backend())\n")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert out.stdout.strip().splitlines()[-1] == "cpu", \
        "a service with JAX_PLATFORMS=cpu must never touch an accelerator"

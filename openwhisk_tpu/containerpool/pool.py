"""ContainerPool: warm/prewarm/cold scheduling within one invoker.

Behavioral rebuild of core/invoker/.../containerpool/ContainerPool.scala
(:59-216 receive, :219-245 warm matching, :306-326 buffering, :440-500
schedule/remove): the pool owns free/busy/prewarmed proxy sets and a FIFO
`run_buffer` for memory pressure. Scheduling order for a job:
  1. warm container initialized with the same (action@rev, namespace) that
     still has concurrency capacity,
  2. if memory allows: a prewarmed stem cell of matching (kind, memory),
  3. if memory allows: a cold container,
  4. evict idle warm containers (LRU) to make room, then 2/3,
  5. otherwise buffer the job until capacity frees up.
Prewarm pools are backfilled when stem cells are consumed (:backfillPrewarms).
"""
from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..core.entity import ExecutableWhiskAction, MB
from ..messaging.message import ActivationMessage
from ..utils.transaction import TransactionId
from ..utils.waterfall import GLOBAL_WATERFALL, STAGE_CONTAINER_ACQUIRE
from .factory import ContainerPoolConfig
from .proxy import ContainerProxy, PAUSED, PAUSING, READY

Job = Tuple[ExecutableWhiskAction, ActivationMessage]


class Run:
    """A scheduling request (ref ContainerProxy.Run message)."""

    __slots__ = ("action", "msg", "retry")

    def __init__(self, action: ExecutableWhiskAction, msg: ActivationMessage,
                 retry: bool = False):
        self.action = action
        self.msg = msg
        self.retry = retry


class ContainerPool:
    def __init__(self, proxy_factory: Callable[[], ContainerProxy],
                 config: ContainerPoolConfig, prewarm_config: Optional[List] = None,
                 logger=None, metrics=None):
        self.make_proxy = proxy_factory
        self.config = config
        self.prewarm_config = prewarm_config or []  # [(kind, image, memory_mb, count)]
        self.logger = logger
        self.metrics = metrics
        self.free: List[ContainerProxy] = []
        self.busy: List[ContainerProxy] = []
        self.prewarmed: List[ContainerProxy] = []
        self.prewarm_starting = 0
        self._prewarm_starting_mb = 0
        self.run_buffer: Deque[Run] = deque()
        self._tasks: set = set()
        self._shutdown = False

    # -- capacity accounting ----------------------------------------------
    def memory_consumption_mb(self) -> int:
        # includes in-flight prewarm starts (ref counts prewarmStartingPool)
        return (sum(p.data.memory_mb for p in self.free) +
                sum(p.data.memory_mb for p in self.busy) +
                sum(p.data.memory_mb for p in self.prewarmed) +
                self._prewarm_starting_mb)

    def has_pool_space(self, memory_mb: int) -> bool:
        return self.memory_consumption_mb() + memory_mb <= self.config.user_memory.to_mb

    # -- startup -----------------------------------------------------------
    async def start(self) -> None:
        """Start prewarm stem cells (ref ContainerPool init + backfill)."""
        awaitables = []
        for kind, image, memory_mb, count in self.prewarm_config:
            for _ in range(count):
                if self.has_pool_space(memory_mb):
                    awaitables.append(self._start_prewarm(kind, image, memory_mb))
        if awaitables:
            await asyncio.gather(*awaitables)

    async def _start_prewarm(self, kind: str, image: str, memory_mb: int) -> None:
        proxy = self._new_proxy()
        self.prewarm_starting += 1
        self._prewarm_starting_mb += memory_mb
        try:
            await proxy.prestart(kind, image, memory_mb)
        finally:
            self.prewarm_starting -= 1
            self._prewarm_starting_mb -= memory_mb
        if proxy.container is not None:
            self.prewarmed.append(proxy)

    # -- scheduling --------------------------------------------------------
    def run(self, job: Run) -> None:
        """Entry point from the invoker (non-blocking)."""
        # Preserve arrival order under memory pressure: new jobs go behind
        # the buffer (ref ContainerPool.scala:108-141).
        if self.run_buffer and not job.retry:
            self.run_buffer.append(job)
            return
        if not self._try_schedule(job):
            if job.retry:
                self.run_buffer.appendleft(job)
            else:
                self.run_buffer.append(job)
            self._emit_gauges()

    def _try_schedule(self, job: Run) -> bool:
        action, msg = job.action, job.msg
        memory_mb = action.limits.memory.megabytes
        max_concurrent = action.limits.concurrency.max_concurrent
        key = _job_key(action, msg)

        # 1. warm match with concurrency capacity (free first, then busy)
        proxy = self._warm_match(key, max_concurrent)
        # 2./3. prewarm or cold if space
        if proxy is None and self.has_pool_space(memory_mb):
            proxy = self._take_prewarm(action) or self._cold(action)
        # 4. evict idle warm containers, then retry
        if proxy is None:
            freed = self._evict_for(memory_mb)
            if freed and self.has_pool_space(memory_mb):
                proxy = self._take_prewarm(action) or self._cold(action)
        if proxy is None:
            return False

        if proxy in self.free:
            self.free.remove(proxy)
        if proxy not in self.busy:
            self.busy.append(proxy)
        # waterfall: a container (warm, prewarmed or cold shell) is now
        # committed to this activation — the acquire->run delta is the
        # cold-start / init cost the waterfall attributes to this stage
        GLOBAL_WATERFALL.stamp(msg.activation_id.asString,
                               STAGE_CONTAINER_ACQUIRE)
        self._spawn(proxy.run(action, msg))
        self._emit_gauges()
        return True

    def _warm_match(self, key: str, max_concurrent: int) -> Optional[ContainerProxy]:
        # idle warm containers first; with intra-container concurrency > 1 a
        # busy container with spare slots also matches (ref :219-231)
        for pool in (self.free, self.busy):
            for p in pool:
                if (not p._destroyed and p.data.action_id is not None and
                        f"{p.data.action_id}/{p.data.invocation_namespace}" == key and
                        p.active_count < max_concurrent):
                    return p
        return None

    def _take_prewarm(self, action: ExecutableWhiskAction) -> Optional[ContainerProxy]:
        kind = action.exec.kind
        memory_mb = action.limits.memory.megabytes
        for p in self.prewarmed:
            if p.data.kind == kind and p.data.memory_mb == memory_mb:
                self.prewarmed.remove(p)
                self._backfill_prewarm(kind, memory_mb)
                return p
        return None

    def _backfill_prewarm(self, kind: str, memory_mb: int) -> None:
        for k, image, mem, _count in self.prewarm_config:
            if k == kind and mem == memory_mb and self.has_pool_space(memory_mb):
                self._spawn(self._start_prewarm(k, image, mem))
                return

    def _cold(self, action: ExecutableWhiskAction) -> ContainerProxy:
        if self.metrics:
            self.metrics.counter("invoker_containerStart_cold_count")
        proxy = self._new_proxy()
        # account the job's memory from scheduling time, not from container
        # creation — concurrent cold starts must not overcommit the pool
        proxy.data.memory_mb = action.limits.memory.megabytes
        proxy.data.kind = action.exec.kind
        return proxy

    def _evict_for(self, memory_mb: int) -> bool:
        """LRU-evict idle free containers until memory_mb fits
        (ref ContainerPool.remove :440-477)."""
        evictable = sorted(
            [p for p in self.free if p.active_count == 0 and
             p.state in (READY, PAUSED, PAUSING)],
            key=lambda p: p.data.last_used)
        freed_any = False
        for p in evictable:
            if self.has_pool_space(memory_mb):
                break
            self.free.remove(p)
            self._spawn(p.halt())
            freed_any = True
        return freed_any

    # -- proxy callbacks ---------------------------------------------------
    def _need_work(self, proxy: ContainerProxy) -> None:
        """Container became idle/warm again (ref NeedWork)."""
        if proxy in self.busy:
            self.busy.remove(proxy)
        if proxy not in self.free and not proxy._destroyed:
            self.free.append(proxy)
        self._process_buffer()

    def _removed(self, proxy: ContainerProxy) -> None:
        for pool in (self.free, self.busy, self.prewarmed):
            if proxy in pool:
                pool.remove(proxy)
        self._process_buffer()

    def _reschedule(self, job: Job) -> None:
        action, msg = job
        self.run(Run(action, msg, retry=True))

    def _process_buffer(self) -> None:
        while self.run_buffer:
            job = self.run_buffer.popleft()
            if not self._try_schedule(job):
                self.run_buffer.appendleft(job)
                break
        self._emit_gauges()

    # -- helpers -----------------------------------------------------------
    def _new_proxy(self) -> ContainerProxy:
        proxy = self.make_proxy()
        proxy.on_need_work = self._need_work
        proxy.on_removed = self._removed
        proxy.on_reschedule = self._reschedule
        return proxy

    def _spawn(self, coro) -> None:
        t = asyncio.get_event_loop().create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    def _emit_gauges(self) -> None:
        if self.metrics:
            self.metrics.gauge("invoker_containerPool_free", len(self.free))
            self.metrics.gauge("invoker_containerPool_busy", len(self.busy))
            self.metrics.gauge("invoker_containerPool_prewarmed", len(self.prewarmed))
            self.metrics.gauge("invoker_containerPool_runBuffer", len(self.run_buffer))
            self.metrics.gauge("invoker_containerPool_memory_mb", self.memory_consumption_mb())

    async def shutdown(self) -> None:
        self._shutdown = True
        all_proxies = self.free + self.busy + self.prewarmed
        self.free, self.busy, self.prewarmed = [], [], []
        for p in all_proxies:
            try:
                await p.halt()
            except Exception:  # noqa: BLE001
                pass
        for t in list(self._tasks):
            t.cancel()


def _job_key(action: ExecutableWhiskAction, msg: ActivationMessage) -> str:
    rev = action.rev.rev or ""
    return f"{action.fully_qualified_name}@{rev}/{msg.user.namespace.name}"

"""Coalescing producer: micro-batched bus produce behind the provider SPI.

The publish->dispatch->invoke->complete path used to pay one bus round trip
per activation: the balancer's readback fan-out wakes N publishers in one
event-loop sweep and each `await producer.send(...)` serialized on the
transport (one lock-guarded TCP frame + ack per message on the TCP bus; one
condition acquire + notify per message on the memory bus). Under open-loop
load those per-request costs compound into the tail (PAPERS.md: Dean &
Barroso — the cure is doing less serial work per request, amortized over
batches).

`CoalescingProducer` wraps any `MessageProducer` and turns concurrent sends
into micro-batches: a send enqueues (payload pre-serialized on the caller's
turn) and resolves when its batch's single `send_many` acknowledges. The
flush fires when the batch fills (`max_batch`) or when the oldest pending
message has waited `window_ms` (a Nagle-style bounded delay; `window_ms=0`
flushes at the end of the current event-loop sweep, which still coalesces a
whole readback wave). Flushes are serialized on one drainer task, so
per-producer ordering is exactly the serial producer's.

Backends with a native batch op ship one frame per micro-batch
(`TcpProducer.send_many` -> the broker's `pubN` op: one length-prefixed
frame, N payloads, one ack, broker-side dedupe per sub-message); backends
without one fall back to the base `send_many` (sequential sends — serial
semantics, no wire-protocol change).

Off switch: `CONFIG_whisk_bus_coalesce_enabled=false` makes
`maybe_coalesce()` return the raw producer — the serial path, bit-exact
with today's behavior.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..utils.config import load_config
from ..utils.microbatch import MicroCoalescer
from .connector import MessageProducer, encode_message

#: process-wide coalescing health counters, exported as gauges by the
#: balancers' supervision tick (export_coalesce_gauges) — one aggregate
#: across producers, like the tracing gauges
_STATS = {"batches": 0, "messages": 0, "max_batch": 0}


@dataclass(frozen=True)
class BusCoalesceConfig:
    """`CONFIG_whisk_bus_coalesce_*` env overrides."""
    enabled: bool = True
    #: flush as soon as this many messages are pending
    max_batch: int = 64
    #: bounded accumulation delay: the oldest pending message waits at most
    #: this long before its frame ships. Default 0 = flush at the end of
    #: the current event-loop sweep, which already coalesces a whole
    #: readback/ack wave at ZERO added idle latency (measured: the produce
    #: stage p99 stays ~1 ms at the sustained rate). Set ~1 ms on expensive
    #: transports (remote TCP, Kafka) to also batch across waves.
    window_ms: float = 0.0

    @classmethod
    def from_env(cls) -> "BusCoalesceConfig":
        return load_config(cls, env_path="bus.coalesce")


class CoalescingProducer(MessageProducer):
    """Micro-batching wrapper over any MessageProducer (see module doc).
    The coalescing loop itself is the shared MicroCoalescer
    (utils/microbatch.py) — the admission plane rides the same one."""

    def __init__(self, inner: MessageProducer, max_batch: int = 64,
                 window_ms: float = 0.0):
        self.inner = inner
        self._co = MicroCoalescer(self._ship, max_batch,
                                  max(0.0, float(window_ms)) / 1e3,
                                  name="bus-coalesce-drain")

    @property
    def sent_count(self) -> int:
        return self.inner.sent_count

    @property
    def pending_count(self) -> int:
        return self._co.pending_count

    async def send(self, topic: str, msg) -> None:
        # serialize on the caller's turn: the flush loop then ships bytes
        # without touching message objects (and a slow .serialize() is
        # charged to the sender, not to every batch-mate). encode_message
        # also feeds the host observatory's per-hop serde accounting.
        payload = encode_message(msg)
        await self._co.submit((topic, payload, msg))

    async def _ship(self, batch) -> None:
        """One coalesced flush: the whole batch rides the provider's
        send_many (one pubN frame on the TCP bus). The coalescer resolves
        the waiter futures on return / failure."""
        _STATS["batches"] += 1
        _STATS["messages"] += len(batch)
        _STATS["max_batch"] = max(_STATS["max_batch"], len(batch))
        await self.inner.send_many([item for (item, _fut) in batch])

    async def flush(self) -> None:
        """Wait until everything enqueued so far has shipped (or failed)."""
        await self._co.drain_all()

    async def close(self) -> None:
        await self.flush()
        await self.inner.close()


def maybe_coalesce(producer: MessageProducer,
                   config: Optional[BusCoalesceConfig] = None
                   ) -> MessageProducer:
    """The wiring hook for producer owners (balancer, invoker, bench echo
    fleet): wrap in a CoalescingProducer when coalescing is on; hand back
    the raw producer — the bit-exact serial path — when it is off."""
    cfg = config if config is not None else BusCoalesceConfig.from_env()
    if not cfg.enabled or isinstance(producer, CoalescingProducer):
        return producer
    return CoalescingProducer(producer, cfg.max_batch, cfg.window_ms)


def export_coalesce_gauges(metrics) -> None:
    """Coalescing health gauges (ridden by the balancers' supervision tick,
    like export_tracing_gauges): flushed batch/message counts and the
    largest batch seen — messages/batches is the live amortization factor."""
    metrics.gauge("bus_coalesce_batches", _STATS["batches"])
    metrics.gauge("bus_coalesce_messages", _STATS["messages"])
    metrics.gauge("bus_coalesce_batch_max", _STATS["max_batch"])

"""LogStore SPI variants (ref core/containerpool/logging/): the log-driver
no-op store and the remote fetch-side stores (Elastic/Splunk equivalents)."""
import asyncio
import time

from openwhisk_tpu.containerpool.logstore import (ContainerLogStore,
                                                  ElasticSearchLogStore,
                                                  LogDriverLogStore,
                                                  SplunkLogStore)
from openwhisk_tpu.core.entity import (ActivationId, EntityName, EntityPath,
                                       Subject, WhiskActivation)
from openwhisk_tpu.standalone import guest_identity


def run(coro):
    return asyncio.run(coro)


def make_activation(logs=None):
    return WhiskActivation(EntityPath("guest"), EntityName("hello"),
                           Subject("guest-subject"), ActivationId.generate(),
                           start=time.time(), logs=logs)


class FakeHttp:
    """Injected transport capturing the request and replaying a response."""

    def __init__(self, response):
        self.response = response
        self.calls = []

    async def __call__(self, method, url, body, headers):
        self.calls.append((method, url, body, headers))
        return self.response


class TestLogStores:
    def test_default_store_fetch_reads_record(self):
        async def go():
            store = ContainerLogStore()
            act = make_activation(logs=["stdout: hi"])
            assert await store.fetch_logs(guest_identity(), act) == ["stdout: hi"]
        run(go())

    def test_log_driver_store_collects_nothing(self):
        async def go():
            store = LogDriverLogStore()
            assert await store.collect_logs(None, None, None, None, None) == []
            msg = await store.fetch_logs(guest_identity(), make_activation())
            assert "not available" in msg[0]
        run(go())

    def test_elasticsearch_fetch(self):
        async def go():
            act = make_activation()
            http = FakeHttp({"hits": {"hits": [
                {"_source": {"time_date": "2026-01-01T00:00:00Z",
                             "stream": "stdout", "message": "line one"}},
                {"_source": {"time_date": "2026-01-01T00:00:01Z",
                             "stream": "stderr", "message": "line two"}},
            ]}})
            store = ElasticSearchLogStore(http, "http://es:9200",
                                          index_pattern="logs-{uuid}")
            lines = await store.fetch_logs(guest_identity(), act)
            assert lines == ["2026-01-01T00:00:00Z stdout: line one",
                             "2026-01-01T00:00:01Z stderr: line two"]
            method, url, body, _ = http.calls[0]
            assert method == "POST" and url.endswith("/_search")
            # per-user index substitution (ref path schema with {uuid})
            assert guest_identity().namespace.uuid.asString in url
            assert body["query"]["term"]["activation_id"] == \
                act.activation_id.asString
            # collection is out-of-band
            assert await store.collect_logs(None, None, None, None, None) == []
        run(go())

    def test_splunk_fetch(self):
        async def go():
            act = make_activation()
            http = FakeHttp({"results": [{"log_message": "alpha"},
                                         {"log_message": "beta"}]})
            store = SplunkLogStore(http, "https://splunk:8089", index="wsk")
            lines = await store.fetch_logs(guest_identity(), act)
            assert lines == ["alpha", "beta"]
            _, url, body, _ = http.calls[0]
            assert url.endswith("/services/search/jobs")
            assert act.activation_id.asString in body["search"]
            assert "index=wsk" in body["search"]
        run(go())

"""On-device fleet telemetry + SLO burn-rate plane (ISSUE 2).

Covers: exact log2 bucket assignment and device/NumPy accumulator parity;
invoker-axis growth preserving counts; the namespace shared-overflow tail;
the TelemetryPlane's burn-rate windows, budget math and SLO report (incl.
per-namespace overrides); all three balancers feeding one telemetry surface
through the base-class hook; the `/admin/slo` endpoint (auth, JSON shape);
config off-switch; and the satellite fixes (readback RTT gauge, summary
quantile exposition, honest sliding-window percentiles, BufferReporter
drop counting).
"""
import asyncio
import base64
import time

import aiohttp
import numpy as np
import pytest

from openwhisk_tpu.controller.loadbalancer import (LeanBalancer,
                                                   ShardingBalancer,
                                                   SloConfig,
                                                   TelemetryConfig,
                                                   TelemetryPlane,
                                                   TpuBalancer)
from openwhisk_tpu.core.entity import (ControllerInstanceId, Identity,
                                       WhiskAuthRecord)
from openwhisk_tpu.messaging import MemoryMessagingProvider
from openwhisk_tpu.ops.telemetry import (DeviceLatencyAccumulator,
                                         NumpyLatencyAccumulator,
                                         OUTCOME_ERROR, OUTCOME_SUCCESS,
                                         OUTCOME_TIMEOUT, bucket_bounds_ms,
                                         bucket_of_us)
from tests.test_balancers import _fleet, _ping_all, make_action, make_msg


class TestBucketMath:
    def test_exact_log2_assignment(self):
        # bounds (ms): 1, 2, 4, 8, ... — a 4.000 ms sample must land in
        # le=4 exactly, never a neighbour via float rounding
        assert list(bucket_of_us([1, 1000, 1001, 2000, 4000, 4001], 8)) == \
            [0, 0, 1, 1, 2, 3]
        assert bucket_bounds_ms(6) == [1.0, 2.0, 4.0, 8.0, 16.0]

    def test_overflow_bucket(self):
        # past the last finite bound everything lands in the +Inf bucket
        b = bucket_of_us([10 ** 9], 8)
        assert b[0] == 7

    def test_device_matches_numpy(self):
        rows = [(1, 3, 4000, OUTCOME_SUCCESS), (1, 3, 5000, OUTCOME_ERROR),
                (5, 2, 100, OUTCOME_TIMEOUT), (0, 0, 2 ** 31 - 1,
                                               OUTCOME_SUCCESS)]
        ev = np.zeros((5, 8), np.int32)
        ev[:4, : len(rows)] = np.asarray(rows, np.int32).T
        ev[4, : len(rows)] = 1
        d = DeviceLatencyAccumulator(2, 16, 24)
        n = NumpyLatencyAccumulator(2, 16, 24)
        d.fold(ev)
        n.fold(ev)
        dc, nc = d.counts(), n.counts()
        for f in dc:
            assert np.allclose(dc[f], nc[f]), f

    def test_growth_preserves_counts(self):
        for acc in (NumpyLatencyAccumulator(2, 8, 8),
                    DeviceLatencyAccumulator(2, 8, 8)):
            ev = np.zeros((5, 8), np.int32)
            ev[:4, 0] = [1, 0, 3000, OUTCOME_SUCCESS]
            ev[4, 0] = 1
            acc.fold(ev)
            acc.ensure_invokers(9)   # -> 16 rows
            c = acc.counts()
            assert c["inv_buckets"].shape[0] == 16
            assert c["inv_buckets"][1, 2] == 1
            assert c["inv_outcomes"][1, OUTCOME_SUCCESS] == 1


class TestTelemetryPlane:
    def _plane(self, **slo):
        return TelemetryPlane(
            TelemetryConfig(buckets=10, namespaces=8,
                            shared_namespace_buckets=2),
            SloConfig(**slo))

    def test_ns_overflow_shared_tail(self):
        tp = self._plane()
        dedicated = tp.n_namespaces - tp.shared_tail
        slots = {f"ns{i}": tp._ns_slot(f"ns{i}") for i in range(12)}
        assert sorted(set(slots[f"ns{i}"] for i in range(dedicated))) == \
            list(range(dedicated))
        # overflow namespaces hash into the tail, never a dedicated row
        for i in range(dedicated, 12):
            assert slots[f"ns{i}"] >= dedicated
            assert tp._ns_label(slots[f"ns{i}"]).startswith("~shared")

    def test_slo_report_compliance_and_overrides(self):
        tp = self._plane(e2e_p99_ms=8.0, error_ratio=0.1,
                         overrides={"tenantB": {"e2e_p99_ms": 1.0}})
        for _ in range(99):
            tp.observe(0, "tenantA", 3.0, OUTCOME_SUCCESS)
        tp.observe(0, "tenantA", 900.0, OUTCOME_ERROR)
        for _ in range(10):
            tp.observe(1, "tenantB", 3.0, OUTCOME_SUCCESS)
        rep = tp.slo_report(["invoker0", "invoker1"])
        g = rep["global"]
        assert g["count"] == 110
        assert g["p99_le_ms"] == 4.0 and g["latency_compliant"] is True
        assert g["error_ratio_compliant"] is True and g["compliant"] is True
        by_ns = {n["namespace"]: n for n in rep["namespaces"]}
        # tenantB's override (1 ms) makes its 3 ms p99 non-compliant while
        # the global 8 ms target passes
        assert by_ns["tenantB"]["latency_target_ms"] == 1.0
        assert by_ns["tenantB"]["latency_compliant"] is False
        assert by_ns["tenantA"]["compliant"] is True
        by_inv = {i["invoker"]: i for i in rep["invokers"]}
        assert by_inv["invoker0"]["count"] == 100
        assert by_inv["invoker1"]["count"] == 10

    def test_target_judged_at_bucket_granularity(self):
        # a 1000 ms target with log2 bounds (…512, 1024…) is judged at
        # le=1024: a fleet whose p99 lands in that bucket (e.g. true p99
        # 600 ms) must NOT be flagged as violating
        tp = TelemetryPlane(TelemetryConfig(buckets=14, namespaces=8,
                                            shared_namespace_buckets=2),
                            SloConfig(e2e_p99_ms=1000.0))
        for _ in range(10):
            tp.observe(0, "ns", 600.0, OUTCOME_SUCCESS)
        g = tp.slo_report()["global"]
        assert g["p99_le_ms"] == 1024.0
        assert g["latency_target_le_ms"] == 1024.0
        assert g["latency_compliant"] is True

    def test_latency_in_overflow_bucket_is_noncompliant(self):
        tp = self._plane(e2e_p99_ms=10_000.0)
        # 10 buckets -> last finite bound 256 ms; p99 beyond it reports None
        for _ in range(10):
            tp.observe(0, "ns", 10_000.0, OUTCOME_SUCCESS)
        g = tp.slo_report()["global"]
        assert g["p99_le_ms"] is None
        assert g["latency_compliant"] is False

    def test_burn_rates_and_budget(self):
        tp = self._plane(error_ratio=0.1)
        t0 = time.monotonic()
        for _ in range(90):
            tp.observe(0, "ns", 1.0, OUTCOME_SUCCESS)
        for _ in range(10):
            tp.observe(0, "ns", 1.0, OUTCOME_ERROR)
        vals = tp.tick(now=t0 + 2.0)
        # 10% errors against a 10% target: burning exactly the budget
        assert vals["slo_burn_rate_1m"] == pytest.approx(1.0)
        assert vals["slo_error_budget_remaining"] == pytest.approx(0.0)
        # a clean follow-up minute decays the fast window to zero
        for _ in range(100):
            tp.observe(0, "ns", 1.0, OUTCOME_SUCCESS)
        vals = tp.tick(now=t0 + 100.0)
        assert vals["slo_burn_rate_1m"] == 0.0
        assert vals["slo_burn_rate_10m"] > 0.0  # slow window still sees them

    def test_disabled_plane_is_inert(self):
        tp = TelemetryPlane(TelemetryConfig(enabled=False))
        tp.observe(0, "ns", 1.0, OUTCOME_SUCCESS)
        assert tp.prometheus_text() == ""
        assert tp.slo_report() == {"enabled": False}
        assert tp.tick() == {}

    def test_from_env_config(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_telemetry_enabled", "false")
        monkeypatch.setenv("CONFIG_whisk_telemetry_buckets", "12")
        monkeypatch.setenv("CONFIG_whisk_slo_e2eP99Ms", "123")
        monkeypatch.setenv("CONFIG_whisk_slo_errorRatio", "0.005")
        monkeypatch.setenv("CONFIG_whisk_slo_overrides",
                           '{"guest": {"e2e_p99_ms": 9}}')
        tp = TelemetryPlane.from_config()
        assert tp.enabled is False
        assert tp.config.buckets == 12
        assert tp.slo.e2e_p99_ms == 123.0
        assert tp.slo.error_ratio == 0.005
        assert tp.slo.overrides["guest"]["e2e_p99_ms"] == 9


class TestBalancersFeedOneSurface:
    def test_tpu_balancer_device_accumulator(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("telem", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(6)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in msgs])
            await asyncio.sleep(0.3)
            bal.telemetry.device_fold()
            rep = bal.telemetry.slo_report(bal._telemetry_invoker_names())
            text = bal.metrics.prometheus_text()
            rtt = bal.metrics.gauge_value("loadbalancer_readback_rtt_ms")
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return rep, text, rtt

        rep, text, rtt = asyncio.run(go())
        assert rep["kernel"] == "device"
        assert rep["global"]["count"] == 6
        assert rep["global"]["outcomes"]["success"] == 6
        assert "openwhisk_invoker_activation_latency_seconds_bucket" in text
        assert 'le="+Inf"' in text
        assert 'outcome="success"' in text
        # satellite: the eager/batched dispatch regime is operator-visible
        assert rtt is not None and rtt > 0

    def test_sharding_balancer_numpy_twin(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = ShardingBalancer(provider, ControllerInstanceId("0"),
                                   managed_fraction=1.0,
                                   blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("telemcpu", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(4)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in msgs])
            await asyncio.sleep(0.2)
            rep = bal.telemetry.slo_report(bal._telemetry_invoker_names())
            text = bal.metrics.prometheus_text()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return rep, text

        rep, text = asyncio.run(go())
        assert rep["kernel"] == "cpu"
        assert rep["global"]["count"] == 4
        assert "openwhisk_namespace_activation_latency_seconds_count" in text

    def test_lean_balancer_and_timeout_outcome(self):
        async def go():
            provider = MemoryMessagingProvider()

            class _DummyInvoker:
                async def stop(self):
                    pass

            async def factory(invoker_id, messaging_provider):
                return _DummyInvoker()

            bal = LeanBalancer(provider, ControllerInstanceId("0"), factory)
            await bal.start()
            ident = Identity.generate("guest")
            action = make_action("leantelem", memory=128)
            m1 = make_msg(action, ident, False)
            m2 = make_msg(action, ident, False)
            await bal.publish(action, m1)
            await bal.publish(action, m2)
            # complete one regularly, force-timeout the other
            bal.process_completion(m1.activation_id, forced=False,
                                   is_system_error=False,
                                   invoker=bal.invoker_id)
            bal.process_completion(m2.activation_id, forced=True,
                                   is_system_error=False,
                                   invoker=bal.invoker_id)
            rep = bal.telemetry.slo_report(bal._telemetry_invoker_names())
            await bal.close()
            return rep

        rep = asyncio.run(go())
        g = rep["global"]
        assert g["count"] == 2
        assert g["outcomes"] == {"success": 1, "error": 0, "timeout": 1}
        # forced timeouts burn the error budget
        assert g["error_ratio"] == pytest.approx(0.5)
        assert rep["invokers"][0]["invoker"] == "invoker0"

    def test_disabled_telemetry_records_nothing(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            bal.telemetry.enabled = False
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("dark", memory=128)
            msg = make_msg(action, ident, True)
            await (await bal.publish(action, msg))
            await asyncio.sleep(0.2)
            rep = bal.telemetry.slo_report()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return rep

        assert asyncio.run(go()) == {"enabled": False}


PORT = 13378


class TestSloEndpoint:
    def _run(self, scenario):
        from openwhisk_tpu.controller.core import Controller

        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            controller = Controller(ControllerInstanceId("0"), provider,
                                    load_balancer=bal)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=PORT)
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            hdrs = {"Authorization": "Basic " + base64.b64encode(
                ident.authkey.compact.encode()).decode()}
            try:
                async with aiohttp.ClientSession() as s:
                    return await scenario(bal, ident, s, hdrs)
            finally:
                await controller.stop()
                for inv in invokers:
                    await inv.stop()

        return asyncio.run(go())

    def test_auth_required(self):
        async def scenario(bal, ident, s, hdrs):
            async with s.get(f"http://127.0.0.1:{PORT}/admin/slo") as r:
                return r.status

        assert self._run(scenario) == 401

    def test_report_shape_under_live_balancer(self):
        async def scenario(bal, ident, s, hdrs):
            action = make_action("sloseen", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(5)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in msgs])
            await asyncio.sleep(0.3)
            bal.telemetry.device_fold()
            async with s.get(f"http://127.0.0.1:{PORT}/admin/slo",
                             headers=hdrs) as r:
                return r.status, await r.json()

        status, rep = self._run(scenario)
        assert status == 200
        assert rep["enabled"] is True and rep["kernel"] == "device"
        assert {"targets", "windows_s", "buckets_le_ms", "global",
                "namespaces", "invokers"} <= set(rep)
        assert rep["global"]["count"] == 5
        assert rep["targets"]["e2e_p99_ms"] == 1000.0
        assert all(i["invoker"].startswith("invoker")
                   for i in rep["invokers"])


class TestSatellites:
    def test_summary_exposition_has_quantiles(self):
        from openwhisk_tpu.utils.logging import MetricEmitter
        m = MetricEmitter()
        for v in range(1, 101):
            m.histogram("loadbalancer_tpu_readback_ms", float(v))
            m.histogram("userevents_duration_ms", float(v),
                        tags={"action": "guest/a"})
        text = m.prometheus_text()
        assert ('openwhisk_loadbalancer_tpu_readback_ms'
                '{quantile="0.5"} ') in text
        assert ('openwhisk_loadbalancer_tpu_readback_ms'
                '{quantile="0.99"} ') in text
        # labelled series merge the quantile label into the label set
        assert ('openwhisk_userevents_duration_ms'
                '{action="guest/a",quantile="0.5"} ') in text
        assert "openwhisk_userevents_duration_ms_count{" in text

    def test_histogram_window_is_honest_sliding_window(self):
        from openwhisk_tpu.utils.logging import MetricEmitter
        m = MetricEmitter()
        n = MetricEmitter.WINDOW + 10
        for v in range(n):
            m.histogram("h", float(v))
        st = m.histogram_stats("h")
        assert st["count"] == n          # lifetime count
        # the window holds exactly the LAST `WINDOW` samples: the 10 oldest
        # were overwritten in arrival order by the write cursor
        window = m._hist[("h", ())][4]
        assert sorted(window)[0] == 10.0
        assert max(window) == float(n - 1)
        assert len(window) == MetricEmitter.WINDOW

    def test_closed_balancer_stops_rendering(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = ShardingBalancer(provider, ControllerInstanceId("0"),
                                   managed_fraction=1.0,
                                   blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 1)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("gone", memory=128)
            msg = make_msg(action, ident, True)
            await (await bal.publish(action, msg))
            await asyncio.sleep(0.2)
            before = bal.metrics.prometheus_text()
            await bal.close()
            after = bal.metrics.prometheus_text()
            for inv in invokers:
                await inv.stop()
            return before, after

        before, after = asyncio.run(go())
        fam = "openwhisk_invoker_activation_latency_seconds"
        assert fam in before
        # a closed balancer must not keep contributing families to a
        # shared emitter (duplicate TYPE lines are an invalid exposition)
        assert fam not in after

    def test_buffer_reporter_counts_drops(self):
        # ring retention (ISSUE 18): a full buffer evicts the OLDEST span
        # — the newest spans are the ones a debugging session wants, and
        # the old behavior (drop new, keep stale) made the buffer useless
        # after the first `max_spans` reports. sent counts every report
        # that reached the buffer; dropped counts the evictions.
        from openwhisk_tpu.utils.tracing import BufferReporter, Span
        rep = BufferReporter(max_spans=2)
        for i in range(5):
            rep.report(Span("t", f"s{i}", None, "op", 0.0, end=1.0))
        assert len(rep.spans) == 2
        assert [s.span_id for s in rep.spans] == ["s3", "s4"]
        assert rep.sent_spans == 5
        assert rep.dropped_spans == 3

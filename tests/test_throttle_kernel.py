"""Device token-bucket admission tests (ops.throttle)."""
import jax.numpy as jnp
import numpy as np

from openwhisk_tpu.ops.throttle import admit_batch, init_buckets


def test_burst_then_throttle_then_refill():
    st = init_buckets(4, rate_per_minute=60)  # 1 token/s, burst 60
    ns = jnp.zeros((64,), jnp.int32)
    valid = jnp.ones((64,), bool)
    st, admitted = admit_batch(st, jnp.float32(0.0), ns, valid)
    assert int(np.asarray(admitted).sum()) == 60  # burst drained
    st, admitted = admit_batch(st, jnp.float32(0.5), ns, valid)
    assert int(np.asarray(admitted).sum()) == 0   # no refill yet
    st, admitted = admit_batch(st, jnp.float32(10.5), ns, valid)
    assert int(np.asarray(admitted).sum()) == 10  # 10 s -> 10 tokens


def test_namespaces_isolated():
    st = init_buckets(2, rate_per_minute=120)
    ns = jnp.asarray([0] * 8 + [1] * 8, jnp.int32)
    st, admitted = admit_batch(st, jnp.float32(0.0), ns, jnp.ones((16,), bool))
    assert np.asarray(admitted).all()
    tokens = np.asarray(st.tokens)
    assert tokens[0] == tokens[1] == 120 - 8


def test_intra_batch_contention():
    st = init_buckets(1, rate_per_minute=60)
    # drain to 3 tokens
    st = st._replace(tokens=jnp.asarray([3.0], jnp.float32))
    ns = jnp.zeros((8,), jnp.int32)
    st, admitted = admit_batch(st, jnp.float32(0.0), ns, jnp.ones((8,), bool))
    a = np.asarray(admitted)
    assert a[:3].all() and not a[3:].any()  # first 3 in batch order win


def test_invalid_rows_ignored():
    st = init_buckets(1, rate_per_minute=60)
    ns = jnp.zeros((4,), jnp.int32)
    valid = jnp.asarray([True, False, True, False])
    st, admitted = admit_batch(st, jnp.float32(0.0), ns, valid)
    assert np.asarray(admitted).tolist() == [True, False, True, False]
    assert float(np.asarray(st.tokens)[0]) == 58.0


class TestDeviceAdmissionInBalancer:
    """r5: admit_batch fused into the TpuBalancer placement step
    (--balancer-rate-limit). Parity vs the entitlement RateThrottler's
    behavior: a burst up to the limit admits, the next request rejects
    with a throttle (429-mapped) error, and no capacity leaks."""

    def test_over_rate_publishes_throttled_and_leak_free(self):
        import asyncio

        import numpy as np

        from openwhisk_tpu.controller.loadbalancer import (
            LoadBalancerThrottleException, TpuBalancer)
        from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
        from openwhisk_tpu.messaging import MemoryMessagingProvider
        from tests.test_balancers import (_fleet, _ping_all, make_action,
                                          make_msg)

        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              batch_window=0.002, max_batch=16,
                              rate_limit_per_minute=5)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            free0 = np.asarray(bal.state.free_mb).copy()
            ident = Identity.generate("guest")
            action = make_action("ratelimited", memory=128)

            async def one():
                try:
                    p = await bal.publish(action,
                                          make_msg(action, ident, True))
                    await p
                    return "ok"
                except LoadBalancerThrottleException:
                    return "throttled"

            # a 12-deep burst against a 5/min bucket: exactly 5 admitted
            results = await asyncio.gather(*[one() for _ in range(12)])
            # drain releases so the books settle
            for _ in range(100):
                await asyncio.sleep(0.01)
                if (sum(bal._slots.refcount.values()) == 0
                        and (np.asarray(bal.state.free_mb) == free0).all()):
                    break
            leaked = sum(bal._slots.refcount.values())
            free_ok = (np.asarray(bal.state.free_mb) == free0).all()
            throttle_count = bal.metrics.counter_value(
                "loadbalancer_device_throttled")
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return results, leaked, free_ok, throttle_count

        results, leaked, free_ok, throttle_count = asyncio.run(go())
        assert results.count("ok") == 5
        assert results.count("throttled") == 7
        assert throttle_count == 7
        assert leaked == 0 and free_ok

    def test_overflow_namespaces_stay_in_shared_subrange(self):
        """Regression (ISSUE 1 satellite): once the dedicated rate buckets
        fill, overflow namespaces must hash into the RESERVED shared tail
        sub-range — never onto a dedicated tenant's bucket, where their
        traffic would drain that tenant's tokens."""
        from openwhisk_tpu.controller.loadbalancer import TpuBalancer
        from openwhisk_tpu.core.entity import ControllerInstanceId
        from openwhisk_tpu.messaging import MemoryMessagingProvider

        bal = TpuBalancer(MemoryMessagingProvider(),
                          ControllerInstanceId("0"),
                          rate_limit_per_minute=60)
        dedicated = bal.RATE_NS_BUCKETS - bal.RATE_NS_SHARED_BUCKETS
        for i in range(dedicated):
            assert bal._ns_slot(f"tenant{i}") == i  # dedicated, memoized
        # every overflow namespace lands in [dedicated, RATE_NS_BUCKETS)
        overflow_slots = {bal._ns_slot(f"overflow{i}") for i in range(500)}
        assert all(dedicated <= s < bal.RATE_NS_BUCKETS
                   for s in overflow_slots)
        # dedicated tenants keep their original buckets
        assert bal._ns_slot("tenant0") == 0
        assert bal._ns_slot(f"tenant{dedicated - 1}") == dedicated - 1

    def test_bucket_state_survives_rebuilds(self):
        """Regression (ISSUE 1 satellite): _build_packed_fns must CARRY the
        live token-bucket state through kernel swaps / growth rebuilds —
        re-initializing would grant a fresh full burst mid-minute."""
        import numpy as np

        from openwhisk_tpu.controller.loadbalancer import TpuBalancer
        from openwhisk_tpu.core.entity import ControllerInstanceId
        from openwhisk_tpu.messaging import MemoryMessagingProvider

        bal = TpuBalancer(MemoryMessagingProvider(),
                          ControllerInstanceId("0"),
                          rate_limit_per_minute=60)
        st = bal._bucket_state
        assert st is not None
        # drain the buckets, then force the rebuild paths
        bal._bucket_state = st._replace(tokens=st.tokens * 0.0)
        bal.update_cluster(2)            # _init_device_state -> rebuild
        assert float(np.asarray(bal._bucket_state.tokens).max()) == 0.0
        bal._use_xla_kernels()           # kernel swap -> rebuild
        assert float(np.asarray(bal._bucket_state.tokens).max()) == 0.0

    def test_refill_readmits_like_rate_window(self):
        """After the window passes, the budget returns (RateThrottler's
        rolling-minute behavior; the bucket refills continuously at
        limit/60 per second)."""
        import jax.numpy as jnp

        from openwhisk_tpu.ops.throttle import admit_batch, init_buckets

        st = init_buckets(4, rate_per_minute=6)  # 0.1 tokens/s
        ns = jnp.zeros((6,), jnp.int32)
        valid = jnp.ones((6,), bool)
        st, admitted = admit_batch(st, jnp.float32(0.0), ns, valid)
        assert admitted.all()  # burst == limit admits, like the window
        st, admitted = admit_batch(st, jnp.float32(1.0), ns, valid)
        assert not admitted.any()  # immediately after: rejected
        st, admitted = admit_batch(st, jnp.float32(61.0), ns, valid)
        assert admitted.all()  # a minute later the full budget is back

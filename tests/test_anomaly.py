"""On-device anomaly & straggler detection + alerting plane (ISSUE 4).

Covers: the jitted anomaly step vs its NumPy twin (parity); straggler
scoring flags exactly the slow invoker (min-samples gated); error-spike
z-tests against the EWMA baseline; the alert FSM's pending/for-duration/
firing/resolved lifecycle with transitions in the ring log; rule overrides
from CONFIG_whisk_alerts_rules; straggler injection end-to-end through a
live TpuBalancer (device accumulator + device detector, recovery included);
the advisory unhealthy hints; both admin endpoints (auth + shape); and the
disabled-plane true no-op.
"""
import asyncio
import base64
import time

import aiohttp
import numpy as np
import pytest

from openwhisk_tpu.controller.loadbalancer import (AlertsConfig,
                                                   AnomalyConfig,
                                                   AnomalyPlane,
                                                   ShardingBalancer,
                                                   TelemetryConfig,
                                                   TelemetryPlane,
                                                   TpuBalancer)
from openwhisk_tpu.controller.loadbalancer.anomaly import (AlertEngine,
                                                           AlertRule,
                                                           build_rules)
from openwhisk_tpu.controller.loadbalancer.supervision import InvokerPool
from openwhisk_tpu.core.entity import (ControllerInstanceId, Identity,
                                       WhiskAuthRecord)
from openwhisk_tpu.messaging import MemoryMessagingProvider
from openwhisk_tpu.ops.anomaly import (S_ANOMALY_FLAG, S_ERR_SPIKE,
                                       S_EWMA_MS, S_STRAGGLER,
                                       S_STRAGGLER_FLAG, S_TOTAL,
                                       anomaly_step_np, init_anomaly,
                                       init_anomaly_np, make_anomaly_step)
from openwhisk_tpu.ops.telemetry import (OUTCOME_ERROR, OUTCOME_SUCCESS,
                                         OUTCOME_TIMEOUT)
from tests.test_balancers import _fleet, _ping_all, make_action, make_msg

CFG = dict(alpha=0.3, z_threshold=3.5, spike_threshold=3.0, min_samples=8,
           mad_floor_ms=1.0)


def _telemetry(counts_ms):
    """Build cumulative telemetry arrays from per-invoker lists of
    (n_samples, mean_latency_ms, n_err, n_tm) accumulated so far."""
    n = len(counts_ms)
    buckets = np.zeros((n, 12), np.int64)
    lat = np.zeros((n,), np.float64)
    out = np.zeros((n, 3), np.int64)
    for i, (cnt, mean_ms, n_err, n_tm) in enumerate(counts_ms):
        b = min(11, max(0, int(np.ceil(np.log2(max(mean_ms, 1e-3))))))
        buckets[i, b] = cnt
        lat[i] = cnt * mean_ms
        out[i, OUTCOME_ERROR] = n_err
        out[i, OUTCOME_TIMEOUT] = n_tm
        out[i, OUTCOME_SUCCESS] = cnt - n_err - n_tm
    return buckets, lat, out


class TestKernelMath:
    def test_device_matches_numpy_twin(self):
        rng = np.random.RandomState(5)
        st_np = init_anomaly_np(8, 12)
        st_dev = init_anomaly(8, 12)
        step = make_anomaly_step(*CFG.values())
        cum = np.zeros((8, 4))
        for _ in range(4):
            cum[:, 0] += rng.randint(0, 30, 8)           # samples
            cum[:, 1] = rng.uniform(1, 50, 8)            # mean ms this tick
            cum[:, 2] += rng.randint(0, 3, 8)            # errors
            cum[:, 3] += rng.randint(0, 2, 8)            # timeouts
            rows = [(int(c[0]), float(c[1]), min(int(c[2]), int(c[0])),
                     min(int(c[3]), int(c[0]) - int(c[2])))
                    for c in cum]
            buckets, lat, out = _telemetry(rows)
            st_np, sc_np = anomaly_step_np(st_np, buckets, lat, out,
                                           *CFG.values())
            st_dev, sc_dev = step(st_dev, buckets.astype(np.int32),
                                  lat.astype(np.float32),
                                  out.astype(np.int32))
            assert np.allclose(np.asarray(sc_dev), sc_np,
                               rtol=1e-3, atol=1e-3)

    def test_straggler_flags_only_slow_invoker(self):
        st = init_anomaly_np(4, 12)
        buckets, lat, out = _telemetry([(20, 2.0, 0, 0), (20, 2.2, 0, 0),
                                        (20, 1.8, 0, 0), (20, 20.0, 0, 0)])
        st, sc = anomaly_step_np(st, buckets, lat, out, *CFG.values())
        assert list(sc[S_STRAGGLER_FLAG]) == [0.0, 0.0, 0.0, 1.0]
        assert sc[S_STRAGGLER, 3] > 3.5
        assert abs(sc[S_STRAGGLER, 0]) < 1.0  # fleet jitter never flags
        assert sc[S_EWMA_MS, 3] == pytest.approx(20.0)

    def test_min_samples_gates_flags(self):
        st = init_anomaly_np(4, 12)
        # the slow invoker has only 3 cumulative samples (< min_samples=8)
        buckets, lat, out = _telemetry([(20, 2.0, 0, 0), (20, 2.0, 0, 0),
                                        (20, 2.0, 0, 0), (3, 40.0, 0, 0)])
        st, sc = anomaly_step_np(st, buckets, lat, out, *CFG.values())
        assert sc[S_STRAGGLER, 3] > 3.5       # the score is visible
        assert sc[S_STRAGGLER_FLAG, 3] == 0.0  # but the flag is gated

    def test_error_spike_scores_burst_not_steady_floor(self):
        st = init_anomaly_np(2, 12)
        # three clean ticks build a clean baseline for invoker 0
        cnt = err = 0
        for _ in range(3):
            cnt += 30
            b, l, o = _telemetry([(cnt, 2.0, err, 0), (cnt, 2.0, 0, 0)])
            st, sc = anomaly_step_np(st, b, l, o, *CFG.values())
            assert sc[S_ERR_SPIKE, 0] == pytest.approx(0.0, abs=1e-6)
        # a burst: 15 of the next 30 completions error
        cnt += 30
        err += 15
        b, l, o = _telemetry([(cnt, 2.0, err, 0), (cnt, 2.0, 0, 0)])
        st, sc = anomaly_step_np(st, b, l, o, *CFG.values())
        assert sc[S_ERR_SPIKE, 0] > 3.0
        assert sc[S_ANOMALY_FLAG, 0] == 1.0
        assert sc[S_ERR_SPIKE, 1] == pytest.approx(0.0, abs=1e-6)

    def test_growth_pads_state(self):
        plane = AnomalyPlane(AnomalyConfig(), AlertsConfig())
        tp = TelemetryPlane(TelemetryConfig(namespaces=8,
                                            shared_namespace_buckets=2))
        plane.attach(telemetry=tp)
        tp.observe(1, "ns", 5.0, OUTCOME_SUCCESS)
        plane.tick(now=1.0)
        n0 = plane._scores.shape[1]
        tp.observe(n0 + 3, "ns", 5.0, OUTCOME_SUCCESS)  # grows the axis
        plane.tick(now=2.0)
        assert plane._scores.shape[1] > n0
        # the original invoker's EWMA survived the growth re-pad
        assert plane._scores[S_EWMA_MS, 1] == pytest.approx(5.0)


class TestAlertFSM:
    def _engine(self, for_s=5.0, threshold=3.0):
        rule = AlertRule("straggler", "straggler_score", threshold, for_s,
                         "warning", "invoker")
        return AlertEngine({"straggler": rule}), rule

    def _sig(self, value, name="invoker3"):
        return {"straggler": [((("invoker", name),), value)]}

    def test_pending_for_duration_firing_resolved(self):
        e, rule = self._engine(for_s=5.0)
        e.evaluate(100.0, self._sig(9.0))
        assert e.active(100.0)[0]["state"] == "pending"
        e.evaluate(103.0, self._sig(9.5))   # inside the for window
        assert e.active(103.0)[0]["state"] == "pending"
        e.evaluate(105.5, self._sig(9.5))   # for-duration elapsed
        act = e.active(105.5)
        assert act[0]["state"] == "firing"
        assert act[0]["labels"] == {"invoker": "invoker3"}
        assert e.firing_counts() == {("straggler", "warning"): 1}
        e.evaluate(110.0, self._sig(0.5))   # recovered
        assert e.active() == [] and e.firing_counts() == {}
        tos = [t["to"] for t in e.log.last(10)]
        assert tos == ["pending", "firing", "resolved"]
        assert e.transition_counts[("straggler", "firing")] == 1
        assert e.transition_counts[("straggler", "resolved")] == 1

    def test_zero_for_duration_fires_immediately(self):
        e, _ = self._engine(for_s=0.0)
        e.evaluate(1.0, self._sig(9.0))
        assert e.active()[0]["state"] == "firing"

    def test_pending_below_threshold_cancels(self):
        e, _ = self._engine(for_s=60.0)
        e.evaluate(1.0, self._sig(9.0))
        e.evaluate(2.0, self._sig(1.0))
        assert e.active() == []
        assert e.log.last(5)[-1]["to"] == "cancelled"

    def test_vanished_subject_resolves(self):
        e, _ = self._engine(for_s=0.0)
        e.evaluate(1.0, self._sig(9.0))
        assert e.firing_counts()
        e.evaluate(2.0, {"straggler": []})  # invoker left the fleet
        assert e.active() == []
        assert e.log.last(5)[-1]["to"] == "resolved"

    def test_rules_from_env(self, monkeypatch):
        monkeypatch.setenv(
            "CONFIG_whisk_alerts_rules",
            '{"straggler": {"threshold": 1.5, "for_s": 2, '
            '"severity": "critical"}, '
            '"timeout_spike": {"enabled": false}, '
            '"my_burn": {"signal": "burn_rate_1m", "threshold": 2.5}}')
        monkeypatch.setenv("CONFIG_whisk_anomaly_zThreshold", "2.0")
        plane = AnomalyPlane.from_config()
        assert plane.config.z_threshold == 2.0
        r = plane.engine.rules
        assert r["straggler"].threshold == 1.5
        assert r["straggler"].for_s == 2.0
        assert r["straggler"].severity == "critical"
        assert r["timeout_spike"].enabled is False
        assert r["my_burn"].signal == "burn_rate_1m" \
            and r["my_burn"].scope == "global"
        # untouched built-ins keep their defaults
        assert r["slo_fast_burn"].threshold == 14.4

    def test_builtin_thresholds_track_anomaly_config(self):
        # one knob: the kernel's flag gate and the built-in alert gate
        # must agree when the operator tunes the anomaly config
        rules = build_rules(None, anomaly=AnomalyConfig(
            z_threshold=2.5, spike_threshold=2.0))
        assert rules["straggler"].threshold == 2.5
        assert rules["error_spike"].threshold == 2.0
        assert rules["timeout_spike"].threshold == 2.0
        # an explicit alerts-rules override still wins over the derivation
        rules = build_rules({"straggler": {"threshold": 4.0}},
                            anomaly=AnomalyConfig(z_threshold=2.5))
        assert rules["straggler"].threshold == 4.0
        plane = AnomalyPlane(AnomalyConfig(z_threshold=2.5))
        assert plane.engine.rules["straggler"].threshold == 2.5

    def test_burn_rate_rule_rides_telemetry_windows(self):
        plane = AnomalyPlane(
            AnomalyConfig(),
            AlertsConfig(rules={"slo_fast_burn": {"for_s": 0}}))
        tp = TelemetryPlane(TelemetryConfig(namespaces=8,
                                            shared_namespace_buckets=2))
        plane.attach(telemetry=tp)
        for _ in range(50):
            tp.observe(0, "ns", 1.0, OUTCOME_ERROR)  # 100% errors
        plane.tick(now=time.monotonic())
        assert plane.engine.firing_counts().get(
            ("slo_fast_burn", "critical")) == 1

    def test_recompile_churn_rule(self):
        class FakeProf:
            enabled = True
            compiles_unexpected = 0

        plane = AnomalyPlane(AnomalyConfig(), AlertsConfig())
        prof = FakeProf()
        plane.attach(profiler=prof)
        t0 = time.monotonic()
        plane.tick(now=t0)
        assert plane.engine.firing_counts() == {}
        prof.compiles_unexpected = 3  # churn since last tick
        plane.tick(now=t0 + 1)
        assert plane.engine.firing_counts().get(
            ("recompile_churn", "warning")) == 1
        # churn ages out of the 60 s hold window -> resolved
        plane.tick(now=t0 + 120)
        assert plane.engine.firing_counts() == {}


class TestDisabledNoOp:
    def test_plane_is_inert(self):
        plane = AnomalyPlane(AnomalyConfig(enabled=False))
        tp = TelemetryPlane(TelemetryConfig(namespaces=8,
                                            shared_namespace_buckets=2))
        plane.attach(telemetry=tp)
        tp.observe(0, "ns", 500.0, OUTCOME_SUCCESS)
        assert plane.tick() == {}
        plane.maybe_tick()
        assert plane._state is None and plane._scores is None
        assert plane.prometheus_text() == ""
        assert plane.alerts_report() == {"enabled": False}
        assert plane.anomalies_report() == {"enabled": False}

    def test_env_off_switch_through_balancer(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_anomaly_enabled", "false")

        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("darkanom", memory=128)
            msg = make_msg(action, ident, True)
            await (await bal.publish(action, msg))
            await asyncio.sleep(0.2)
            bal.telemetry.device_fold()
            bal.anomaly.tick(bal.metrics)
            out = (bal.anomaly.enabled, bal.anomaly._state,
                   bal.anomaly.prometheus_text())
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return out

        enabled, state, text = asyncio.run(go())
        assert enabled is False and state is None and text == ""


class TestHints:
    def test_pool_surfaces_hints(self):
        pool = InvokerPool(MemoryMessagingProvider())
        from openwhisk_tpu.core.entity import InvokerInstanceId, MB
        pool.on_ping(InvokerInstanceId(0, user_memory=MB(512)))
        pool.set_unhealthy_hints({0: "straggler"})
        h = pool.health()
        assert h[0].hint == "straggler"
        assert h[0].to_json()["unhealthyHint"] == "straggler"
        # advisory only: status derivation is untouched
        assert h[0].status == "up"
        pool.set_unhealthy_hints({})
        assert pool.health()[0].hint is None

    def test_hint_sink_gated_by_config(self):
        for hint_on in (True, False):
            plane = AnomalyPlane(
                AnomalyConfig(min_samples=4, hint_unhealthy=hint_on),
                AlertsConfig(rules={"straggler": {"for_s": 0}}))
            tp = TelemetryPlane(TelemetryConfig(namespaces=8,
                                                shared_namespace_buckets=2))
            got = {}
            plane.attach(telemetry=tp,
                         invoker_names=lambda: [f"invoker{i}"
                                                for i in range(4)],
                         hint_sink=lambda h: got.update(h))
            for _ in range(10):
                for i in range(3):
                    tp.observe(i, "ns", 2.0, OUTCOME_SUCCESS)
                tp.observe(3, "ns", 50.0, OUTCOME_SUCCESS)
            plane.tick(now=time.monotonic())
            assert plane.hints == {3: "straggler"}
            assert (got == {3: "straggler"}) is hint_on


class TestStragglerEndToEnd:
    """The acceptance scenario: one invoker's completions delayed ~10x,
    through a live TpuBalancer (device accumulator + device detector)."""

    def test_flag_fire_recover(self):
        async def go():
            provider = MemoryMessagingProvider()
            plane = AnomalyPlane(
                AnomalyConfig(alpha=0.6, min_samples=6, mad_floor_ms=2.0),
                AlertsConfig(rules={"straggler": {"for_s": 0.3}}))
            # prewarm=False: the compile-ahead drainer runs XLA compiles on
            # a background thread DURING the measured rounds — on this
            # 2-core box the GIL hiccups inflate every in-flight e2e
            # sample, which the EWMAs then misread as fleet noise
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0,
                              anomaly=plane, prewarm=False)
            await bal.start()
            invokers, producer = await _fleet(provider, 4)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            actions = [make_action(f"e2e{i}", memory=128) for i in range(16)]

            async def round_trip():
                msgs = [(a, make_msg(a, ident, True)) for a in actions]
                promises = [await bal.publish(a, m) for a, m in msgs]
                await asyncio.gather(*promises)

            async def settle(n=5):
                # the device detector harvests one tick late, and the
                # straggler rule holds pending for its 0.3 s for-duration:
                # five 0.25 s ticks cover both with margin
                for _ in range(n):
                    bal.telemetry.device_fold()
                    plane.tick(bal.metrics)
                    await asyncio.sleep(0.25)

            # warm-up (same rationale as bench.py): the measured rounds'
            # release-bucket shapes jit-compile on first use, and an
            # in-dispatch compile stalls the loop long enough to inflate
            # every in-flight e2e sample — latencies the EWMAs would then
            # misread as fleet noise
            for _ in range(2):
                await round_trip()
            # 0.25 s vs sub-ms: under suite load the concurrent publish
            # gather inflates the "fast" invokers' e2e EWMAs to tens of
            # ms, so the separation must stay an order of magnitude above
            # that noise floor for the robust z to be deterministic. (Not
            # higher: 16 in-flight actions x 0.6 s once pushed a round
            # past the supervision silence window and took the fleet
            # offline mid-test.)
            from tools.loadgen import apply_stragglers
            assert apply_stragglers(invokers, "3:0.25") == {3: 0.25}
            for _ in range(4):
                await round_trip()
            await settle()
            rep1 = await asyncio.to_thread(
                plane.anomalies_report, bal._telemetry_invoker_names())
            alerts1 = plane.alerts_report()
            text1 = bal.metrics.prometheus_text()
            # recovery: the slow invoker speeds back up
            apply_stragglers(invokers, {3: 0.0})
            for _ in range(6):
                await round_trip()
                await settle(1)
            await settle()
            rep2 = await asyncio.to_thread(
                plane.anomalies_report, bal._telemetry_invoker_names())
            alerts2 = plane.alerts_report()
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return rep1, alerts1, text1, rep2, alerts2

        rep1, alerts1, text1, rep2, alerts2 = asyncio.run(go())

        # exactly the delayed invoker is flagged, with bucket evidence
        assert rep1["enabled"] is True and rep1["kernel"] == "device"
        flagged = [r for r in rep1["invokers"] if r["straggler"]]
        assert [r["invoker"] for r in flagged] == ["invoker3"]
        assert flagged[0]["straggler_score"] > 3.5
        assert flagged[0]["ewma_latency_ms"] > 10.0
        # every active invoker carries bucket-movement evidence fields
        assert all("evidence" in r for r in rep1["invokers"])

        # the straggler alert went pending -> firing for invoker3. Under
        # suite load a scheduler-starved HEALTHY invoker can blip its own
        # transient pending into the shared log, so the FSM sequence is
        # asserted on invoker3's transitions only (same noise tolerance as
        # the recovery phase below).
        trans = [t for t in alerts1["transitions"]
                 if t["alert"] == "straggler"
                 and t["labels"] == {"invoker": "invoker3"}]
        assert [t["to"] for t in trans[:2]] == ["pending", "firing"]
        assert any(a["alert"] == "straggler" and a["state"] == "firing"
                   and a.get("labels") == {"invoker": "invoker3"}
                   for a in alerts1["active"])

        # all three new families render on the shared /metrics page
        assert ("# TYPE openwhisk_loadbalancer_invoker_anomaly_score gauge"
                in text1)
        assert ('openwhisk_alerts_firing{alertname="straggler"'
                in text1)
        assert ('openwhisk_alert_transitions_total{alertname="straggler"'
                ',transition="firing"} 1') in text1

        # after recovery: the INJECTED straggler's flag cleared, its firing
        # alert resolved, and it is no longer active. Under suite load the
        # fleet median jitters a few ms, so a marginal re-breach
        # (pending -> cancelled) may trail the resolve in the log, and a
        # scheduler-starved HEALTHY invoker can blip a transient flag of
        # its own — invoker3's recovery is the contract, not a globally
        # quiet fleet.
        assert "invoker3" not in [r["invoker"] for r in rep2["invokers"]
                                  if r["straggler"]]
        targets2 = [t["to"] for t in alerts2["transitions"]
                    if t["alert"] == "straggler"
                    and t["labels"] == {"invoker": "invoker3"}]
        assert "resolved" in targets2[targets2.index("firing"):]
        assert not any(a["alert"] == "straggler"
                       and a.get("labels") == {"invoker": "invoker3"}
                       for a in alerts2["active"])


PORT = 13380


class TestAdminEndpoints:
    def _run(self, scenario):
        from openwhisk_tpu.controller.core import Controller

        async def go():
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            controller = Controller(ControllerInstanceId("0"), provider,
                                    load_balancer=bal)
            ident = Identity.generate("guest")
            await controller.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await controller.start(port=PORT)
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            hdrs = {"Authorization": "Basic " + base64.b64encode(
                ident.authkey.compact.encode()).decode()}
            try:
                async with aiohttp.ClientSession() as s:
                    return await scenario(bal, ident, s, hdrs)
            finally:
                await controller.stop()
                for inv in invokers:
                    await inv.stop()

        return asyncio.run(go())

    def test_auth_required(self):
        async def scenario(bal, ident, s, hdrs):
            out = []
            for path in ("/admin/alerts", "/admin/anomalies"):
                async with s.get(f"http://127.0.0.1:{PORT}{path}") as r:
                    out.append(r.status)
            return out

        assert self._run(scenario) == [401, 401]

    def test_report_shapes_under_live_balancer(self):
        async def scenario(bal, ident, s, hdrs):
            action = make_action("anomseen", memory=128)
            msgs = [make_msg(action, ident, True) for _ in range(6)]
            await asyncio.gather(*[await bal.publish(action, m)
                                   for m in msgs])
            await asyncio.sleep(0.3)
            bal.telemetry.device_fold()
            bal.anomaly.tick(bal.metrics)
            await asyncio.sleep(0.05)
            bal.anomaly.tick(bal.metrics)  # device path: harvest tick
            out = {}
            for name, path in (("alerts", "/admin/alerts?limit=5"),
                               ("anomalies", "/admin/anomalies")):
                async with s.get(f"http://127.0.0.1:{PORT}{path}",
                                 headers=hdrs) as r:
                    out[name] = (r.status, await r.json())
            return out

        out = self._run(scenario)
        status, alerts = out["alerts"]
        assert status == 200 and alerts["enabled"] is True
        rule_names = {r["name"] for r in alerts["rules"]}
        assert {"straggler", "error_spike", "slo_fast_burn",
                "slo_slow_burn", "recompile_churn"} <= rule_names
        assert {"active", "transitions", "transitions_dropped"} <= \
            set(alerts)
        status, anom = out["anomalies"]
        assert status == 200 and anom["enabled"] is True
        assert anom["kernel"] == "device"
        assert {"config", "fleet", "invokers"} <= set(anom)
        assert anom["invokers"], "active invokers must report scores"
        row = anom["invokers"][0]
        assert {"invoker", "straggler_score", "error_spike_score",
                "timeout_spike_score", "straggler", "anomalous",
                "ewma_latency_ms", "samples", "evidence"} <= set(row)

"""Diff two BENCH_*.json rounds mechanically.

ROADMAP house-keeping: the outstanding PR 9 claim (>5M placements/s for
`pallas_repair`, a sane `auto_pick` verdict) needs a clean device round,
and every round since r04 died on the dead-tunnel guard — when the next
clean round lands, it should be judged by a tool, not by eyeballing two
JSON blobs. This CLI prints a per-rider delta table between two rounds and
exits nonzero when any HEADLINE metric regressed by more than the
threshold (default 20%).

Usage (documented in docs/tpu-balancer.md):

    python tools/bench_compare.py BENCH_r04.json BENCH_r06.json
    python tools/bench_compare.py old.json new.json --threshold 10

Judgment rules:
  * Only the curated HEADLINES list gates the exit code; the delta table
    is informational and covers every shared numeric at the top two
    levels.
  * A metric missing (or null) on either side is SKIPPED and said so —
    a rider that failed to run is a different problem than a regression.
  * When the two rounds ran on different backends (`cpu_fallback`
    tagging, unchanged from PR 4), the comparison is ADVISORY: deltas
    print, the exit code stays 0, and the mismatch is named — a CPU
    number must never fail a device round or vice versa.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Tuple

#: (label, path into the round dict, direction). "higher" metrics regress
#: when the new value drops below old*(1-thr); "lower" metrics (latencies,
#: downtime) regress when the new value climbs above old*(1+thr); "zero"
#: metrics are correctness invariants — ANY nonzero new value regresses,
#: no threshold (a 0->1 jump has no percentage).
HEADLINES = (
    ("placements_per_sec", ("value",), "higher"),
    ("balancer_activations_per_sec",
     ("balancer", "activations_per_sec"), "higher"),
    ("e2e_sustained_per_sec",
     ("e2e_open_loop", "sustained_activations_per_sec"), "higher"),
    ("e2e_p99_ms", ("e2e_open_loop", "p99_ms"), "lower"),
    ("host_observatory_sustained_per_sec",
     ("host_observatory", "sustained_activations_per_sec"), "higher"),
    ("host_observatory_loop_lag_p99_ms",
     ("host_observatory", "loop_lag_p99_ms"), "lower"),
    # ISSUE 14: the two host-floor numbers the batched publish SPI and
    # the lazy ack result column are judged by
    ("host_observatory_serde_worst_hop_pct",
     ("host_observatory", "stage_shares", "serde_worst_hop_pct"), "lower"),
    ("host_observatory_tasks_per_activation",
     ("host_observatory", "stage_shares", "tasks_per_activation"), "lower"),
    ("e2e_fleet_mesh_sustained_per_sec",
     ("e2e_open_loop", "fleet_mesh_point", "sustained_activations_per_sec"),
     "higher"),
    ("bus_coalesced_msgs_per_sec",
     ("bus_coalesce_speedup", "coalesced_msgs_per_sec"), "higher"),
    ("failover_downtime_ms", ("failover_downtime", "downtime_ms"), "lower"),
    # ISSUE 15: active/active partitioned control under a mid-burst kill.
    # double_executions is the zero-double-execution CONTRACT, not a
    # perf number — any nonzero value fails the round outright.
    ("partition_chaos_downtime_s",
     ("partition_chaos", "downtime_s"), "lower"),
    ("partition_chaos_double_executions",
     ("partition_chaos", "double_executions"), "zero"),
    ("partition_chaos_absorbed_rate",
     ("partition_chaos", "absorbed_rate"), "higher"),
    # ISSUE 16: the reconstructed causal timeline decomposes the chaos
    # outage into named phases (their sum IS the timeline's downtime, so
    # a regression here names WHICH phase got slower); plus the --procs
    # fleet-merged generator headline
    ("partition_chaos_phase_detect_s",
     ("partition_chaos", "timeline", "phases", "detect_s"), "lower"),
    ("partition_chaos_phase_claim_s",
     ("partition_chaos", "timeline", "phases", "claim_s"), "lower"),
    ("partition_chaos_phase_absorb_s",
     ("partition_chaos", "timeline", "phases", "absorb_s"), "lower"),
    ("partition_chaos_phase_first_placement_s",
     ("partition_chaos", "timeline", "phases", "first_placement_s"),
     "lower"),
    ("fleet_merged_sustained_per_sec",
     ("e2e_open_loop", "multiproc_point", "fleet_merged_sustained_per_sec"),
     "higher"),
    # ISSUE 20: the SHARED multi-process deployment — front-end worker
    # processes funneling ONE balancer process over the TCP bus. The
    # merged-schedule sustained rate is a system number (topology
    # "shared"), unlike the twins-mode generator headline above; the
    # proc count rides along so a rate regression that came from a
    # smaller front-end ladder names itself.
    ("funnel_sustained_per_sec",
     ("funnel_10k", "funnel_sustained_per_sec"), "higher"),
    ("funnel_frontend_procs",
     ("funnel_10k", "funnel_frontend_procs"), "higher"),
    # ISSUE 17: placement quality under the straggler A/B — predicted
    # regret left on the table and how often the penalized shadow would
    # have placed differently (both lower-is-better), plus the plane's
    # <= 5% paired-overhead gate
    ("placement_regret_p99_ms",
     ("placement_quality", "straggler", "regret_p99_le_ms"), "lower"),
    ("shadow_divergence_ratio",
     ("placement_quality", "shadow_divergence_ratio"), "lower"),
    ("placement_quality_overhead_pct",
     ("placement_quality_overhead", "overhead_pct"), "lower"),
    # ISSUE 19: incident forensics — every acceptance plane must keep
    # landing in the bundle, the time-travel replay is a determinism
    # CONTRACT (any mismatch fails the round outright), and the armed
    # recorder rides the house paired-overhead gate
    ("incident_capture_planes",
     ("incident_capture", "planes_captured"), "higher"),
    ("incident_replay_mismatches",
     ("incident_capture", "replay_parity_mismatches"), "zero"),
    ("incident_overhead_pct",
     ("incident_overhead", "overhead_pct"), "lower"),
)


def unwrap_round(doc: dict) -> dict:
    """Accept either a bare bench.py JSON line or the driver's
    BENCH_r*.json envelope ({n, cmd, rc, tail}), whose `tail` holds the
    process output with the one JSON line somewhere in it (usually last).
    A dead round (rc!=0, no JSON line) unwraps to {} — every metric then
    reads as missing, which is the honest verdict."""
    if "value" in doc or "metric" in doc:
        return doc
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    inner = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(inner, dict):
                    return inner
        return {}
    return doc


def _get(doc: dict, path: Tuple[str, ...]):
    node = doc
    for p in path:
        if not isinstance(node, dict):
            return None
        node = node.get(p)
    return node if isinstance(node, (int, float)) and not isinstance(
        node, bool) else None


def _pct(old: float, new: float) -> Optional[float]:
    if not old:
        return None
    return 100.0 * (new - old) / old


def compare(old: dict, new: dict, threshold_pct: float = 20.0) -> dict:
    """Headline verdicts + the informational delta table. Pure function:
    the CLI below owns printing and the exit code."""
    backend_old = old.get("backend") or (old.get("balancer") or {}).get(
        "backend")
    backend_new = new.get("backend") or (new.get("balancer") or {}).get(
        "backend")
    # Advisory when the backends differ — OR when exactly one side is
    # tagged: rounds before r06 only tagged the backend on CPU fallback,
    # so an untagged old round is almost certainly a DEVICE round, and a
    # device-vs-CPU diff must never gate (a CPU number reading as a 99%
    # placements regression against TPU hardware is a category error,
    # not a regression). Rounds from r06 on are always tagged, so
    # same-backend comparisons keep their teeth.
    backend_mismatch = (backend_old != backend_new
                        and (backend_old is not None
                             or backend_new is not None))
    rows = []
    regressions = []
    for label, path, direction in HEADLINES:
        o, n = _get(old, path), _get(new, path)
        if o is None or n is None:
            rows.append({"metric": label, "old": o, "new": n,
                         "delta_pct": None, "verdict": "skipped (missing)"})
            continue
        delta = _pct(o, n)
        regressed = False
        if direction == "zero":
            regressed = n > 0
        elif delta is not None:
            if direction == "higher":
                regressed = n < o * (1.0 - threshold_pct / 100.0)
            else:
                regressed = n > o * (1.0 + threshold_pct / 100.0)
        verdict = "REGRESSED" if regressed else "ok"
        if regressed and backend_mismatch:
            verdict = "regressed (advisory: backend mismatch)"
        elif regressed:
            regressions.append(label)
        rows.append({"metric": label, "old": o, "new": n,
                     "delta_pct": round(delta, 1) if delta is not None
                     else None, "verdict": verdict})

    # informational table: every shared numeric at the top two levels
    deltas = []

    def walk(prefix, a, b, depth):
        for k in sorted(set(a) & set(b)):
            va, vb = a[k], b[k]
            name = f"{prefix}{k}"
            if isinstance(va, (int, float)) and not isinstance(va, bool) \
                    and isinstance(vb, (int, float)) \
                    and not isinstance(vb, bool):
                deltas.append((name, va, vb, _pct(va, vb)))
            elif isinstance(va, dict) and isinstance(vb, dict) and depth < 2:
                walk(name + ".", va, vb, depth + 1)

    walk("", old, new, 0)
    return {
        "headlines": rows,
        "regressions": regressions,
        "deltas": deltas,
        "backend_old": backend_old,
        "backend_new": backend_new,
        "backend_mismatch": backend_mismatch,
        "threshold_pct": threshold_pct,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("old", help="baseline BENCH_*.json")
    ap.add_argument("new", help="candidate BENCH_*.json")
    ap.add_argument("--threshold", type=float, default=20.0,
                    help="regression threshold in percent (default 20)")
    ap.add_argument("--full", action="store_true",
                    help="print the full two-level delta table, not just "
                         "the headline metrics")
    args = ap.parse_args()
    try:
        with open(args.old) as f:
            old = json.load(f)
        with open(args.new) as f:
            new = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_compare: cannot read rounds: {e}", file=sys.stderr)
        return 2
    if not isinstance(old, dict) or not isinstance(new, dict):
        print("bench_compare: rounds must be JSON objects", file=sys.stderr)
        return 2
    old, new = unwrap_round(old), unwrap_round(new)
    out = compare(old, new, args.threshold)
    # code provenance (ISSUE 19 satellite): bench.py stamps git_commit +
    # round label into `host`, so the diff names what code produced each
    # side even after branches moved on
    def _prov(doc):
        host = doc.get("host") or {}
        commit = host.get("git_commit") or "?"
        rnd = host.get("round")
        return f"{commit} (round {rnd})" if rnd else commit

    print(f"# old: {_prov(old)}  ->  new: {_prov(new)}")
    if out["backend_mismatch"]:
        print(f"# BACKEND MISMATCH: old={out['backend_old']} "
              f"new={out['backend_new']} — comparison is advisory, "
              "exit code stays 0")
    w = max(len(r["metric"]) for r in out["headlines"])
    print(f"{'metric':<{w}}  {'old':>12}  {'new':>12}  {'delta':>8}  verdict")
    for r in out["headlines"]:
        old_s = "-" if r["old"] is None else f"{r['old']:g}"
        new_s = "-" if r["new"] is None else f"{r['new']:g}"
        d = "-" if r["delta_pct"] is None else f"{r['delta_pct']:+.1f}%"
        print(f"{r['metric']:<{w}}  {old_s:>12}  {new_s:>12}  {d:>8}  "
              f"{r['verdict']}")
    if args.full and out["deltas"]:
        print("\n# full delta table (top two levels)")
        for name, o, n, d in out["deltas"]:
            ds = "-" if d is None else f"{d:+.1f}%"
            print(f"{name}  {o:g} -> {n:g}  ({ds})")
    if out["regressions"]:
        print(f"\nREGRESSION: {', '.join(out['regressions'])} moved more "
              f"than {args.threshold:g}% the wrong way", file=sys.stderr)
        return 1
    print(f"\nok: no headline metric regressed more than "
          f"{args.threshold:g}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())

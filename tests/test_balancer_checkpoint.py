"""Balancer checkpoint/resume lifecycle (SURVEY §5.4): periodic atomic
snapshots of the device capacity matrix + registry, restored at boot; every
failure path degrades to a cold start, never a boot abort."""
import asyncio
import json
import os

from openwhisk_tpu.controller.loadbalancer import ShardingBalancer, TpuBalancer
from openwhisk_tpu.controller.loadbalancer.checkpoint import (
    BalancerSnapshotter, load_snapshot, write_snapshot)
from openwhisk_tpu.core.entity import ControllerInstanceId, Identity
from openwhisk_tpu.messaging import MemoryMessagingProvider

from tests.test_balancers import _fleet, _ping_all, make_action, make_msg


def _balancer(provider, instance="0"):
    return TpuBalancer(provider, ControllerInstanceId(instance),
                       managed_fraction=1.0, blackbox_fraction=0.0)


class TestSnapshotRoundtrip:
    def test_write_restore_preserves_in_flight_books(self, tmp_path):
        path = str(tmp_path / "bal.snap")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            await bal.start()
            invokers, producer = await _fleet(provider, 4, delay=1.0)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("held", memory=256)
            promises = [await bal.publish(action, make_msg(action, ident, True))
                        for _ in range(4)]  # 4 in-flight holds
            write_snapshot(bal, path)

            cold = _balancer(provider, "1")
            assert load_snapshot(cold, path) is True
            import numpy as np
            same_free = np.array_equal(np.asarray(cold.state.free_mb),
                                       np.asarray(bal.state.free_mb))
            same_conc = np.array_equal(np.asarray(cold.state.conc_free),
                                       np.asarray(bal.state.conc_free))
            regs = [i.instance for i in cold._registry]
            await asyncio.gather(*[asyncio.wait_for(p, 5) for p in promises])
            await bal.close()
            await cold.close()
            for inv in invokers:
                await inv.stop()
            return same_free, same_conc, regs

        same_free, same_conc, regs = asyncio.run(go())
        assert same_free, "restored memory books must match (holds included)"
        assert same_conc, "restored concurrency books must match"
        assert regs == [0, 1, 2, 3]

    def test_missing_and_corrupt_snapshots_cold_start(self, tmp_path):
        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            assert load_snapshot(bal, str(tmp_path / "nope")) is False
            bad = tmp_path / "bad.snap"
            bad.write_text("{not json")
            assert load_snapshot(bal, str(bad)) is False
            # structurally-wrong JSON must not abort boot either
            ugly = tmp_path / "ugly.snap"
            ugly.write_text(json.dumps({"n_pad": "wat"}))
            assert load_snapshot(bal, str(ugly)) is False
            await bal.close()

        asyncio.run(go())

    def test_truncated_snapshot_rejected_cheaply(self, tmp_path):
        """ISSUE 9 satellite: a half-written file — whether it breaks the
        JSON or survives as valid-but-short JSON — is rejected by the
        version/crc32 envelope, not by an arbitrary exception inside
        restore()."""
        path = str(tmp_path / "torn.snap")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            write_snapshot(bal, path)
            raw = open(path).read()
            # torn write: drop the tail (broken JSON)
            open(path, "w").write(raw[: len(raw) // 2])
            cold = _balancer(provider, "1")
            torn_ok = load_snapshot(cold, path)
            # bit rot that KEEPS valid JSON: flip a payload value — only
            # the crc can catch this one
            doc = json.loads(raw)
            doc["free_mb"] = [v + 1 for v in doc["free_mb"]]
            json.dump(doc, open(path, "w"))
            rot_ok = load_snapshot(cold, path)
            await bal.close()
            await cold.close()
            for inv in invokers:
                await inv.stop()
            return torn_ok, rot_ok

        torn_ok, rot_ok = asyncio.run(go())
        assert torn_ok is False, "torn snapshot must cold-start"
        assert rot_ok is False, "crc-failing snapshot must cold-start"

    def test_stale_cluster_size_yields_to_topology(self, tmp_path):
        """A snapshot from a 1-controller deployment restored into a
        2-controller topology must re-shard to the OPERATOR's cluster size
        (holds reset, as on a live membership change), never double-book."""
        path = str(tmp_path / "stale.snap")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            write_snapshot(bal, path)  # cluster_size=1 inside
            cold = _balancer(provider, "1")
            assert load_snapshot(cold, path, cluster_size=2) is True
            import numpy as np
            shares = np.asarray(cold.state.free_mb)[:2]
            await bal.close()
            await cold.close()
            for inv in invokers:
                await inv.stop()
            return cold.cluster_size, shares.tolist()

        cs, shares = asyncio.run(go())
        assert cs == 2, "topology wins over the stale snapshot"
        assert shares == [1024, 1024], \
            "per-invoker share must be re-divided by the real cluster size"

    def test_non_checkpointable_balancer_noops(self, tmp_path):
        async def go():
            provider = MemoryMessagingProvider()
            bal = ShardingBalancer(provider, ControllerInstanceId("0"))
            assert not hasattr(bal, "restore")
            assert load_snapshot(bal, str(tmp_path / "x")) is False
            snap = BalancerSnapshotter(bal, str(tmp_path / "x"), 0.01).start()
            await asyncio.sleep(0.05)
            await snap.stop()
            assert not os.path.exists(tmp_path / "x")
            await bal.close()

        asyncio.run(go())


class TestSnapshotter:
    def test_periodic_and_final_dump(self, tmp_path):
        path = str(tmp_path / "periodic.snap")

        async def go():
            provider = MemoryMessagingProvider()
            bal = _balancer(provider)
            await bal.start()
            invokers, producer = await _fleet(provider, 2)
            await _ping_all(invokers, producer)
            snap = BalancerSnapshotter(bal, path, interval=0.05).start()
            for _ in range(100):
                if os.path.exists(path):
                    break
                await asyncio.sleep(0.02)
            periodic = os.path.exists(path)
            first = json.load(open(path)) if periodic else None
            # fleet grows; the FINAL dump at stop must capture it
            inv3, producer = await _fleet(provider, 4)
            await _ping_all(inv3, producer)
            await snap.stop()
            final = json.load(open(path))
            await bal.close()
            for inv in invokers + inv3:
                await inv.stop()
            return periodic, first, final

        periodic, first, final = asyncio.run(go())
        assert periodic, "periodic dump must appear"
        assert len(first["registry"]) >= 2
        assert len(final["registry"]) == 4, "final dump captures fleet growth"

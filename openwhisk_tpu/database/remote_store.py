"""Networked ArtifactStore: a framework-native shared document store.

The reference's multi-host persistence is CouchDB behind an HTTP client
(common/scala/.../database/CouchDbRestStore.scala:1-564 over
ArtifactStore.scala:41-150). This module is the equivalent without an
external database: `DocStoreServer` serves any backing ArtifactStore
(sqlite for durability, memory for tests) over the same length-prefixed
JSON framing the TCP bus uses (messaging/tcp.py), and
`RemoteArtifactStore` is the client implementing the full ArtifactStore
contract — so multi-host controllers and invokers share one entity /
activation database with revision semantics intact.

Protocol (4-byte big-endian length + JSON), one request per frame:
  {"op": "put", "rid": r, "id": i, "doc": {...}, "rev": v} -> {"rev": v'}
  {"op": "get", "id": i}                                   -> {"doc": {...}}
  {"op": "delete", "rid": r, "id": i, "rev": v}            -> {"ok": true}
  {"op": "query"/"count", ...view params}                  -> {"docs"/"n"}
  {"op": "attach"/"read_attachment"/"delete_attachments"}  -> ...
  errors                                    -> {"err": kind, "msg": text}

Mutating ops carry a client request id (`rid`); the server replays the
recorded response for a rid it has already applied, so a client retry
after a dropped TCP ack cannot double-apply a revision bump (the same
effectively-once trick the bus uses for publishes, messaging/tcp.py).
The rid cache is in-memory, so a retry across a server RESTART can still
re-dispatch; the client resolves that ambiguity itself (a retried put
answered with a conflict checks whether the stored body is its own; a
retried delete answered with no-document treats the delete as applied;
attach/delete_attachments are naturally idempotent).
"""
from __future__ import annotations

import asyncio
import base64
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..messaging.tcp import _frame, _read_frame
from .store import (ArtifactStore, ArtifactStoreException, DocumentConflict,
                    NoDocumentException, StaleParameter)

_ERR_TYPES = {
    "no_document": NoDocumentException,
    "conflict": DocumentConflict,
    "stale": StaleParameter,
    "internal": ArtifactStoreException,
}


def _err_kind(exc: Exception) -> str:
    if isinstance(exc, NoDocumentException):
        return "no_document"
    if isinstance(exc, DocumentConflict):
        return "conflict"
    if isinstance(exc, StaleParameter):
        return "stale"
    return "internal"


class DocStoreServer:
    """Serve a backing ArtifactStore to remote clients."""

    def __init__(self, backing: ArtifactStore, host: str = "127.0.0.1",
                 port: int = 4223):
        self.backing = backing
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._client_writers: set = set()
        # rid -> recorded response for applied mutations (retry dedupe)
        self._applied: "OrderedDict[str, dict]" = OrderedDict()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            for w in list(self._client_writers):
                w.close()
            await self._server.wait_closed()
        await self.backing.close()

    def _record(self, rid: Optional[str], resp: dict) -> dict:
        if rid is not None:
            self._applied[rid] = resp
            while len(self._applied) > 4096:
                self._applied.popitem(last=False)
        return resp

    async def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        rid = req.get("rid")
        if rid is not None and rid in self._applied:
            return self._applied[rid]
        b = self.backing
        if op == "put":
            rev = await b.put(req["id"], req["doc"], rev=req.get("rev"))
            return self._record(rid, {"rev": rev})
        if op == "get":
            return {"doc": await b.get(req["id"])}
        if op == "delete":
            ok = await b.delete(req["id"], rev=req.get("rev"))
            return self._record(rid, {"ok": bool(ok)})
        if op == "query":
            docs = await b.query(
                req["collection"], namespace=req.get("namespace"),
                name=req.get("name"), since=req.get("since"),
                upto=req.get("upto"), skip=int(req.get("skip", 0)),
                limit=int(req.get("limit", 0)),
                descending=bool(req.get("descending", True)))
            return {"docs": docs}
        if op == "count":
            n = await b.count(
                req["collection"], namespace=req.get("namespace"),
                name=req.get("name"), since=req.get("since"),
                upto=req.get("upto"))
            return {"n": n}
        if op == "attach":
            await b.attach(req["id"], req["name"], req["content_type"],
                           base64.b64decode(req["data"]))
            return self._record(rid, {"ok": True})
        if op == "read_attachment":
            ct, data = await b.read_attachment(req["id"], req["name"])
            return {"content_type": ct,
                    "data": base64.b64encode(data).decode()}
        if op == "delete_attachments":
            await b.delete_attachments(req["id"],
                                       except_name=req.get("except_name"))
            return self._record(rid, {"ok": True})
        if op == "ping":
            return {"ok": True}
        return {"err": "internal", "msg": f"unknown op {op!r}"}

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._client_writers.add(writer)
        try:
            while True:
                req = await _read_frame(reader)
                if req is None:
                    break
                try:
                    resp = await self._dispatch(req)
                except ArtifactStoreException as e:
                    resp = {"err": _err_kind(e), "msg": str(e)}
                except Exception as e:  # noqa: BLE001 — server must not die
                    resp = {"err": "internal", "msg": f"{type(e).__name__}: {e}"}
                writer.write(_frame(resp))
                await writer.drain()
        finally:
            self._client_writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass


class _PooledConnection:
    """One TCP connection with reconnect-and-retry (safe: mutations carry
    rids the server dedupes on)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None

    async def request(self, obj: dict):
        """Returns (response, retried): `retried` means the frame may have
        been applied by the server even though the first response was lost
        — callers resolve the ambiguity for non-idempotent ops."""
        for attempt in (1, 2):
            if self.writer is None or self.writer.is_closing():
                self.reader, self.writer = await asyncio.open_connection(
                    self.host, self.port)
            try:
                self.writer.write(_frame(obj))
                await self.writer.drain()
                resp = await _read_frame(self.reader)
                if resp is not None:
                    return resp, attempt > 1
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass
            self.writer.close()  # dead transport: release the fd now
            self.writer = None
        raise ConnectionError(
            f"docstore at {self.host}:{self.port} unreachable")

    async def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except (ConnectionResetError, OSError):
                pass
            self.writer = None


class RemoteArtifactStore(ArtifactStore):
    """ArtifactStore client talking to a DocStoreServer.

    Requests multiplex over a small connection pool so concurrent control-
    plane DB ops (entity fetch on the invoke path, activation writes, list
    queries) don't serialize behind one socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 4223,
                 pool_size: int = 8):
        self.host = host
        self.port = port
        self.pool_size = pool_size
        self._free: List[_PooledConnection] = []
        self._total = 0
        self._waiter = asyncio.Condition()

    async def _acquire(self) -> _PooledConnection:
        async with self._waiter:
            while True:
                if self._free:
                    return self._free.pop()
                if self._total < self.pool_size:
                    self._total += 1
                    return _PooledConnection(self.host, self.port)
                await self._waiter.wait()

    async def _release(self, conn: _PooledConnection) -> None:
        async with self._waiter:
            self._free.append(conn)
            self._waiter.notify()

    async def _request(self, obj: dict) -> dict:
        conn = await self._acquire()
        try:
            resp, retried = await conn.request(obj)
        except BaseException:
            await conn.close()
            async with self._waiter:
                self._total -= 1
                self._waiter.notify()
            raise
        await self._release(conn)
        err = resp.get("err")
        if err is not None:
            exc = _ERR_TYPES.get(err, ArtifactStoreException)(
                resp.get("msg", err))
            # the server's in-memory rid dedupe covers same-life retries;
            # after a server RESTART a retried mutation may have applied
            # before the crash ate its ack — callers use this to resolve
            exc.retried = retried
            raise exc
        return resp

    # -- CRUD --------------------------------------------------------------
    async def put(self, doc_id: str, doc: Dict[str, Any],
                  rev: Optional[str] = None) -> str:
        try:
            resp = await self._request({"op": "put", "rid": uuid.uuid4().hex,
                                        "id": doc_id, "doc": doc, "rev": rev})
            return resp["rev"]
        except DocumentConflict as e:
            if not getattr(e, "retried", False):
                raise
            # ambiguous: our first frame may have applied before the server
            # died. If the stored body IS our body, our write won — return
            # its revision; otherwise it is a genuine conflict.
            stored = await self.get(doc_id)
            body = {k: v for k, v in stored.items() if not k.startswith("_")}
            if body == doc:
                return stored["_rev"]
            raise

    async def get(self, doc_id: str) -> Dict[str, Any]:
        return (await self._request({"op": "get", "id": doc_id}))["doc"]

    async def delete(self, doc_id: str, rev: Optional[str] = None) -> bool:
        try:
            resp = await self._request({"op": "delete",
                                        "rid": uuid.uuid4().hex,
                                        "id": doc_id, "rev": rev})
            return bool(resp["ok"])
        except NoDocumentException as e:
            # ambiguous only when the frame was retried across a server
            # restart: our first attempt likely deleted it already
            if getattr(e, "retried", False):
                return True
            raise

    # -- views -------------------------------------------------------------
    async def query(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None,
                    skip: int = 0, limit: int = 0,
                    descending: bool = True) -> List[Dict[str, Any]]:
        resp = await self._request({
            "op": "query", "collection": collection, "namespace": namespace,
            "name": name, "since": since, "upto": upto, "skip": skip,
            "limit": limit, "descending": descending})
        return resp["docs"]

    async def count(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None
                    ) -> int:
        resp = await self._request({
            "op": "count", "collection": collection, "namespace": namespace,
            "name": name, "since": since, "upto": upto})
        return int(resp["n"])

    # -- attachments -------------------------------------------------------
    async def attach(self, doc_id: str, name: str, content_type: str,
                     data: bytes) -> None:
        if self.attachment_store is not None:
            return await self.attachment_store.attach(doc_id, name,
                                                      content_type, data)
        await self._request({"op": "attach", "rid": uuid.uuid4().hex,
                             "id": doc_id, "name": name,
                             "content_type": content_type,
                             "data": base64.b64encode(data).decode()})

    async def read_attachment(self, doc_id: str, name: str) -> Tuple[str, bytes]:
        if self.attachment_store is not None:
            return await self.attachment_store.read_attachment(doc_id, name)
        resp = await self._request({"op": "read_attachment", "id": doc_id,
                                    "name": name})
        return resp["content_type"], base64.b64decode(resp["data"])

    async def delete_attachments(self, doc_id: str,
                                 except_name: Optional[str] = None) -> None:
        if self.attachment_store is not None:
            return await self.attachment_store.delete_attachments(
                doc_id, except_name=except_name)
        await self._request({"op": "delete_attachments",
                             "rid": uuid.uuid4().hex, "id": doc_id,
                             "except_name": except_name})

    async def ping(self) -> bool:
        try:
            return bool((await self._request({"op": "ping"})).get("ok"))
        except (ConnectionError, OSError):
            return False

    async def close(self) -> None:
        await super().close()
        async with self._waiter:
            conns, self._free, self._total = self._free, [], 0
        for c in conns:
            await c.close()


class RemoteArtifactStoreProvider:
    @staticmethod
    def make_store(host: str = "127.0.0.1", port: int = 4223, **kwargs
                   ) -> RemoteArtifactStore:
        return RemoteArtifactStore(host, port)


def open_store(db: str) -> ArtifactStore:
    """Resolve a --db argument: `docstore://host:port` connects to a shared
    DocStoreServer; `couchdb://host:port/dbname` (or couchdbs:// for TLS)
    connects to a CouchDB server; `cosmos://KEY@host:port/db/container`
    (cosmoss:// for TLS; KEY percent-encoded base64 master key) connects
    to an Azure Cosmos DB SQL-API account or emulator; anything else is a
    local sqlite path."""
    if db.startswith("docstore://"):
        hostport = db[len("docstore://"):]
        host, _, port = hostport.rpartition(":")
        return RemoteArtifactStore(host or "127.0.0.1", int(port))
    if db.startswith(("couchdb://", "couchdbs://")):
        from urllib.parse import unquote, urlsplit

        from .couchdb_store import CouchDbArtifactStore
        parts = urlsplit(db)
        scheme = "https" if parts.scheme == "couchdbs" else "http"
        host = parts.hostname or "127.0.0.1"
        port = parts.port or (6984 if scheme == "https" else 5984)
        # urlsplit does NOT percent-decode userinfo; credentials with
        # reserved chars (@ : /) arrive encoded and must be restored
        return CouchDbArtifactStore(
            f"{scheme}://{host}:{port}",
            db=(parts.path.strip("/") or "whisks"),
            username=unquote(parts.username) if parts.username else None,
            password=unquote(parts.password) if parts.password else None)
    if db.startswith(("cosmos://", "cosmoss://")):
        from urllib.parse import unquote, urlsplit

        from .cosmosdb_store import CosmosDbArtifactStore
        parts = urlsplit(db)
        scheme = "https" if parts.scheme == "cosmoss" else "http"
        if not parts.username:
            raise ValueError(
                "cosmos:// needs the master key as userinfo: "
                "cosmos://KEY@host:port/db/container")
        segs = [s for s in parts.path.split("/") if s]
        return CosmosDbArtifactStore(
            f"{scheme}://{parts.hostname or '127.0.0.1'}:{parts.port or 8081}",
            key=unquote(parts.username),
            db=segs[0] if segs else "whisks",
            container=segs[1] if len(segs) > 1 else "whisks")
    from .sqlite_store import SqliteArtifactStore
    return SqliteArtifactStore(db)


def main(argv: Optional[List[str]] = None) -> None:
    """CLI: run a doc-store server over a durable sqlite backing.

      python -m openwhisk_tpu.database.remote_store \
          --db /path/whisks.db --host 0.0.0.0 --port 4223
    """
    import argparse

    parser = argparse.ArgumentParser(prog="owdocstore")
    parser.add_argument("--db", required=True, help="sqlite backing path")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=4223)
    args = parser.parse_args(argv)

    async def run() -> None:
        from .sqlite_store import SqliteArtifactStore
        server = DocStoreServer(SqliteArtifactStore(args.db),
                                host=args.host, port=args.port)
        await server.start()
        print(f"docstore up on {args.host}:{args.port} (db={args.db})",
              flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await server.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()

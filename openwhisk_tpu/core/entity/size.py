"""Byte sizes (ref common/scala/.../core/entity/size.scala).

Parses/renders the reference's wire format ("256 MB", "10485760 B") and
supports the arithmetic the capacity model needs (MB-quantized permits).
"""
from __future__ import annotations

import re
from functools import total_ordering

_UNITS = {"B": 1, "KB": 1024, "MB": 1024**2, "GB": 1024**3, "TB": 1024**4}
_RX = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([KMGT]?B)?\s*$", re.IGNORECASE)


@total_ordering
class ByteSize:
    __slots__ = ("bytes",)

    def __init__(self, size: float, unit: str = "B"):
        u = unit.upper()
        if u not in _UNITS:
            raise ValueError(f"unknown size unit {unit!r}")
        self.bytes = int(size * _UNITS[u])

    @classmethod
    def from_string(cls, s: str) -> "ByteSize":
        m = _RX.match(s)
        if not m:
            raise ValueError(f"invalid size string {s!r} (want e.g. '256 MB')")
        return cls(float(m.group(1)), (m.group(2) or "B"))

    @property
    def to_kb(self) -> int:
        return self.bytes // 1024

    @property
    def to_mb(self) -> int:
        return self.bytes // (1024**2)

    def __add__(self, other: "ByteSize") -> "ByteSize":
        return ByteSize(self.bytes + other.bytes)

    def __sub__(self, other: "ByteSize") -> "ByteSize":
        return ByteSize(self.bytes - other.bytes)

    def __mul__(self, k) -> "ByteSize":
        return ByteSize(int(self.bytes * k))

    def __eq__(self, other) -> bool:
        return isinstance(other, ByteSize) and self.bytes == other.bytes

    def __lt__(self, other: "ByteSize") -> bool:
        return self.bytes < other.bytes

    def __hash__(self) -> int:
        return hash(self.bytes)

    def __repr__(self) -> str:
        for unit in ("TB", "GB", "MB", "KB"):
            if self.bytes and self.bytes % _UNITS[unit] == 0:
                return f"{self.bytes // _UNITS[unit]} {unit}"
        return f"{self.bytes} B"

    def to_json(self) -> str:
        return repr(self)

    @classmethod
    def from_json(cls, j) -> "ByteSize":
        if isinstance(j, (int, float)):
            return cls(int(j))
        return cls.from_string(str(j))


def MB(n: float) -> ByteSize:
    return ByteSize(n, "MB")


def KB(n: float) -> ByteSize:
    return ByteSize(n, "KB")


def B(n: float) -> ByteSize:
    return ByteSize(n, "B")


def GB(n: float) -> ByteSize:
    return ByteSize(n, "GB")

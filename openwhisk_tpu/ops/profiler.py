"""Kernel profiling observatory: the compiled data path, observed.

The flight recorder (PR 1) explains *where* a placement went and the
telemetry plane (PR 2) says *whether* the fleet meets its SLOs — but the
fused TPU program itself was still a black box: an unexpected recompile
(shape churn, a growth event) silently costs hundreds of milliseconds of
placement latency, and nothing in the stack could report why the device
path degraded. This module closes that gap with four host-side surfaces,
all wired through the balancer base-class hook so the CPU-twin balancers
(sharding, lean) report through the same plane with a `kernel: "cpu"`
profile:

  1. **Compile tracking** — `wrap(name, fn)` interposes on a jitted entry
     point and detects compile events by jit-cache-key signature (shapes +
     dtypes of array args, values of static scalars: exactly what keys the
     XLA cache). Each event records wall time and a classification:
     *expected* (first call, a growth/swap event the balancer flagged via
     `expect(reason)`, or a signature the entry's `expected` predicate
     blesses — the power-of-two batch buckets) or *unexpected* shape
     churn. Churn trips the recompile watchdog: a structured warning and a
     `loadbalancer_kernel_recompiles_total{expected="false"}` bump.
  2. **Per-phase device timing** — `observe_phase` folds the dispatch
     cycle's assembly/dispatch/readback/fanout millis into log2 bucket
     counts rendered as a real Prometheus histogram family
     (`loadbalancer_phase_duration_seconds{phase=...}`) via the
     `MetricEmitter.register_renderer` hook, plus a per-phase sliding
     window for p50/p99 rollups on the admin surface.
  3. **HBM watermarks** — `refresh_memory` reads `device.memory_stats()`
     (guarded: a no-op on backends without it, e.g. CPU) into
     `loadbalancer_hbm_*` gauges on the supervision tick, keeping a
     high-watermark across ticks even when the backend reports no peak.
  4. **The capture plane** — `arm_capture(n)` records the next n dispatch
     steps at full detail (optionally wrapping `jax.profiler.trace` into a
     server-side directory when the real profiler is importable), and
     `admit_batch` implements tail sampling: with a threshold configured,
     full per-decision flight-recorder rows are kept only for batches
     slower than it — deep detail gets cheaper, not pricier, at scale.

Hot-path budget: with profiling disabled, `wrap` returns the function
unchanged and every other entry point returns before allocating — a true
no-op (asserted by tier-1). Enabled, the steady-state cost per dispatch is
one signature tuple + dict hit per wrapped call and one bucket increment
per phase; everything else (classification, logging, capture) runs only on
the rare compile/capture events. Off-switch: `CONFIG_whisk_profiling_*`.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.config import load_config
from ..utils.ring_buffer import SeqRingBuffer

#: phase-duration bucket upper bounds, ms: 1/16 ms .. ~8.2 s, log2-spaced
#: (assembly runs tens of microseconds; a tunneled readback runs ~100 ms)
PHASE_BOUNDS_MS: List[float] = [2.0 ** e for e in range(-4, 14)]
_PHASE_BOUNDS = np.asarray(PHASE_BOUNDS_MS, np.float64)


@dataclass(frozen=True)
class ProfilingConfig:
    """`CONFIG_whisk_profiling_*` env overrides."""
    enabled: bool = True
    #: compile events kept in the log ring
    compile_log: int = 256
    #: per-phase samples kept for the p50/p99 rollups
    phase_window: int = 512
    #: hard cap on the steps one capture window may arm
    capture_limit: int = 256
    #: how long a flagged `expect(reason)` stays live, seconds
    expect_window_s: float = 30.0
    #: >0: the flight recorder keeps full per-decision rows only for
    #: batches slower than this (tail sampling); 0 keeps everything
    tail_threshold_ms: float = 0.0


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def pow2_statics(*args: Any) -> bool:
    """The TPU balancer's expected-shape predicate: every static int
    argument (batch bucket widths) is a power of two — the shapes its
    `_bucket` padding is allowed to produce. Anything else is churn."""
    return all(_is_pow2(a) for a in args
               if isinstance(a, int) and not isinstance(a, bool))


def _sig_of(x: Any) -> Any:
    """One leaf of a jit cache-key signature: array-likes key by
    (shape, dtype) — exactly what XLA's cache keys on — and python
    scalars key by value (they are static arguments to the jit)."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("arr", tuple(x.shape), str(x.dtype))
    if isinstance(x, (tuple, list)):  # NamedTuple pytrees included
        return tuple(_sig_of(e) for e in x)
    if isinstance(x, (bool, int, float, str)) or x is None:
        return ("static", x)
    return ("obj", type(x).__name__)


class _PhaseAgg:
    """Per-phase accumulation: log2 bucket counts (exposition) + a
    pre-sized sliding sample window (p50/p99 rollups). One bucket
    increment and one window write per observation — no growth. Traced
    observations additionally pin the latest exemplar on their bucket
    (bucket_idx -> (labels, value_ms, unix_ts)), linking the histogram's
    OpenMetrics rendering back to a trace."""

    __slots__ = ("counts", "sum_ms", "count", "window", "cursor",
                 "exemplars")

    def __init__(self, window: int):
        self.counts = np.zeros(len(PHASE_BOUNDS_MS) + 1, np.int64)
        self.sum_ms = 0.0
        self.count = 0
        self.window = np.zeros(max(8, window), np.float64)
        self.cursor = 0
        self.exemplars: Dict[int, tuple] = {}

    def add(self, ms: float, trace_id: Optional[str] = None) -> None:
        b = int(np.searchsorted(_PHASE_BOUNDS, ms, "left"))
        self.counts[b] += 1
        self.sum_ms += ms
        self.window[self.cursor] = ms
        self.cursor = (self.cursor + 1) % self.window.shape[0]
        self.count += 1
        if trace_id is not None:
            self.exemplars[b] = ({"trace_id": trace_id}, ms, time.time())

    def rollup(self) -> dict:
        n = min(self.count, self.window.shape[0])
        win = np.sort(self.window[:n]) if n else self.window[:0]
        return {
            "count": self.count,
            "mean_ms": round(self.sum_ms / self.count, 4) if self.count else None,
            "p50_ms": round(float(win[n // 2]), 4) if n else None,
            "p99_ms": round(float(win[min(n - 1, int(n * 0.99))]), 4) if n else None,
        }


class KernelProfiler:
    """One per balancer (base-class hook, like the flight recorder)."""

    def __init__(self, config: Optional[ProfilingConfig] = None,
                 logger=None, metrics=None):
        self.config = config or ProfilingConfig()
        self.enabled = self.config.enabled
        self.logger = logger
        self.metrics = metrics
        self.tail_threshold_ms = float(self.config.tail_threshold_ms)
        #: entry name -> {"fn", "seen": {sig: calls}, "compiles", "expected"}
        self._entries: Dict[str, dict] = {}
        self._compile_log: SeqRingBuffer[dict] = SeqRingBuffer(
            max(1, int(self.config.compile_log)))
        self.compiles_expected = 0
        self.compiles_unexpected = 0
        #: batches the tail sampler dropped full rows for
        self.tail_skipped = 0
        self._expect_reason: Optional[str] = None
        self._expect_until = 0.0
        #: observe_phase is called from the readback worker thread AND the
        #: event loop; rollup/render from scrape threads
        self._phase_lock = threading.Lock()
        self._phases: Dict[str, _PhaseAgg] = {}
        # capture plane
        self._capture_remaining = 0
        self._capture_rows: List[dict] = []
        self._capture_started: Optional[float] = None
        self._trace_dir: Optional[str] = None
        self._trace_active = False
        # HBM watermark across ticks (backends without peak_bytes_in_use)
        self._hbm_high_water = 0
        self._mem_refreshed = 0.0

    @classmethod
    def from_config(cls, logger=None, metrics=None) -> "KernelProfiler":
        return cls(config=load_config(ProfilingConfig, env_path="profiling"),
                   logger=logger, metrics=metrics)

    # -- compile tracking --------------------------------------------------
    def expect(self, reason: str) -> None:
        """Flag that upcoming compiles are expected (growth event, kernel
        swap, restore): classification windows for `expect_window_s`."""
        if not self.enabled:
            return
        self._expect_reason = reason
        self._expect_until = time.monotonic() + self.config.expect_window_s

    def wrap(self, name: str, fn: Callable,
             expected: Optional[Callable[..., bool]] = None) -> Callable:
        """Interpose on a jitted entry point. Disabled -> `fn` unchanged.
        Re-wrapping a name with a NEW callable (the balancer rebuilt its
        fused program) resets the signature cache: the fresh jit cache
        will compile every signature again, and those compiles classify
        through the expect window the balancer flags around rebuilds."""
        if not self.enabled:
            return fn
        entry = self._entries.get(name)
        if entry is None or entry["fn"] is not fn:
            # re-registering a NAME with a new callable is a kernel/backend
            # swap (pallas<->xla calibration, VMEM fallback, growth
            # rebuild): stamp it so the fresh cache's compiles classify as
            # the swap they are — for expect_window_s after the rebuild —
            # instead of leaning on first_call (one compile only) or the
            # shape predicate, and never as shape_churn
            rebuilt = None if entry is None else time.monotonic()
            entry = {"fn": fn, "seen": {}, "compiles": 0,
                     "expected": expected, "rebuilt_at": rebuilt}
            self._entries[name] = entry
        seen = entry["seen"]

        def profiled(*args):
            if not self.enabled:
                return fn(*args)
            sig = tuple(_sig_of(a) for a in args)
            hit = seen.get(sig)
            if hit is not None:
                seen[sig] = hit + 1
                return fn(*args)
            # cache miss: this call traces + compiles (jax compiles
            # synchronously, so the call's wall time covers the compile)
            t0 = time.monotonic()
            out = fn(*args)
            wall_ms = (time.monotonic() - t0) * 1e3
            seen[sig] = 1
            self._on_compile(name, entry, sig, args, wall_ms)
            return out

        profiled.__wrapped__ = fn
        profiled._kernel_profiled = True
        return profiled

    def _on_compile(self, name: str, entry: dict, sig: tuple, args: tuple,
                    wall_ms: float) -> None:
        rebuilt_at = entry.get("rebuilt_at")
        if self._expect_reason is not None \
                and time.monotonic() < self._expect_until:
            exp, reason = True, self._expect_reason
        elif rebuilt_at is not None and (time.monotonic() - rebuilt_at
                                         < self.config.expect_window_s):
            # a freshly swapped-in entry point recompiling its working set
            # (see wrap): expected, whatever the signature looks like
            exp, reason = True, "kernel_swap"
        elif entry["compiles"] == 0:
            exp, reason = True, "first_call"
        elif entry["expected"] is not None and entry["expected"](*args):
            exp, reason = True, "bucketed_shape"
        else:
            exp, reason = False, "shape_churn"
        entry["compiles"] += 1
        if exp:
            self.compiles_expected += 1
        else:
            self.compiles_unexpected += 1
        event = {
            "ts": round(time.time(), 3),
            "entry": name,
            "signature": repr(sig),
            "wall_ms": round(wall_ms, 3),
            "expected": exp,
            "reason": reason,
        }
        self._compile_log.append(event)
        if self.metrics is not None:
            self.metrics.counter(
                "loadbalancer_kernel_recompiles_total",
                tags={"expected": "true" if exp else "false"})
        if not exp and self.logger is not None:
            # the recompile watchdog: churn costs hundreds of ms of
            # placement latency per event — say so, with the shape key
            self.logger.warn(
                None, f"unexpected kernel recompile (shape churn): "
                f"entry={name} wall_ms={wall_ms:.1f} sig={sig}",
                "KernelProfiler")

    def compile_log(self, n: int = 50) -> List[dict]:
        return self._compile_log.last(n)

    def cache_census(self) -> dict:
        """Per entry point: live cache keys, compiles paid, total calls."""
        return {name: {
            "signatures": len(e["seen"]),
            "compiles": e["compiles"],
            "calls": int(sum(e["seen"].values())),
        } for name, e in self._entries.items()}

    # -- per-phase device timing -------------------------------------------
    def observe_phase(self, phase: str, ms: float,
                      trace_id: Optional[str] = None) -> None:
        """Fold one phase duration in. `trace_id` (from a flight-recorder
        row that carried a trace context) pins an exemplar on the bucket
        this observation lands in — rendered only on OpenMetrics scrapes."""
        if not self.enabled:
            return
        with self._phase_lock:
            agg = self._phases.get(phase)
            if agg is None:
                agg = _PhaseAgg(self.config.phase_window)
                self._phases[phase] = agg
            agg.add(ms, trace_id)

    def phase_rollups(self) -> dict:
        with self._phase_lock:
            return {phase: agg.rollup()
                    for phase, agg in self._phases.items()}

    def prometheus_text(self, openmetrics: bool = False) -> str:
        """The phase-duration histogram family, rendered through the same
        exposition helpers as the telemetry plane (register_renderer
        hook). Empty while no phases observed (or disabled). When the
        scrape negotiated OpenMetrics, bucket lines carry the pinned
        trace exemplars (the classic text format has no exemplar syntax,
        so they are omitted there)."""
        if not self.enabled:
            return ""
        from ..controller.monitoring import histogram_family_text
        with self._phase_lock:
            rows = [(phase, agg.counts.copy(), agg.sum_ms)
                    for phase, agg in sorted(self._phases.items())]
            exemplars = ({phase: dict(agg.exemplars)
                          for phase, agg in self._phases.items()
                          if agg.exemplars} if openmetrics else None)
        if not rows:
            return ""
        return "\n".join(histogram_family_text(
            "openwhisk_loadbalancer_phase_duration_seconds", "phase",
            rows, PHASE_BOUNDS_MS, exemplars=exemplars))

    # -- HBM / memory watermarks -------------------------------------------
    def memory_stats(self) -> dict:
        """`device.memory_stats()` of the first local device, guarded: CPU
        backends (and PJRT plugins without the API) answer {}."""
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — introspection must never raise
            return {}
        if not stats:
            return {}
        return {k: int(v) for k, v in stats.items()
                if isinstance(v, (int, np.integer))}

    def refresh_memory(self, metrics=None) -> dict:
        """Refresh the `loadbalancer_hbm_*` gauges (supervision tick)."""
        if not self.enabled:
            return {}
        stats = self.memory_stats()
        if not stats:
            return {}
        in_use = stats.get("bytes_in_use", 0)
        self._hbm_high_water = max(self._hbm_high_water,
                                   stats.get("peak_bytes_in_use", in_use))
        out = {
            "loadbalancer_hbm_bytes_in_use": in_use,
            "loadbalancer_hbm_peak_bytes_in_use": self._hbm_high_water,
        }
        limit = stats.get("bytes_limit")
        if limit:
            out["loadbalancer_hbm_bytes_limit"] = limit
            out["loadbalancer_hbm_utilization_ratio"] = round(
                in_use / limit, 6)
        m = metrics if metrics is not None else self.metrics
        if m is not None:
            for k, v in out.items():
                m.gauge(k, v)
        return out

    def maybe_refresh_memory(self, metrics=None,
                             min_interval_s: float = 1.0) -> None:
        """`refresh_memory` with a 1 Hz cap, for balancers without a
        supervision scheduler (lean) that refresh off the dispatch/
        completion stream — the analogue of TelemetryPlane.maybe_tick."""
        if not self.enabled:
            return
        now = time.monotonic()
        if now - self._mem_refreshed < min_interval_s:
            return
        self._mem_refreshed = now
        self.refresh_memory(metrics)

    # -- capture plane + tail sampling -------------------------------------
    @property
    def capture_armed(self) -> bool:
        return self._capture_remaining > 0

    def arm_capture(self, steps: int, trace_dir: Optional[str] = None,
                    tail_threshold_ms: Optional[float] = None) -> dict:
        """Arm a bounded capture window: the next `steps` dispatch steps
        are recorded at full detail (capped at `capture_limit`). With
        `trace_dir`, also starts a `jax.profiler` trace into it when the
        real profiler is importable (stopped when the window drains).
        `tail_threshold_ms` re-targets the tail sampler (0 disables)."""
        steps = max(1, min(int(steps), int(self.config.capture_limit)))
        if self._trace_active:
            self._stop_trace()  # re-arm replaces any live trace
        self._capture_rows = []
        self._capture_remaining = steps
        self._capture_started = time.time()
        if tail_threshold_ms is not None:
            self.tail_threshold_ms = max(0.0, float(tail_threshold_ms))
        trace = {"requested": trace_dir is not None, "active": False}
        if trace_dir is not None:
            try:
                import jax.profiler
                jax.profiler.start_trace(trace_dir)
                self._trace_dir = trace_dir
                self._trace_active = True
                trace["active"] = True
            except Exception as e:  # noqa: BLE001 — the capture window
                # still works without the device trace
                trace["error"] = repr(e)
        return {"armed": True, "steps": steps, "trace": trace,
                "tail_threshold_ms": self.tail_threshold_ms}

    def _stop_trace(self) -> None:
        self._trace_active = False
        try:
            import jax.profiler
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a dead trace must not take
            pass           # the dispatch path down with it

    def capture_step(self, row: dict) -> bool:
        """Record one dispatch step into the armed window; returns False
        (and stays a no-op) when no window is armed."""
        if not self.enabled or self._capture_remaining <= 0:
            return False
        self._capture_rows.append(row)
        self._capture_remaining -= 1
        if self._capture_remaining == 0 and self._trace_active:
            self._stop_trace()
        return True

    def admit_batch(self, total_ms: float) -> bool:
        """Tail-sampling admission for full flight-recorder rows: with a
        threshold set, only batches slower than it keep per-decision
        detail — unless a capture window wants everything. Counts what it
        drops (silent truncation would read as 'recorded everything')."""
        if not self.enabled:
            return True
        if self._capture_remaining > 0:
            return True
        if self.tail_threshold_ms <= 0.0 or total_ms >= self.tail_threshold_ms:
            return True
        self.tail_skipped += 1
        return False

    # -- the admin payload -------------------------------------------------
    def profile_json(self, kernel: str = "cpu") -> dict:
        """The `GET /admin/profile/kernel` payload: compile log + census,
        per-phase p50/p99 rollups, memory stats, capture status."""
        return {
            "enabled": self.enabled,
            "kernel": kernel,
            "compiles": {
                "expected": self.compiles_expected,
                "unexpected": self.compiles_unexpected,
                "log": self.compile_log(),
            },
            "cache_census": self.cache_census(),
            "phases": self.phase_rollups(),
            "phase_bounds_ms": PHASE_BOUNDS_MS,
            "memory": self.memory_stats(),
            "hbm_high_water_bytes": self._hbm_high_water,
            "tail_threshold_ms": self.tail_threshold_ms,
            "tail_skipped": self.tail_skipped,
            "capture": {
                "armed": self.capture_armed,
                "remaining": self._capture_remaining,
                "captured": len(self._capture_rows),
                "started": self._capture_started,
                "trace_dir": self._trace_dir,
                "trace_active": self._trace_active,
                "steps": self._capture_rows,
            },
        }

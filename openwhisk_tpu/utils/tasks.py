"""Fire-and-forget task spawning that survives garbage collection.

asyncio only holds weak references to tasks: an unreferenced
`loop.create_task(...)` can be collected before it runs. `spawn` keeps a
strong reference until the task completes (and swallows/loggs its errors —
these are best-effort side channels like telemetry events).
"""
from __future__ import annotations

import asyncio
from typing import Coroutine, Optional

_background: set = set()


def spawn(coro: Coroutine, logger=None, name: Optional[str] = None) -> asyncio.Task:
    task = asyncio.get_event_loop().create_task(coro, name=name)
    _background.add(task)

    def _done(t: asyncio.Task) -> None:
        _background.discard(t)
        if not t.cancelled() and t.exception() is not None and logger is not None:
            from .transaction import TransactionId
            logger.warn(TransactionId.SYSTEM,
                        f"background task {name or ''} failed: {t.exception()!r}")

    task.add_done_callback(_done)
    return task


async def wait_for_shutdown() -> None:
    """Block until SIGTERM/SIGINT so service mains can run their `finally`
    cleanup (destroy sandboxes, close servers). A bare
    `await asyncio.Event().wait()` dies uncleanly on SIGTERM — the default
    handler terminates the process before any cleanup runs."""
    import signal

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
    await stop.wait()

"""Entitlement: privileges, rate throttling, concurrency throttling.

Rebuild of core/controller/.../entitlement/Entitlement.scala (:86-153 rate
throttler wiring, :197-211 kind restriction, :280-317 check pipeline) +
RateThrottler.scala + ActivationThrottler.scala:
  - privilege model READ/PUT/DELETE/ACTIVATE + implicit rights in the
    subject's own namespace,
  - per-minute rate throttle (invocations and trigger fires) with per-user
    overrides from Identity.limits,
  - concurrent-activation throttle backed by the load balancer's live
    in-flight counters,
  - per-cluster division: each controller enforces limit/clusterSize with
    the reference's 20% overcommit (:94-99,123-133),
  - kind whitelist (KindRestrictor).
Device-side note: the vectorized token-bucket equivalent for bulk admission
lives in openwhisk_tpu/ops/throttle.py; the TPU balancer fuses it into its
placement step when constructed with rate_limit_per_minute (controller flag
--balancer-rate-limit) as a bus-boundary backstop behind this front-door
throttler. Semantics differ deliberately: this class is the reference's
rolling-minute window with per-user overrides; the device bucket is a
continuous-refill token bucket at the platform default rate.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from ..core.entity import Identity

READ = "READ"
PUT = "PUT"
DELETE = "DELETE"
ACTIVATE = "ACTIVATE"
REJECT = "REJECT"


class EntitlementException(Exception):
    status = 403

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class RejectRequest(EntitlementException):
    pass


class ThrottleRejectRequest(EntitlementException):
    status = 429


def rate_limit_message(description: str) -> str:
    """The 429 body for a rate rejection — ONE copy shared by the serial
    path and the batched AdmissionPlane (clients key on this text, and the
    batched path's parity contract includes it verbatim)."""
    return ("Too many requests in the last minute (count: exceeded, "
            f"allowed: {description}).")


CONCURRENT_LIMIT_MESSAGE = ("Too many concurrent requests in flight "
                            "(count: exceeded, allowed: concurrent "
                            "invocations).")


class RateThrottler:
    """Sliding one-minute window counter per namespace (ref
    RateThrottler.scala — the reference uses a rolling minute bucket)."""

    def __init__(self, description: str, default_per_minute: int):
        self.description = description
        self.default_per_minute = default_per_minute
        self._events: Dict[str, deque] = {}

    def check(self, namespace_id: str, limit_override: Optional[int] = None,
              now: Optional[float] = None) -> bool:
        """`now` (monotonic seconds) defaults to the call time; the batched
        admission plane's parity fuzz pins it so serial and vectorized
        decisions are compared at identical clocks."""
        limit = limit_override if limit_override is not None else self.default_per_minute
        now = time.monotonic() if now is None else now
        q = self._events.setdefault(namespace_id, deque())
        while q and q[0] <= now - 60.0:
            q.popleft()
        if len(q) >= limit:
            return False
        q.append(now)
        return True


class ActivationThrottler:
    """Concurrent-activation limit backed by LB in-flight counters
    (ref ActivationThrottler.scala)."""

    def __init__(self, load_balancer, default_concurrent: int):
        self.load_balancer = load_balancer
        self.default_concurrent = default_concurrent

    def check(self, namespace_id: str, limit_override: Optional[int] = None) -> bool:
        limit = limit_override if limit_override is not None else self.default_concurrent
        return self.load_balancer.active_activations_for(namespace_id) < limit


class LocalEntitlementProvider:
    """Grants + throttles (ref EntitlementProvider.check:280-317 and
    LocalEntitlement explicit-grant map)."""

    OVERCOMMIT = 1.2  # ref Entitlement.scala:94-99

    def __init__(self, load_balancer=None,
                 invocations_per_minute: int = 60,
                 concurrent_invocations: int = 30,
                 fires_per_minute: int = 60,
                 allowed_kinds: Optional[set] = None,
                 metrics=None, event_producer=None,
                 admission_config=None, frontend_config=None):
        self.load_balancer = load_balancer
        self.metrics = metrics
        self.event_producer = event_producer  # `events` topic (throttle events)
        self._grants: Dict[str, set] = {}
        # batched admission: concurrent ACTIVATE throttle checks coalesce
        # into one vectorized pass (controller/admission.py). Off
        # (CONFIG_whisk_admission_batch_enabled=false) keeps the serial
        # _check_throttles path bit-exact with the pre-batching behavior.
        from .admission import AdmissionBatchConfig, AdmissionPlane
        from .frontend import FrontendConfig
        adm_cfg = (admission_config if admission_config is not None
                   else AdmissionBatchConfig.from_env())
        fe_cfg = (frontend_config if frontend_config is not None
                  else FrontendConfig.from_env())
        # when the sharded front end will own admission (shards >= 2),
        # the single-loop plane is never reachable from check() — don't
        # build dead state whose stats would read 0 beside the real work
        self.admission: Optional[AdmissionPlane] = (
            AdmissionPlane(self, adm_cfg)
            if adm_cfg.enabled and fe_cfg.shards <= 1 else None)
        cluster = max(1, getattr(load_balancer, "cluster_size", 1) or 1)
        per_instance = lambda n: max(1, int(n / cluster * self.OVERCOMMIT)) \
            if cluster > 1 else n
        self.invoke_rate = RateThrottler("invocations per minute",
                                         per_instance(invocations_per_minute))
        self.fire_rate = RateThrottler("trigger fires per minute",
                                       per_instance(fires_per_minute))
        self.concurrent = ActivationThrottler(load_balancer,
                                              per_instance(concurrent_invocations))
        self.allowed_kinds = allowed_kinds  # None = all kinds allowed
        # sharded front end (controller/frontend.py): with
        # CONFIG_whisk_frontend_shards >= 2, ACTIVATE throttle checks
        # route to N admission worker loops partitioned by namespace
        # hash, each owning its slice of throttle state (built LAST: the
        # shard facades snapshot the throttler descriptions/limits
        # above). None (shards=1, the default) keeps the single-loop
        # admission path bit-exact. With admission BATCHING disabled the
        # shards still own their namespace slices but flush one check at
        # a time (max_batch=1) — a 1-deep rate_admit_batch is exactly the
        # serial check, so the admission off-switch keeps its serial
        # semantics under sharding instead of being silently bypassed.
        from .frontend import maybe_shard_frontend
        shard_adm = (adm_cfg if adm_cfg.enabled
                     else AdmissionBatchConfig(enabled=False, window_ms=0.0,
                                               max_batch=1))
        self.frontend = maybe_shard_frontend(self, config=fe_cfg,
                                             admission_config=shard_adm)

    # -- explicit grants (LocalEntitlement) --------------------------------
    def grant(self, subject: str, right: str, resource: str) -> None:
        self._grants.setdefault(f"{subject}/{resource}", set()).add(right)

    def revoke(self, subject: str, right: str, resource: str) -> None:
        self._grants.get(f"{subject}/{resource}", set()).discard(right)

    def _entitled(self, identity: Identity, right: str, namespace: str) -> bool:
        if right in identity.rights and namespace == str(identity.namespace.name):
            return True  # implicit rights in own namespace
        return right in self._grants.get(f"{identity.subject}/{namespace}", set())

    # -- the check pipeline ------------------------------------------------
    async def check(self, identity: Identity, right: str, namespace: str,
                    throttle: bool = False, is_trigger_fire: bool = False,
                    waterfall_ctx=None) -> None:
        """`waterfall_ctx` (an un-adopted stage vector from the latency
        waterfall plane) gets the entitle/throttle stages stamped between
        the pipeline's two halves, so the end-to-end budget can tell an
        entitlement-bound tail from a throttle-bound one."""
        from ..utils.waterfall import (STAGE_ENTITLE, STAGE_THROTTLE,
                                       ActivationWaterfall)
        if REJECT in identity.rights:
            raise RejectRequest("The subject is not entitled to access this API.")
        if not self._entitled(identity, right, namespace):
            raise RejectRequest(
                f"The supplied authentication is not authorized to access "
                f"'{namespace}' with {right} right.")
        if waterfall_ctx is not None:
            ActivationWaterfall.stamp_ctx(waterfall_ctx, STAGE_ENTITLE)
        if throttle and right == ACTIVATE:
            if self.frontend is not None:
                # sharded front end: the check runs on the worker loop
                # owning this namespace's slice of admission state (same
                # decisions, same exceptions — per-namespace arrival
                # order is preserved by the hash partition)
                await self.frontend.check_throttles(identity, is_trigger_fire)
            elif self.admission is not None:
                # batched path: this check coalesces with concurrent
                # arrivals and resolves from one vectorized flush (same
                # decisions, same exceptions as the serial path)
                await self.admission.check_throttles(identity, is_trigger_fire)
            else:
                self._check_throttles(identity, is_trigger_fire)
            if waterfall_ctx is not None:
                ActivationWaterfall.stamp_ctx(waterfall_ctx, STAGE_THROTTLE)

    def _check_throttles(self, identity: Identity, is_trigger_fire: bool) -> None:
        ns_id = identity.namespace.uuid.asString
        limits = identity.limits
        if is_trigger_fire:
            if not self.fire_rate.check(ns_id, limits.fires_per_minute):
                self._throttle_event("TimedRateLimit", identity)
                raise ThrottleRejectRequest(
                    rate_limit_message(self.fire_rate.description))
        else:
            if not self.invoke_rate.check(ns_id, limits.invocations_per_minute):
                self._throttle_event("TimedRateLimit", identity)
                raise ThrottleRejectRequest(
                    rate_limit_message(self.invoke_rate.description))
            if self.load_balancer is not None and \
                    not self.concurrent.check(ns_id, limits.concurrent_invocations):
                self._throttle_event("ConcurrentRateLimit", identity)
                raise ThrottleRejectRequest(CONCURRENT_LIMIT_MESSAGE)

    async def close(self) -> None:
        """Stop the sharded front end's worker loops (no-op at shards=1).
        The thread joins run on the executor — a slow shard must not
        stall the controller loop mid-shutdown."""
        if self.frontend is not None:
            import asyncio
            await asyncio.get_event_loop().run_in_executor(
                None, self.frontend.close)

    def check_kind(self, identity: Identity, kind: str) -> None:
        """Kind whitelist (ref KindRestrictor, Entitlement.scala:197-211)."""
        allowed = identity.limits.allowed_kinds or self.allowed_kinds
        if allowed is not None and kind not in allowed:
            raise RejectRequest(f"action kind '{kind}' not allowed for this subject")

    def _throttle_event(self, which: str, identity: Identity) -> None:
        """Count + publish the user-facing throttle event
        (ref Entitlement.scala:383-399 -> `events` topic)."""
        if self.metrics:
            self.metrics.counter(f"controller_throttle_{which}")
        if self.event_producer is not None:
            from ..messaging.message import EventMessage
            from ..utils.tasks import spawn
            spawn(self.event_producer.send(
                "events", EventMessage.for_metric(
                    "controller", which, 1, str(identity.subject),
                    str(identity.namespace.name),
                    identity.namespace.uuid.asString)), name="throttle-event")

"""`wsk package bind` (ref wsk CLI + Packages.scala binding semantics):
bind a provider package under a new name with parameter overrides, then
invoke an action through the binding."""
import asyncio
import base64

import aiohttp

from openwhisk_tpu.standalone import GUEST_KEY, GUEST_UUID, make_standalone
from openwhisk_tpu.tools import wsk

AUTH_PAIR = f"{GUEST_UUID}:{GUEST_KEY}"
AUTH = "Basic " + base64.b64encode(AUTH_PAIR.encode()).decode()
HDRS = {"Authorization": AUTH, "Content-Type": "application/json"}
PORT = 13283
HOST = f"http://127.0.0.1:{PORT}"
BASE = f"{HOST}/api/v1"

CODE = "def main(a):\n    return {'who': a.get('who')}\n"


async def _wsk(*argv) -> int:
    return await asyncio.to_thread(
        wsk.main, ["--apihost", HOST, "--auth", AUTH_PAIR, *argv])


def test_bind_and_invoke_through_binding():
    async def go():
        controller = await make_standalone(port=PORT)
        try:
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{BASE}/namespaces/_/packages/provider",
                                 headers=HDRS,
                                 json={"parameters": [
                                     {"key": "who", "value": "provider"}]}) as r:
                    assert r.status == 200
                async with s.put(
                        f"{BASE}/namespaces/_/actions/provider/who",
                        headers=HDRS,
                        json={"exec": {"kind": "python:3",
                                       "code": CODE}}) as r:
                    assert r.status == 200
                # relative provider reference resolves to the caller's ns
                assert await _wsk("package", "bind", "provider", "mybind",
                                  "-p", "who", "bound") == 0
                async with s.get(f"{BASE}/namespaces/_/packages/mybind",
                                 headers=HDRS) as r:
                    doc = await r.json()
                    assert doc["binding"]["name"] == "provider"
                    assert doc["binding"]["namespace"] == "guest"
                async with s.post(
                        f"{BASE}/namespaces/_/actions/mybind/who"
                        "?blocking=true&result=true",
                        headers=HDRS, json={}) as r:
                    assert r.status == 200
                    assert await r.json() == {"who": "bound"}
                # binding to a nonexistent provider fails loudly
                assert await _wsk("package", "bind", "ghost", "b2") == 1
                # malformed provider references: usage error, no traceback
                assert await _wsk("package", "bind", "a/b/c", "b3") == 2
                # binding to a binding is rejected (one-level dereference)
                async with s.put(f"{BASE}/namespaces/_/packages/chain",
                                 headers=HDRS,
                                 json={"binding": {"namespace": "guest",
                                                   "name": "mybind"}}) as r:
                    assert r.status == 400
                    assert "binding" in (await r.json())["error"]
        finally:
            await controller.stop()

    asyncio.run(go())


def test_cross_namespace_bind_requires_public_provider():
    """Security: a private package in another namespace must not be
    bindable (its parameters often carry credentials); publishing it opens
    the bind (ref Packages.scala bind semantics)."""
    async def go():
        controller = await make_standalone(port=PORT + 1)
        base = f"http://127.0.0.1:{PORT + 1}/api/v1"
        try:
            # a second identity with its own namespace owning a package
            from openwhisk_tpu.core.entity import (Identity, WhiskAuthRecord,
                                                   WhiskPackage, EntityPath,
                                                   EntityName, Parameters)
            victim = Identity.generate("victim")
            await controller.auth_store.put(WhiskAuthRecord(
                victim.subject, [victim.namespace], [victim.authkey]))
            secret = WhiskPackage(EntityPath("victim"), EntityName("creds"),
                                  None, Parameters.from_json(
                                      [{"key": "apikey", "value": "s3cr3t"}]))
            await controller.entity_store.put(secret)

            async with aiohttp.ClientSession() as s:
                async with s.put(f"{base}/namespaces/_/packages/steal",
                                 headers=HDRS,
                                 json={"binding": {"namespace": "victim",
                                                   "name": "creds"}}) as r:
                    private = (r.status, (await r.json())["error"])
                # no existence oracle: a nonexistent cross-ns provider must
                # be INDISTINGUISHABLE from a private one
                async with s.put(f"{base}/namespaces/_/packages/probe",
                                 headers=HDRS,
                                 json={"binding": {"namespace": "victim",
                                                   "name": "nope"}}) as r:
                    ghost = (r.status, (await r.json())["error"])
                assert private == ghost == \
                    (403, "the referenced package is not accessible")
                # the victim publishes: the bind opens
                secret2 = await controller.entity_store.get_package(
                    "victim/creds")
                secret2.publish = True
                await controller.entity_store.put(secret2)
                async with s.put(f"{base}/namespaces/_/packages/ok",
                                 headers=HDRS,
                                 json={"binding": {"namespace": "victim",
                                                   "name": "creds"}}) as r:
                    assert r.status == 200, await r.text()
        finally:
            await controller.stop()

    asyncio.run(go())

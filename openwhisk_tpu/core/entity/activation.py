"""Activations: the record of one function invocation.

Refs: ActivationResponse.scala (status codes 0..3 = success / application
error / developer error / whisk internal error, with `shrink`-able result
payloads) and WhiskActivation.scala (start/end, logs, response, annotations
incl. waitTime/initTime/kind/path/limits — the audit log of the system,
SURVEY §5.5).
"""
from __future__ import annotations

import json
import time
from typing import Any, List, Optional

from .entity import WhiskEntity
from .ids import ActivationId, Subject
from .names import EntityName, EntityPath
from .parameters import Parameters
from .semver import SemVer

# status codes (ActivationResponse.scala:42-48)
SUCCESS = 0
APPLICATION_ERROR = 1
DEVELOPER_ERROR = 2
WHISK_INTERNAL_ERROR = 3

_STATUS_NAMES = {
    SUCCESS: "success",
    APPLICATION_ERROR: "application error",
    DEVELOPER_ERROR: "action developer error",
    WHISK_INTERNAL_ERROR: "whisk internal error",
}


class ActivationResponse:
    __slots__ = ("status_code", "result", "size")

    def __init__(self, status_code: int, result: Optional[Any] = None,
                 size: Optional[int] = None):
        self.status_code = status_code
        self.result = result
        self.size = size

    # -- constructors (ref ActivationResponse.scala:60-120) ----------------
    @classmethod
    def success(cls, result: Optional[Any] = None) -> "ActivationResponse":
        return cls(SUCCESS, result)

    @classmethod
    def application_error(cls, error: Any) -> "ActivationResponse":
        return cls(APPLICATION_ERROR, {"error": error})

    @classmethod
    def developer_error(cls, error: Any) -> "ActivationResponse":
        return cls(DEVELOPER_ERROR, {"error": error})

    @classmethod
    def whisk_error(cls, error: Any) -> "ActivationResponse":
        return cls(WHISK_INTERNAL_ERROR, {"error": error})

    @classmethod
    def payload_placeholder(cls) -> "ActivationResponse":
        return cls(SUCCESS, {"error": "payload was too large to include"})

    # -- predicates --------------------------------------------------------
    @property
    def is_success(self) -> bool:
        return self.status_code == SUCCESS

    @property
    def is_app_error(self) -> bool:
        return self.status_code == APPLICATION_ERROR

    @property
    def is_whisk_error(self) -> bool:
        return self.status_code == WHISK_INTERNAL_ERROR

    @property
    def status(self) -> str:
        return _STATUS_NAMES[self.status_code]

    def shrink(self, limit_bytes: int) -> "ActivationResponse":
        """Drop an oversized result payload (ref AcknowledgementMessage.shrink,
        Message.scala — keeps the ack under the bus payload cap)."""
        if self.result is not None and len(json.dumps(self.result).encode()) > limit_bytes:
            return ActivationResponse(self.status_code, None,
                                      size=len(json.dumps(self.result).encode()))
        return self

    def to_json(self) -> dict:
        j = {"statusCode": self.status_code, "status": self.status,
             "success": self.is_success}
        if self.result is not None:
            j["result"] = self.result
        if self.size is not None:
            j["size"] = self.size
        return j

    @classmethod
    def from_json(cls, j: dict) -> "ActivationResponse":
        return cls(int(j.get("statusCode", SUCCESS)), j.get("result"), j.get("size"))

    def __eq__(self, other):
        return isinstance(other, ActivationResponse) and \
            (self.status_code, self.result) == (other.status_code, other.result)

    def __repr__(self):
        return f"ActivationResponse({self.status}, {self.result!r})"


class WhiskActivation(WhiskEntity):
    collection = "activations"

    def __init__(self, namespace: EntityPath, name: EntityName,
                 subject: Subject, activation_id: ActivationId,
                 start: float, end: float = 0.0,
                 response: Optional[ActivationResponse] = None,
                 logs: Optional[List[str]] = None,
                 annotations: Optional[Parameters] = None,
                 duration: Optional[int] = None,
                 cause: Optional[ActivationId] = None,
                 version: Optional[SemVer] = None, publish: bool = False):
        super().__init__(namespace, name, version, publish, annotations)
        self.subject = subject
        self.activation_id = activation_id
        self.start = start
        self.end = end
        self.response = response or ActivationResponse.success()
        self.logs = logs or []
        self.duration = duration
        self.cause = cause

    @property
    def docid(self) -> str:
        return f"{self.namespace}/{self.activation_id}"

    def with_logs(self, logs: List[str]) -> "WhiskActivation":
        self.logs = logs
        return self

    def without_logs(self) -> "WhiskActivation":
        """Summary view used on the wire when logs are collected later."""
        return WhiskActivation(self.namespace, self.name, self.subject,
                               self.activation_id, self.start, self.end,
                               self.response, [], self.annotations,
                               self.duration, self.cause, self.version, self.publish)

    def resulting_json(self) -> dict:
        """The `?result=true` projection (just the response result)."""
        return self.response.result if self.response.result is not None else {}

    def to_json(self) -> dict:
        j = self.base_json()
        j.update({
            "subject": self.subject.to_json(),
            "activationId": self.activation_id.to_json(),
            "start": int(self.start * 1000),
            "end": int(self.end * 1000),
            "response": self.response.to_json(),
            "logs": self.logs,
        })
        if self.duration is not None:
            j["duration"] = self.duration
        if self.cause is not None:
            j["cause"] = self.cause.to_json()
        return j

    @classmethod
    def from_json(cls, j: dict) -> "WhiskActivation":
        return cls(
            EntityPath(j["namespace"]), EntityName(j["name"]),
            Subject(j["subject"]), ActivationId(j["activationId"]),
            j.get("start", 0) / 1000.0, j.get("end", 0) / 1000.0,
            ActivationResponse.from_json(j.get("response", {})),
            list(j.get("logs", [])),
            Parameters.from_json(j.get("annotations")),
            j.get("duration"),
            ActivationId(j["cause"]) if j.get("cause") else None,
            SemVer.from_string(j.get("version", "0.0.1")),
            bool(j.get("publish", False)),
        )

    def summary_json(self) -> dict:
        """List-view projection (ref WhiskActivation.summaryFields)."""
        return {
            "namespace": self.namespace.to_json(), "name": self.name.to_json(),
            "activationId": self.activation_id.to_json(),
            "start": int(self.start * 1000), "end": int(self.end * 1000),
            "duration": self.duration,
            "statusCode": self.response.status_code,
            "version": self.version.to_json(), "cause": self.cause.to_json() if self.cause else None,
            "annotations": self.annotations.to_json(),
            "publish": self.publish,
        }


def now_ms() -> float:
    return time.time()

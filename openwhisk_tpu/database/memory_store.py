"""In-memory ArtifactStore (ref common/scala/.../database/memory/
MemoryArtifactStore.scala) — used by tests and standalone mode."""
from __future__ import annotations

import asyncio
import copy
from typing import Any, Dict, List, Optional, Tuple

from .store import (ArtifactStore, DocumentConflict, NoDocumentException,
                    match_query, sort_key)


class MemoryArtifactStore(ArtifactStore):
    def __init__(self):
        self._docs: Dict[str, Dict[str, Any]] = {}
        self._attachments: Dict[str, Dict[str, Tuple[str, bytes]]] = {}
        self._lock = asyncio.Lock()

    def _put_locked(self, doc_id: str, doc: Dict[str, Any],
                    rev: Optional[str] = None) -> str:
        existing = self._docs.get(doc_id)
        if existing is not None:
            cur = existing["_rev"]
            if rev is None or rev != cur:
                raise DocumentConflict(f"document {doc_id!r} update conflict")
            new_rev = f"{int(cur.split('-')[0]) + 1}-mem"
        else:
            if rev is not None:
                raise DocumentConflict(f"document {doc_id!r} does not exist at rev {rev}")
            new_rev = "1-mem"
        stored = copy.deepcopy(doc)
        stored["_id"] = doc_id
        stored["_rev"] = new_rev
        self._docs[doc_id] = stored
        return new_rev

    async def put(self, doc_id: str, doc: Dict[str, Any],
                  rev: Optional[str] = None) -> str:
        async with self._lock:
            return self._put_locked(doc_id, doc, rev)

    async def put_many(self, docs: List[Tuple[str, Dict[str, Any]]]) -> List[str]:
        """Bulk insert for the activation-record batcher: one lock acquire
        for N new documents, same per-document conflict semantics as put()
        (a mid-batch conflict fails the whole batch, exactly like the
        serial loop the batcher would otherwise run)."""
        async with self._lock:
            return [self._put_locked(doc_id, doc) for doc_id, doc in docs]

    async def get(self, doc_id: str) -> Dict[str, Any]:
        doc = self._docs.get(doc_id)
        if doc is None:
            raise NoDocumentException(doc_id)
        return copy.deepcopy(doc)

    async def delete(self, doc_id: str, rev: Optional[str] = None) -> bool:
        async with self._lock:
            doc = self._docs.get(doc_id)
            if doc is None:
                raise NoDocumentException(doc_id)
            if rev is not None and doc["_rev"] != rev:
                raise DocumentConflict(f"document {doc_id!r} delete conflict")
            del self._docs[doc_id]
            self._attachments.pop(doc_id, None)
            return True

    async def query(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None,
                    skip: int = 0, limit: int = 0,
                    descending: bool = True) -> List[Dict[str, Any]]:
        docs = [d for d in self._docs.values()
                if match_query(d, collection, namespace, name, since, upto)]
        docs.sort(key=sort_key, reverse=descending)
        if skip:
            docs = docs[skip:]
        if limit:
            docs = docs[:limit]
        return copy.deepcopy(docs)

    async def count(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None
                    ) -> int:
        return len([d for d in self._docs.values()
                    if match_query(d, collection, namespace, name, since, upto)])

    async def attach(self, doc_id: str, name: str, content_type: str,
                     data: bytes) -> None:
        if self.attachment_store is not None:
            return await self.attachment_store.attach(doc_id, name,
                                                      content_type, data)
        self._attachments.setdefault(doc_id, {})[name] = (content_type, bytes(data))

    async def read_attachment(self, doc_id: str, name: str) -> Tuple[str, bytes]:
        if self.attachment_store is not None:
            return await self.attachment_store.read_attachment(doc_id, name)
        try:
            return self._attachments[doc_id][name]
        except KeyError:
            raise NoDocumentException(f"attachment {doc_id}/{name}") from None

    async def delete_attachments(self, doc_id: str,
                                 except_name: Optional[str] = None) -> None:
        if self.attachment_store is not None:
            return await self.attachment_store.delete_attachments(
                doc_id, except_name=except_name)
        if except_name is None:
            self._attachments.pop(doc_id, None)
        elif doc_id in self._attachments:
            self._attachments[doc_id] = {
                k: v for k, v in self._attachments[doc_id].items()
                if k == except_name}


class MemoryArtifactStoreProvider:
    """SPI factory (ref ArtifactStoreProvider)."""

    @staticmethod
    def make_store(name: str = "whisks", **kwargs) -> MemoryArtifactStore:
        return MemoryArtifactStore()

"""Action proxy: the HTTP server inside an action "container".

This is the framework's equivalent of the runtime images' proxy (the contract
is documented by the reference's tools/actionProxy/invoke.py and
docs/actions-new.md): POST /init receives {"value": {code, main, binary,
env}}; POST /run receives {"value": args, ...activation context} and must
return the action result as JSON. After every /run the proxy prints the log
sentinel to stdout and stderr so the log collector can frame per-activation
logs.

Runs standalone: `python -m openwhisk_tpu.containerpool.actionproxy <port>`.
Kept dependency-free (stdlib only) so it can be dropped into any image.
"""
from __future__ import annotations

import io
import json
import os
import sys
import traceback
from contextlib import redirect_stderr, redirect_stdout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

SENTINEL = "XXX_THE_END_OF_A_WHISK_ACTIVATION_XXX"

_state = {"fn": None, "env": {}, "workdir": None}


class _InitRunGate:
    """Reader-writer gate for the ThreadingHTTPServer: /run requests run
    concurrently (intra-container concurrency), but a re-/init waits for
    in-flight runs to drain and blocks new ones — it evicts the previous
    zip's modules and deletes its workdir, which a concurrently executing
    old action could still be importing from."""

    def __init__(self):
        import threading

        self._cond = threading.Condition()
        self._runs = 0
        self._initing = False

    def begin_run(self) -> None:
        with self._cond:
            while self._initing:
                self._cond.wait()
            self._runs += 1

    def end_run(self) -> None:
        with self._cond:
            self._runs -= 1
            self._cond.notify_all()

    def begin_init(self) -> None:
        with self._cond:
            while self._initing:
                self._cond.wait()
            self._initing = True
            while self._runs:
                self._cond.wait()

    def end_init(self) -> None:
        with self._cond:
            self._initing = False
            self._cond.notify_all()


_gate = _InitRunGate()


def _compile_action(code: str, main: str):
    scope: dict = {}
    exec(compile(code, "<action>", "exec"), scope)  # noqa: S102 — this IS the sandbox body
    fn = scope.get(main)
    if not callable(fn):
        raise ValueError(f"Initialization has failed: no callable {main!r}")
    return fn


def _compile_binary_action(b64_zip: str, main: str):
    """Binary action: base64 zip with __main__.py, like the reference's
    python runtime (the zip may carry a package tree; it is extracted and
    put on sys.path so imports inside it resolve)."""
    import base64
    import tempfile
    import zipfile

    workdir = tempfile.mkdtemp(prefix="ow-action-")
    zip_path = os.path.join(workdir, "action.zip")
    with open(zip_path, "wb") as f:
        f.write(base64.b64decode(b64_zip))
    with zipfile.ZipFile(zip_path) as z:
        for member in z.namelist():  # refuse path traversal
            target = os.path.realpath(os.path.join(workdir, member))
            if not target.startswith(os.path.realpath(workdir) + os.sep):
                raise ValueError("zip entry escapes the action directory")
        z.extractall(workdir)
    entry = os.path.join(workdir, "__main__.py")
    if not os.path.exists(entry):
        raise ValueError("Initialization has failed: zip has no __main__.py")
    import shutil

    # Re-init: the previous zip's path entry and modules must not shadow
    # imports of the new code — but a failed re-init must leave the old
    # action fully working, so evict recoverably and clean up only after
    # the new archive compiles.
    prev = _state.get("workdir")
    evicted: dict = {}
    prev_in_path = prev is not None and prev in sys.path
    if prev is not None:
        if prev_in_path:
            sys.path.remove(prev)
        for name, mod in list(sys.modules.items()):
            if getattr(mod, "__file__", None) and \
                    str(mod.__file__).startswith(prev + os.sep):
                evicted[name] = sys.modules.pop(name)
    sys.path.insert(0, workdir)
    try:
        with open(entry) as f:
            fn = _compile_action(f.read(), main)
    except BaseException:
        if workdir in sys.path:
            sys.path.remove(workdir)
        for name, mod in list(sys.modules.items()):
            if getattr(mod, "__file__", None) and \
                    str(mod.__file__).startswith(workdir + os.sep):
                del sys.modules[name]
        if prev_in_path:
            sys.path.insert(0, prev)
        sys.modules.update(evicted)
        shutil.rmtree(workdir, ignore_errors=True)
        raise
    if prev is not None:
        shutil.rmtree(prev, ignore_errors=True)
    _state["workdir"] = workdir
    return fn


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _reply(self, status: int, body: dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _read_json(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return {}

    def do_POST(self):  # noqa: N802 — http.server API
        if self.path == "/init":
            self._init(self._read_json())
        elif self.path == "/run":
            self._run(self._read_json())
        else:
            self._reply(404, {"error": "unknown path"})

    def do_GET(self):  # noqa: N802
        self._reply(200 if _state["fn"] else 503, {"ok": _state["fn"] is not None})

    def _init(self, payload: dict) -> None:
        value = payload.get("value", {})
        code = value.get("code", "")
        main = value.get("main") or "main"
        _gate.begin_init()
        try:
            if value.get("binary"):
                _state["fn"] = _compile_binary_action(code, main)
            else:
                _state["fn"] = _compile_action(code, main)
            _state["env"] = value.get("env") or {}
            # export the init environment (e.g. __OW_API_KEY) so user code
            # can read it via os.environ, as in the real runtime images
            for k, v in _state["env"].items():
                os.environ[str(k)] = str(v)
            self._reply(200, {"ok": True})
        except Exception as e:  # noqa: BLE001 — report any user-code failure
            self._reply(502, {"error": f"Initialization has failed: {e}"})
        finally:
            _gate.end_init()

    def _run(self, payload: dict) -> None:
        _gate.begin_run()
        try:
            self._run_locked(payload)
        finally:
            _gate.end_run()

    def _run_locked(self, payload: dict) -> None:
        if _state["fn"] is None:
            self._reply(502, {"error": "cannot invoke an uninitialized action"})
            return
        args = payload.get("value") or {}
        # activation context -> env vars, as the runtime containers do
        for k, v in payload.items():
            if k != "value" and isinstance(v, str):
                os.environ["__OW_" + k.upper()] = v
        out, err = io.StringIO(), io.StringIO()
        try:
            with redirect_stdout(out), redirect_stderr(err):
                result = _state["fn"](args)
            if result is None:
                result = {}
            if not isinstance(result, dict):
                self._reply(502, {"error": "the action did not return a dictionary"})
            else:
                self._reply(200, result)
        except Exception:  # noqa: BLE001 — user code error -> application error
            err.write(traceback.format_exc())
            self._reply(502, {"error": "An error has occurred while running the action."})
        finally:
            # relay user logs + sentinel framing to the real stdout/stderr
            sys.stdout.write(out.getvalue())
            sys.stdout.write(SENTINEL + "\n")
            sys.stdout.flush()
            sys.stderr.write(err.getvalue())
            sys.stderr.write(SENTINEL + "\n")
            sys.stderr.flush()


def main() -> None:
    # memory cap: the process-level analogue of docker -m. Applied here (after
    # exec) rather than via a parent preexec_fn — fork hooks are unsafe in a
    # multithreaded parent (JAX), and the limit belongs to the sandbox anyway.
    limit = os.environ.get("OW_MEMORY_LIMIT_BYTES")
    if limit:
        try:
            import resource
            resource.setrlimit(resource.RLIMIT_AS, (int(limit), int(limit)))
        except (ValueError, OSError, ImportError):
            pass
    # per-connection handler threads get 4 MB stacks instead of the ~8 MB
    # default: the virtual stack counts against RLIMIT_AS (the sandbox
    # memory cap limits ADDRESS SPACE), and bursts of fresh connections
    # were exhausting it and wedging the accept loop. Not smaller: the
    # handler thread IS the user-code execution context, and C-stack-heavy
    # actions (deep json/re/pickle recursion) must raise catchable errors,
    # not overflow the thread stack
    import threading
    threading.stack_size(4 * 1024 * 1024)
    port = int(sys.argv[1]) if len(sys.argv) > 1 else 8080
    # optional bind host: a container runtime hands each sandbox its own
    # address (e.g. per-container loopback IPs); default matches the
    # process factory's 127.0.0.1
    host = sys.argv[2] if len(sys.argv) > 2 else "127.0.0.1"
    server = ThreadingHTTPServer((host, port), Handler)
    print(f"action proxy listening on {host}:{port}", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()

"""Cosmos-specific store behavior over real HTTP against the faithful
emulator (tests/fake_cosmosdb.py): master-key request signing (verified
server-side per request), slash→pipe id mapping, etag MVCC status codes,
continuation paging, sidecar attachment GC, the cross-partition query
gate, and the cosmos:// open_store URL (contract parity itself runs in
test_database.py's 5-backend fixture)."""
import asyncio
import base64
from urllib.parse import quote

import pytest

from openwhisk_tpu.database import (ArtifactStoreException, DocumentConflict,
                                    NoDocumentException)
from openwhisk_tpu.database.cosmosdb_store import (CosmosDbArtifactStore,
                                                   CosmosDbArtifactStoreProvider)
from tests.fake_cosmosdb import MASTER_KEY, FakeCosmosDB


def run(coro):
    return asyncio.run(coro)


class TestCosmosStore:
    def test_signature_verified_and_bad_key_rejected(self):
        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            good = CosmosDbArtifactStore(url, key=MASTER_KEY)
            await good.put("ns/a", {"entityType": "actions",
                                    "namespace": "ns", "name": "a",
                                    "updated": 1})
            assert fake.unauthorized == 0  # every signature recomputed OK
            bad = CosmosDbArtifactStore(
                url, key=base64.b64encode(b"wrong-key").decode())
            with pytest.raises(Exception):
                await bad.get("ns/a")
            assert fake.unauthorized >= 1
            await good.close()
            await bad.close()
            await fake.stop()
        run(go())

    def test_slash_ids_map_to_pipes_and_back(self):
        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            rev = await store.put("ns/pkg/act", {"entityType": "actions",
                                                 "namespace": "ns/pkg",
                                                 "name": "act",
                                                 "updated": 1})
            # stored under the PIPE id (Cosmos forbids '/' in ids), in the
            # root-namespace partition
            coll = fake.dbs["whisks"]["whisks"]
            assert ("ns", "ns|pkg|act") in coll
            doc = await store.get("ns/pkg/act")
            assert doc["_id"] == "ns/pkg/act" and doc["_rev"] == rev
            assert await store.delete("ns/pkg/act", rev)
            await store.close()
            await fake.stop()
        run(go())

    def test_stale_etag_maps_to_conflict(self):
        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            rev1 = await store.put("ns/doc", {"entityType": "actions",
                                              "namespace": "ns",
                                              "name": "doc", "updated": 1})
            await store.put("ns/doc", {"entityType": "actions",
                                       "namespace": "ns", "name": "doc",
                                       "updated": 2}, rev1)
            with pytest.raises(DocumentConflict):  # 412 PreconditionFailed
                await store.put("ns/doc", {"entityType": "actions",
                                           "namespace": "ns", "name": "doc",
                                           "updated": 3}, rev1)
            with pytest.raises(DocumentConflict):  # stale delete
                await store.delete("ns/doc", rev1)
            await store.close()
            await fake.stop()
        run(go())

    def test_continuation_paging_drains_all_rows(self):
        async def go():
            fake = FakeCosmosDB()  # PAGE_SIZE=3 forces continuations
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            for i in range(10):
                await store.put(f"ns/a{i}", {"entityType": "actions",
                                             "namespace": "ns",
                                             "name": f"a{i}",
                                             "updated": i + 1})
            docs = await store.query("actions", "ns")
            assert len(docs) == 10  # > 3 pages followed to exhaustion
            assert [d["name"] for d in docs[:3]] == ["a9", "a8", "a7"]
            assert await store.count("actions", "ns") == 10
            await store.close()
            await fake.stop()
        run(go())

    def test_cross_partition_queries_declare_themselves(self):
        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            for ns in ("nsa", "nsb"):
                await store.put(f"{ns}/x", {"entityType": "actions",
                                            "namespace": ns, "name": "x",
                                            "updated": 1})
            # namespace=None → cross-partition: the fake 400s unless the
            # documented opt-in header is present, so success proves it
            docs = await store.query("actions", None)
            assert {d["namespace"] for d in docs} == {"nsa", "nsb"}
            await store.close()
            await fake.stop()
        run(go())

    def test_sidecar_attachments_gc_with_document(self):
        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            rev = await store.put("ns/a", {"entityType": "actions",
                                           "namespace": "ns", "name": "a",
                                           "updated": 1})
            await store.attach("ns/a", "code", "text/plain", b"abc")
            await store.attach("ns/a", "code2", "text/plain", b"def")
            ct, data = await store.read_attachment("ns/a", "code")
            assert (ct, data) == ("text/plain", b"abc")
            await store.delete_attachments("ns/a", except_name="code2")
            with pytest.raises(NoDocumentException):
                await store.read_attachment("ns/a", "code")
            assert (await store.read_attachment("ns/a", "code2"))[1] == b"def"
            await store.delete("ns/a", rev)  # sidecars GC with the doc
            with pytest.raises(NoDocumentException):
                await store.read_attachment("ns/a", "code2")
            await store.close()
            await fake.stop()
        run(go())

    def test_open_store_cosmos_url(self):
        from openwhisk_tpu.database import open_store

        st = open_store(
            f"cosmos://{quote(MASTER_KEY, safe='')}@127.0.0.1:8081/mydb/mycoll")
        assert isinstance(st, CosmosDbArtifactStore)
        assert st.db == "mydb" and st.container == "mycoll"
        assert st.base == "http://127.0.0.1:8081"
        with pytest.raises(ValueError):
            open_store("cosmos://127.0.0.1:8081/mydb")  # key required

    def test_provider_spi(self):
        st = CosmosDbArtifactStoreProvider.instance(
            url="http://127.0.0.1:8081", key=MASTER_KEY)
        assert isinstance(st, CosmosDbArtifactStore)


class TestCosmosReviewRegressions:
    def test_att_namespace_entities_partition_and_list_correctly(self):
        """r5 review: a user namespace literally named 'att' must partition
        by itself (sidecars use the 'att:' prefix — ':' is impossible in
        entity ids — so no collision is possible)."""
        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            await store.put("att/myaction", {"entityType": "actions",
                                             "namespace": "att",
                                             "name": "myaction",
                                             "updated": 1})
            docs = await store.query("actions", "att")
            assert [d["name"] for d in docs] == ["myaction"]
            # and attachments on it don't collide with its entities
            rev = (await store.get("att/myaction"))["_rev"]
            await store.attach("att/myaction", "code", "text/plain", b"x")
            assert (await store.read_attachment("att/myaction", "code"))[1] \
                == b"x"
            assert len(await store.query("actions", "att")) == 1
            await store.delete("att/myaction", rev)
            await store.close()
            await fake.stop()
        run(go())

    def test_cross_partition_query_merges_per_range_streams(self):
        """ISSUE 3 satellite: cross-partition SQL carries no ORDER BY (the
        raw-REST gateway rejects it), so the fake serves one unmerged
        stream per partition key range — interleave sort keys across three
        partitions and the client-side merge sort must still produce one
        globally ordered list, both directions."""
        async def go():
            fake = FakeCosmosDB()  # PAGE_SIZE=3: continuations too
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            # updated values interleave ACROSS partitions, so partition-key
            # order (nsa, nsb, nsc) is NOT the sort order
            for i, ns in enumerate(("nsa", "nsb", "nsc") * 3):
                await store.put(f"{ns}/a{i}", {"entityType": "actions",
                                               "namespace": ns,
                                               "name": f"a{i}",
                                               "updated": i + 1})
            docs = await store.query("actions", None)
            assert len(docs) == 9
            assert [d["updated"] for d in docs] == list(range(9, 0, -1))
            asc = await store.query("actions", None, descending=False)
            assert [d["updated"] for d in asc] == list(range(1, 10))
            await store.close()
            await fake.stop()
        run(go())

    def test_cross_partition_count_pages_ids_across_ranges(self):
        """ISSUE 3 satellite: the fake answers a cross-partition
        `SELECT VALUE COUNT(1)` with one PARTIAL count per partition key
        range, so the store counts by paging ids instead — the total must
        cover every partition through the continuation loop."""
        async def go():
            fake = FakeCosmosDB()  # PAGE_SIZE=3 forces continuations
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            for i, ns in enumerate(("nsa", "nsb") * 4):
                await store.put(f"{ns}/a{i}", {"entityType": "actions",
                                               "namespace": ns,
                                               "name": f"a{i}",
                                               "updated": i + 1})
            assert await store.count("actions", None) == 8
            assert await store.count("actions", "nsa") == 4
            await store.close()
            await fake.stop()
        run(go())

    def test_attachment_names_reject_id_breaking_chars(self):
        """ISSUE 3 satellite: sidecar doc ids embed the attachment name, so
        '/' (adds a path segment), '|' (the id encoding maps it to '/' on
        read — the encode/decode asymmetry), and the Cosmos-forbidden
        '\\', '?', '#' must be rejected at attach() before a sidecar is
        written with an id that cannot round-trip."""
        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            store = CosmosDbArtifactStore(url, key=MASTER_KEY)
            await store.put("ns/a", {"entityType": "actions",
                                     "namespace": "ns", "name": "a",
                                     "updated": 1})
            for bad in ("co|de", "co/de", "co\\de", "co?de", "co#de", ""):
                with pytest.raises(ArtifactStoreException):
                    await store.attach("ns/a", bad, "text/plain", b"x")
            # nothing leaked into the collection as a sidecar
            coll = fake.dbs["whisks"]["whisks"]
            assert not any(i.startswith("att:") for (_, i) in coll)
            # the '|' asymmetry regression: had 'co|de' been written, its
            # sidecar id would decode with '/' where the '|' was, so the
            # name could never be read back under the name it was attached
            # with — a dotted name (legal) still round-trips exactly
            await store.attach("ns/a", "co.de-1", "text/plain", b"ok")
            assert (await store.read_attachment("ns/a", "co.de-1"))[1] == b"ok"
            await store.close()
            await fake.stop()
        run(go())

    def test_attachment_store_delegation_and_close(self):
        """r5 review: with_attachment_store must actually delegate (the
        >2MB escape hatch the docstring promises) and close() must close
        the wired attachment store."""
        from openwhisk_tpu.database import MemoryAttachmentStore

        async def go():
            fake = FakeCosmosDB()
            url = await fake.start()
            att = MemoryAttachmentStore()
            store = CosmosDbArtifactStore(
                url, key=MASTER_KEY).with_attachment_store(att)
            await store.put("ns/a", {"entityType": "actions",
                                     "namespace": "ns", "name": "a",
                                     "updated": 1})
            await store.attach("ns/a", "code", "text/plain", b"big")
            # bytes went to the attachment store, not a sidecar document
            coll = fake.dbs["whisks"]["whisks"]
            assert not any(i.startswith("att:") for (_, i) in coll)
            assert (await store.read_attachment("ns/a", "code"))[1] == b"big"
            await store.delete_attachments("ns/a")
            await store.close()
            await fake.stop()
        run(go())

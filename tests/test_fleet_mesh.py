"""Sharded-fleet balancer tests (ISSUE 13): the `('fleet',)` mesh kernels
must be BIT-EXACT with the single-device kernels — decisions, forced bits,
books, and repair-round counts — on the 8-way virtual CPU mesh, the
fleet-mesh balancer mode must place identically to the single-device
balancer (off switch = today's path, bit-exact), cluster grow/resize must
classify as expected reshard compiles, the occupancy/admin planes must
aggregate per-shard books host-side, and the calibration cache must key by
per-shard shape."""
import asyncio
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openwhisk_tpu.controller.loadbalancer import HEALTHY, TpuBalancer
from openwhisk_tpu.core.entity import (ActivationId, CodeExec,
                                       ControllerInstanceId, EntityName,
                                       EntityPath, ExecutableWhiskAction,
                                       Identity, InvokerInstanceId, MB,
                                       ActionLimits, MemoryLimit, TimeLimit)
from openwhisk_tpu.core.entity.ids import DocRevision
from openwhisk_tpu.messaging import (ActivationMessage,
                                     MemoryMessagingProvider)
from openwhisk_tpu.ops.placement import (RequestBatch, init_state,
                                         release_batch_vector,
                                         schedule_batch,
                                         schedule_batch_repair)
from openwhisk_tpu.parallel.fleet_mesh import (FLEET_AXIS, fleet_pair,
                                               make_fleet_mesh,
                                               make_fleet_release_vector,
                                               make_fleet_repair_schedule,
                                               mesh_shards, mesh_topology,
                                               shard_state)
from openwhisk_tpu.utils.transaction import TransactionId

pytestmark = pytest.mark.mesh

N_SHARDS = 8


@pytest.fixture(scope="module")
def mesh():
    return make_fleet_mesh(N_SHARDS)


def _rand_batch(rng, n, b, *, need=None, maxc_pool=(1, 1, 1, 4),
                slots=16, invalid_frac=0.1):
    """A random request batch over the whole [0, n) partition — mixed
    memory needs, shared-container actions (max_conc > 1), some invalid
    rows, randomized forced-placement rotations."""
    return RequestBatch(
        offset=jnp.zeros(b, jnp.int32),
        size=jnp.full(b, n, jnp.int32),
        home=jnp.asarray(rng.randint(0, n, b), jnp.int32),
        step_inv=jnp.ones(b, jnp.int32),
        need_mb=jnp.asarray(need if need is not None
                            else rng.choice([128, 256, 512], b), jnp.int32),
        conc_slot=jnp.asarray(rng.randint(0, slots, b), jnp.int32),
        max_conc=jnp.asarray(rng.choice(maxc_pool, b), jnp.int32),
        rand=jnp.asarray(rng.randint(0, n, b), jnp.int32),
        valid=jnp.asarray(rng.rand(b) > invalid_frac))


def _dirty_state(rng, n, slots=16, slot_mb=2048):
    """A partially-occupied state: random memory holds, random open
    containers with spare permits, a few unhealthy rows."""
    free = jnp.asarray(
        slot_mb - rng.choice([0, 128, 256, 1024], n), jnp.int32)
    conc = np.zeros((n, slots), np.int32)
    for _ in range(n // 2):
        conc[rng.randint(0, n), rng.randint(0, slots)] = rng.randint(1, 4)
    health = jnp.asarray(rng.rand(n) > 0.1)
    return init_state(n, [slot_mb] * n, n_pad=n, action_slots=slots
                      )._replace(free_mb=free, conc_free=jnp.asarray(conc),
                                 health=health)


def _same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def _states_equal(s1, s2):
    return (_same(s1.free_mb, s2.free_mb)
            and _same(s1.conc_free, s2.conc_free)
            and _same(s1.health, s2.health))


class TestFleetKernelParity:
    """The tentpole acceptance: sharded decisions, books AND round counts
    bit-identical to the single-device repair kernel on the 8-way virtual
    mesh — mixed traffic, forced overload, container-open permits,
    invalid rows, releases, chained steps."""

    def test_repair_parity_fuzz(self, mesh):
        sched = make_fleet_repair_schedule(mesh)
        rng = np.random.RandomState(7)
        for n, b in [(16, 8), (32, 32), (64, 64), (128, 96)]:
            for trial in range(3):
                st = _dirty_state(rng, n)
                batch = _rand_batch(rng, n, b)
                s1, c1, f1, r1 = schedule_batch_repair(st, batch)
                s2, c2, f2, r2 = sched(shard_state(st, mesh), batch)
                assert _same(c1, c2), (n, b, trial)
                assert _same(f1, f2), (n, b, trial)
                assert _states_equal(s1, s2), (n, b, trial)
                assert int(r1) == int(r2), (n, b, trial)

    def test_forced_overload_parity(self, mesh):
        """Needs far beyond capacity: every placement forces (over-commit
        books go negative) — the forced-candidate election must match the
        single-device argmin exactly."""
        sched = make_fleet_repair_schedule(mesh)
        rng = np.random.RandomState(11)
        n, b = 32, 48
        st = _dirty_state(rng, n)
        batch = _rand_batch(rng, n, b, need=np.full(b, 1900, np.int32),
                            maxc_pool=(1,))
        s1, c1, f1, r1 = schedule_batch_repair(st, batch)
        s2, c2, f2, r2 = sched(shard_state(st, mesh), batch)
        assert bool(np.asarray(f1).any()), "protocol must actually force"
        assert _same(c1, c2) and _same(f1, f2)
        assert _states_equal(s1, s2) and int(r1) == int(r2)

    def test_container_open_burst_parity(self, mesh):
        """Same-action bursts opening shared containers (max_conc > 1):
        the permit-grant cascade is the hardest conflict class — permits
        minted by an earlier commit can flip a later request's choice."""
        sched = make_fleet_repair_schedule(mesh)
        rng = np.random.RandomState(13)
        n, b = 32, 64
        st = init_state(n, [2048] * n, n_pad=n, action_slots=16)
        batch = _rand_batch(rng, n, b, maxc_pool=(4,), slots=4,
                            invalid_frac=0.0)
        s1, c1, f1, r1 = schedule_batch_repair(st, batch)
        s2, c2, f2, r2 = sched(shard_state(st, mesh), batch)
        assert _same(c1, c2) and _same(f1, f2)
        assert _states_equal(s1, s2) and int(r1) == int(r2)

    def test_release_vector_parity_incl_conflation(self, mesh):
        """The owner-masked vector release, including the heterogeneous
        slot-conflation residue (two actions sharing one hashed slot with
        different need/max_conc replay sequentially)."""
        rel = make_fleet_release_vector(mesh)
        rng = np.random.RandomState(17)
        n, r = 32, 48
        st = _dirty_state(rng, n)
        inv = jnp.asarray(rng.randint(0, n, r), jnp.int32)
        slot = jnp.asarray(rng.randint(0, 4, r), jnp.int32)
        need = jnp.asarray(rng.choice([128, 256], r), jnp.int32)
        maxc = jnp.asarray(rng.choice([1, 4, 6], r), jnp.int32)
        valid = jnp.asarray(rng.rand(r) > 0.15)
        s1 = release_batch_vector(st, inv, slot, need, maxc, valid)
        s2 = rel(shard_state(st, mesh), inv, slot, need, maxc, valid)
        assert _states_equal(s1, s2)

    def test_chained_steps_with_releases_parity(self, mesh):
        """Several fused-style rounds: schedule, then release what placed,
        then schedule again on the dirtied books — covers the production
        steady state where both kernels run back to back."""
        sched = make_fleet_repair_schedule(mesh)
        rel = make_fleet_release_vector(mesh)
        rng = np.random.RandomState(19)
        n, b = 64, 48
        st1 = init_state(n, [2048] * n, n_pad=n, action_slots=16)
        st2 = shard_state(st1, mesh)
        for step in range(4):
            batch = _rand_batch(rng, n, b)
            st1, c1, f1, r1 = schedule_batch_repair(st1, batch)
            st2, c2, f2, r2 = sched(st2, batch)
            assert _same(c1, c2) and int(r1) == int(r2), step
            inv = jnp.asarray(np.clip(np.asarray(c1), 0, None), jnp.int32)
            ok = jnp.asarray(np.asarray(c1) >= 0)
            st1 = release_batch_vector(st1, inv, batch.conc_slot,
                                       batch.need_mb, batch.max_conc, ok)
            st2 = rel(st2, inv, batch.conc_slot, batch.need_mb,
                      batch.max_conc, ok)
            assert _states_equal(st1, st2), step

    def test_scan_pair_parity(self, mesh):
        """fleet_pair('scan') keeps the prototype sharded scan — parity
        with the single-device scan (the legacy mesh path, still exact)."""
        sched, rel, resolved = fleet_pair(mesh, "scan")
        assert resolved == "scan"
        rng = np.random.RandomState(23)
        n, b = 32, 24
        st = _dirty_state(rng, n)
        batch = _rand_batch(rng, n, b)
        s1, c1, f1 = schedule_batch(st, batch)
        out = sched(shard_state(st, mesh), batch)
        s2, c2, f2 = out[0], out[1], out[2]
        assert _same(c1, c2) and _same(f1, f2) and _states_equal(s1, s2)

    def test_auto_pair_is_per_bucket_hybrid(self, mesh):
        """fleet_pair('auto') routes by static batch width exactly like
        _xla_pair: scan below repair_min_batch (rounds absent/0), repair
        at and above it (rounds >= 1) — both bit-exact with the oracle."""
        sched, rel, resolved = fleet_pair(mesh, "auto",
                                          repair_min_batch=32)
        assert resolved == "repair"
        assert getattr(sched, "_placement_hybrid", False)
        rng = np.random.RandomState(29)
        n = 32
        st = _dirty_state(rng, n)
        small = _rand_batch(rng, n, 8)
        big = _rand_batch(rng, n, 64)
        out_small = sched(shard_state(st, mesh), small)
        assert len(out_small) == 3  # the scan pair: no rounds element
        s1, c1, _f1 = schedule_batch(st, small)
        assert _same(c1, out_small[1])
        out_big = sched(shard_state(st, mesh), big)
        s2, c2, _f2, r2 = schedule_batch_repair(st, big)
        assert _same(c2, out_big[1]) and int(out_big[3]) == int(r2)

    def test_grow_reshard_continues_bit_exact(self, mesh):
        """Fleet growth = reshard: re-pad the invoker axis (holds
        preserved), reshard onto the same mesh, and keep placing — books
        and decisions must track the single-device kernel through the
        resize."""
        sched = make_fleet_repair_schedule(mesh)
        rng = np.random.RandomState(31)
        n1, n2, b = 32, 64, 24
        st1 = _dirty_state(rng, n1)
        st2 = shard_state(st1, mesh)
        batch = _rand_batch(rng, n1, b)
        st1, c1, _f, _r = schedule_batch_repair(st1, batch)
        st2, c2, _f2, _r2 = sched(st2, batch)
        assert _same(c1, c2)

        def grow(st, pad):
            free = np.zeros((pad,), np.int32)
            free[:n1] = np.asarray(st.free_mb)
            conc = np.zeros((pad, st.conc_free.shape[1]), np.int32)
            conc[:n1] = np.asarray(st.conc_free)
            health = np.zeros((pad,), bool)
            health[:n1] = np.asarray(st.health)
            # the new rows come up healthy at full capacity (registration)
            free[n1:] = 2048
            health[n1:] = True
            from openwhisk_tpu.ops.placement import PlacementState
            return PlacementState(jnp.asarray(free), jnp.asarray(conc),
                                  jnp.asarray(health))

        st1 = grow(st1, n2)
        st2 = shard_state(grow(st2, n2), mesh)
        batch2 = _rand_batch(rng, n2, b)
        st1, c1, _f, r1 = schedule_batch_repair(st1, batch2)
        st2, c2, _f2, r2 = sched(st2, batch2)
        assert _same(c1, c2) and int(r1) == int(r2)
        assert _states_equal(st1, st2)


# -- balancer level ---------------------------------------------------------

def _make_action(name="act", memory=256):
    a = ExecutableWhiskAction(EntityPath("guest"), EntityName(name),
                              CodeExec(kind="python:3", code="x"),
                              limits=ActionLimits(TimeLimit(5000),
                                                  MemoryLimit(MB(memory))))
    a.rev = DocRevision("1-b")
    return a


def _make_msg(action, ident):
    return ActivationMessage(TransactionId(), action.fully_qualified_name,
                             action.rev.rev, ident, ActivationId.generate(),
                             ControllerInstanceId("0"), False, {})


def _mk_balancer(provider, **kw):
    kw.setdefault("managed_fraction", 1.0)
    kw.setdefault("blackbox_fraction", 0.0)
    kw.setdefault("prewarm", False)
    kw.setdefault("initial_pad", 16)
    kw.setdefault("max_batch", 32)
    return TpuBalancer(provider, ControllerInstanceId("0"), **kw)


async def _drive(bal, n_invokers=12, waves=3, per_wave=40):
    """Register a fleet directly, publish identical traffic, and return
    the placement decisions in PUBLISH order plus the final books."""
    placed = {}

    async def fake_send(msg, invoker):
        placed[msg.activation_id.asString] = invoker.instance

    bal.send_activation_to_invoker = fake_send
    for i in range(n_invokers):
        bal._status_change(InvokerInstanceId(i, user_memory=MB(2048)),
                           HEALTHY)
    ident = Identity.generate("guest")
    actions = [_make_action(f"fm{i}", memory=[128, 256, 512][i % 3])
               for i in range(10)]
    ordered = []
    for _ in range(waves):
        msgs = [_make_msg(actions[i % 10], ident) for i in range(per_wave)]
        ordered += [m.activation_id.asString for m in msgs]
        await asyncio.gather(*[bal.publish(actions[i % 10], m)
                               for i, m in enumerate(msgs)])
    books = np.asarray(bal.state.free_mb).tolist()
    return [placed[a] for a in ordered], books


class TestFleetBalancer:
    def test_fleet_mode_places_like_single_device(self):
        """The production acceptance: identical publish traffic through
        the fleet-mesh balancer and the single-device balancer yields
        identical placements and identical books (the off switch IS the
        single-device path, so this is also the off-switch bit-exactness
        proof)."""
        async def go(fleet_mesh):
            bal = _mk_balancer(MemoryMessagingProvider(),
                               fleet_mesh=fleet_mesh,
                               fleet_shards=N_SHARDS)
            if fleet_mesh:
                assert bal.kernel_resolved == "sharded"
                assert bal.n_shards == N_SHARDS
                assert bal.fleet_axis == FLEET_AXIS
            else:
                assert bal.mesh is None and bal.n_shards == 1
            try:
                return await _drive(bal)
            finally:
                await bal.close()

        d_off, b_off = asyncio.run(go(False))
        d_on, b_on = asyncio.run(go(True))
        assert d_on == d_off, "fleet-mesh placements must be bit-exact"
        assert b_on == b_off, "fleet-mesh books must be bit-exact"

    def test_env_knob_builds_the_mesh(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_fleetMesh", "true")
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_fleetShards",
                           str(N_SHARDS))
        bal = _mk_balancer(MemoryMessagingProvider())
        assert bal.n_shards == N_SHARDS
        assert bal.fleet_axis == FLEET_AXIS
        asyncio.run(bal.close())

    def test_growth_resharding_classifies_expected(self):
        """Cluster grow = reshard event: registrations past the pad force
        a re-pad + reshard mid-traffic; every compile must classify
        expected (the PR 3 watchdog contract) and placement must keep
        working across the reshard."""
        async def go():
            os.environ["CONFIG_whisk_profiling_enabled"] = "true"
            try:
                bal = _mk_balancer(MemoryMessagingProvider(),
                                   fleet_mesh=True,
                                   fleet_shards=N_SHARDS)
            finally:
                os.environ.pop("CONFIG_whisk_profiling_enabled", None)
            placed = {}

            async def fake_send(msg, invoker):
                placed[msg.activation_id.asString] = invoker.instance

            bal.send_activation_to_invoker = fake_send
            for i in range(12):
                bal._status_change(
                    InvokerInstanceId(i, user_memory=MB(2048)), HEALTHY)
            ident = Identity.generate("guest")
            a = _make_action("grow", memory=128)
            await asyncio.gather(*[bal.publish(a, _make_msg(a, ident))
                                   for _ in range(12)])
            # grow past initial_pad=16 -> _grow_padding -> reshard
            for i in range(12, 20):
                bal._status_change(
                    InvokerInstanceId(i, user_memory=MB(2048)), HEALTHY)
            assert bal._n_pad == 32
            assert bal._n_pad % bal.n_shards == 0
            await asyncio.gather(*[bal.publish(a, _make_msg(a, ident))
                                   for _ in range(12)])
            prof = bal.kernel_profile()
            await bal.close()
            return prof, len(placed)

        prof, n_placed = asyncio.run(go())
        assert n_placed == 24
        assert prof["compiles"]["unexpected"] == 0
        assert any(c["reason"] == "reshard"
                   for c in prof["compiles"]["log"]), \
            "the re-pad compiles must classify under the reshard window"
        assert prof["mesh"] == {"n_shards": N_SHARDS, "axis": FLEET_AXIS}

    def test_occupancy_shards_block_and_gauges(self):
        """The admin/occupancy planes aggregate per-shard books from the
        HOST cache (never a device sync): the shard rows must sum to the
        fleet totals, and the supervision-tick gauges must export the
        shard count and per-shard ratios."""
        async def go():
            bal = _mk_balancer(MemoryMessagingProvider(), fleet_mesh=True,
                               fleet_shards=N_SHARDS)
            try:
                await _drive(bal, waves=1)
                occ = bal.occupancy()
                assert occ["mesh"] == {"n_shards": N_SHARDS,
                                       "axis": FLEET_AXIS}
                shards = occ["shards"]
                assert len(shards) == N_SHARDS
                assert sum(s["capacity_mb"] for s in shards) == \
                    occ["fleet"]["capacity_mb"]
                assert sum(s["used_mb"] for s in shards) == \
                    occ["fleet"]["used_mb"]
                assert sum(s["invokers"] for s in shards) == 12
                # API-path contract: serving occupancy never syncs device
                assert bal.OCCUPANCY_SYNCS_DEVICE is False
                bal._telemetry_tick()
                assert bal.metrics.gauge_value(
                    "loadbalancer_fleet_shards") == N_SHARDS
                for s in range(N_SHARDS):
                    assert bal.metrics.gauge_value(
                        "loadbalancer_shard_occupancy_ratio",
                        tags={"shard": str(s)}) is not None
            finally:
                await bal.close()

        asyncio.run(go())

    def test_snapshot_reshards_across_topologies(self):
        """Snapshots carry GLOBAL books: a single-device snapshot restores
        onto the mesh (deterministic reshard) and a mesh snapshot restores
        onto a single device — books preserved both ways, `fleet_shards`
        recorded."""
        async def go():
            single = _mk_balancer(MemoryMessagingProvider())
            await _drive(single, waves=1)
            snap1 = single.snapshot()
            assert snap1["fleet_shards"] == 1
            books1 = np.asarray(single.state.free_mb)[:12]
            await single.close()

            meshy = _mk_balancer(MemoryMessagingProvider(),
                                 fleet_mesh=True, fleet_shards=N_SHARDS)
            meshy.restore(snap1)
            assert _same(np.asarray(meshy.state.free_mb)[:12], books1)
            await _drive(meshy, waves=1)
            snap2 = meshy.snapshot()
            assert snap2["fleet_shards"] == N_SHARDS
            books2 = np.asarray(meshy.state.free_mb)[:12]
            await meshy.close()

            back = _mk_balancer(MemoryMessagingProvider())
            back.restore(snap2)
            assert _same(np.asarray(back.state.free_mb)[:12], books2)
            await back.close()

        asyncio.run(go())


class TestPerShardCalibration:
    """Satellite: `calibrate_backend_rates`/`cached_backend_choice` key by
    PER-SHARD shape (n_pad // n_shards), so a 256k-fleet/8-shard balancer
    calibrates — and a restarted one adopts — the 32k-row program it
    actually runs."""

    def test_cache_keys_by_shard_rows(self):
        import openwhisk_tpu.controller.loadbalancer.tpu_balancer as tb
        saved = dict(tb._KERNEL_CALIBRATION)
        tb._KERNEL_CALIBRATION.clear()
        try:
            platform = jax.default_backend()
            # a verdict measured at 64 rows (single device, n_pad=64)...
            tb._KERNEL_CALIBRATION[(platform, 64, 64, "auto", 8, 8, 8)] = {
                "rates": {"xla": 1.0, "pallas": 9.0}, "winner": "pallas",
                "platform": platform, "n_pad": 64, "shard_rows": 64,
                "n_shards": 1, "action_slots": 64,
                "placement_kernel": "auto", "sig": [8, 8, 8], "iters": 1}
            # ...is THE verdict for a 512-invoker fleet over 8 shards
            # (512 // 8 == 64 rows per device: the same program)
            assert tb.cached_backend_choice(512, 64, "auto",
                                            n_shards=8) == "pallas"
            # and calibrating that fleet geometry cache-hits it
            cal = tb.calibrate_backend_rates(512, 64, 8, 8, 8,
                                             placement_kernel="auto",
                                             n_shards=8)
            assert cal["winner"] == "pallas" and cal["shard_rows"] == 64
            # a cache hit re-stamps the CALLER's topology (the cached
            # value was measured single-device at n_pad=64) so admin
            # planes report their own geometry
            assert cal["n_pad"] == 512 and cal["n_shards"] == 8
            # a DIFFERENT per-shard shape does not match
            assert tb.cached_backend_choice(512, 64, "auto",
                                            n_shards=4) is None
        finally:
            tb._KERNEL_CALIBRATION.clear()
            tb._KERNEL_CALIBRATION.update(saved)

    def test_calibration_benches_the_per_shard_program(self):
        """An actual (tiny) calibration run at n_shards=2 must build and
        measure the shard_rows-row program and record both key halves."""
        import openwhisk_tpu.controller.loadbalancer.tpu_balancer as tb
        saved = dict(tb._KERNEL_CALIBRATION)
        tb._KERNEL_CALIBRATION.clear()
        try:
            cal = tb.calibrate_backend_rates(
                32, 16, 8, 8, 8, placement_kernel="scan",
                include_pallas=False, iters=1, warmup=1, n_shards=2)
            assert cal["shard_rows"] == 16 and cal["n_shards"] == 2
            assert cal["rates"]["xla"]
            key = (jax.default_backend(), 16, 16, "scan", 8, 8, 8)
            assert key in tb._KERNEL_CALIBRATION
        finally:
            tb._KERNEL_CALIBRATION.clear()
            tb._KERNEL_CALIBRATION.update(saved)

    def test_fleet_balancer_calibrates_per_shard_advisorily(self):
        """A fleet-mesh balancer with kernel='auto' + calibration forced
        runs the microbench at the PER-SHARD shape on its prewarm
        drainer: the verdict lands in the shared cache keyed by
        shard_rows and on the admin plane, but the running kernels never
        swap (the sharded pair has no xla/pallas choice)."""
        import openwhisk_tpu.controller.loadbalancer.tpu_balancer as tb
        saved = dict(tb._KERNEL_CALIBRATION)
        tb._KERNEL_CALIBRATION.clear()

        async def go():
            bal = _mk_balancer(MemoryMessagingProvider(), fleet_mesh=True,
                               fleet_shards=N_SHARDS, kernel="auto",
                               calibrate_kernel="force", prewarm=True)
            try:
                await bal.start()
                await _drive(bal, waves=1, per_wave=20)
                for _ in range(200):
                    if (bal._calibration is not None
                            and (bal._warm_task is None
                                 or bal._warm_task.done())):
                        break
                    await asyncio.sleep(0.05)
                assert bal._calibration is not None
                assert bal._calibration["n_shards"] == N_SHARDS
                assert bal._calibration["shard_rows"] == \
                    bal._n_pad // N_SHARDS
                # the cache is keyed by the per-shard rows
                assert any(k[1] == bal._n_pad // N_SHARDS
                           for k in tb._KERNEL_CALIBRATION)
                # advisory only: the sharded pair never swaps
                assert bal.kernel_resolved == "sharded"
                assert bal.kernel_profile()["calibration"]["shard_rows"] \
                    == bal._n_pad // N_SHARDS
            finally:
                await bal.close()

        try:
            asyncio.run(go())
        finally:
            tb._KERNEL_CALIBRATION.clear()
            tb._KERNEL_CALIBRATION.update(saved)

    def test_restart_rule_adopts_per_shard_verdict(self):
        """A fresh fleet-mesh-geometry balancer construction consults the
        per-shard cache (the cached-choice restart rule) — exercised via
        _resolve_kernel on a single-device balancer whose n_pad matches
        the seeded shard shape."""
        import openwhisk_tpu.controller.loadbalancer.tpu_balancer as tb
        saved = dict(tb._KERNEL_CALIBRATION)
        tb._KERNEL_CALIBRATION.clear()
        platform = jax.default_backend()
        tb._KERNEL_CALIBRATION[(platform, 16, 4096, "auto", 8, 8, 8)] = {
            "rates": {"xla": 1.0, "pallas": 9.0}, "winner": "pallas",
            "platform": platform, "n_pad": 16, "shard_rows": 16,
            "n_shards": 1, "action_slots": 4096,
            "placement_kernel": "auto", "sig": [8, 8, 8], "iters": 1}
        try:
            bal = _mk_balancer(MemoryMessagingProvider(), kernel="auto",
                               calibrate_kernel="off")
            assert bal._n_pad == 16
            assert bal.kernel_resolved == "pallas"
            assert bal._kernel_chosen_by == "calibration"
            asyncio.run(bal.close())
        finally:
            tb._KERNEL_CALIBRATION.clear()
            tb._KERNEL_CALIBRATION.update(saved)


class TestMeshTopologyHelpers:
    def test_mesh_topology_record(self, mesh):
        topo = mesh_topology(mesh)
        assert topo["n_shards"] == N_SHARDS
        assert topo["axis"] == FLEET_AXIS
        assert mesh_topology(None) == {"n_shards": 1, "axis": None}

    def test_make_fleet_mesh_rejects_non_pow2(self):
        with pytest.raises(ValueError):
            make_fleet_mesh(6)

    def test_make_fleet_mesh_default_pow2_floors(self, mesh):
        assert mesh_shards(make_fleet_mesh(None)) in (8, 4, 2, 1)
        # 0 is the knob's documented "all devices" value — same floor,
        # never the explicit-count validation path
        assert mesh_shards(make_fleet_mesh(0)) == \
            mesh_shards(make_fleet_mesh(None))


class TestFleetSweepRider:
    def test_sweep_row_parity_census_and_heal(self):
        """Satellite: the bench rider's in-process body on the virtual
        mesh — parity column true, MULTICHIP heal check folded in, zero
        unexpected recompiles, n_devices/mesh_axis recorded."""
        import bench
        out = bench._sharded_fleet_sweep_measure(
            fleet_sizes=(64,), n_devices=N_SHARDS, batch_size=32,
            iters=2, repeats=1)
        assert out["n_devices"] == N_SHARDS
        assert out["mesh_axis"] == FLEET_AXIS
        assert out["parity_all"] is True
        assert out["recompiles_unexpected"] == 0
        row = out["rows"][0]
        assert row["shard_rows"] == 64 // N_SHARDS
        assert row["books_heal"] is True
        assert row["rate_median"] > 0

"""Device kernels (JAX/XLA) — the accelerator-native pieces of the framework.

`placement` — the batched invoker-placement kernel (the hot loop of the
controller's load balancer, replacing ShardingContainerPoolBalancer.schedule's
per-activation CPU probe loop with a vectorized bin-packing step).
`throttle` — vectorized token-bucket admission for bulk entitlement checks.
"""
from .placement import (PlacementState, RequestBatch, init_state,
                        schedule_batch, release_batch, set_health)
from .profiler import KernelProfiler, ProfilingConfig
from .throttle import TokenBucketState, admit_batch, init_buckets

__all__ = [n for n in dir() if not n.startswith("_")]

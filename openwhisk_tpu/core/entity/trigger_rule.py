"""Triggers and rules.

Refs: WhiskTrigger.scala (a trigger doc embeds its rules as a map
rule-fqn -> ReducedRule(action, status)) and WhiskRule.scala (+ Status:
ACTIVE/INACTIVE, docs in core/controller/.../Rules.scala). Firing a trigger
activates every ACTIVE rule's action (Triggers.scala:320-381) — in this
framework via direct internal dispatch, not an HTTP loopback.
"""
from __future__ import annotations

from typing import Dict, Optional

from .entity import WhiskEntity
from .limits import LimitViolation
from .names import EntityName, EntityPath, FullyQualifiedEntityName
from .parameters import Parameters
from .semver import SemVer

ACTIVE = "active"
INACTIVE = "inactive"
_STATUSES = (ACTIVE, INACTIVE)


class Status:
    @staticmethod
    def validate(s: str) -> str:
        if s not in _STATUSES:
            raise LimitViolation(f"invalid rule status {s!r}")
        return s


class ReducedRule:
    __slots__ = ("action", "status")

    def __init__(self, action: FullyQualifiedEntityName, status: str = ACTIVE):
        self.action = action
        self.status = Status.validate(status)

    def to_json(self):
        return {"action": str(self.action), "status": self.status}

    @classmethod
    def from_json(cls, j):
        return cls(FullyQualifiedEntityName.parse(j["action"]), j.get("status", ACTIVE))


class WhiskTrigger(WhiskEntity):
    collection = "triggers"

    def __init__(self, namespace: EntityPath, name: EntityName,
                 parameters: Optional[Parameters] = None,
                 rules: Optional[Dict[str, ReducedRule]] = None,
                 version: Optional[SemVer] = None, publish: bool = False,
                 annotations: Optional[Parameters] = None,
                 updated: Optional[float] = None):
        super().__init__(namespace, name, version, publish, annotations, updated)
        self.parameters = parameters or Parameters()
        self.rules = dict(rules or {})

    def add_rule(self, rule_fqn: str, rule: ReducedRule) -> "WhiskTrigger":
        self.rules[rule_fqn] = rule
        return self

    def remove_rule(self, rule_fqn: str) -> "WhiskTrigger":
        self.rules.pop(rule_fqn, None)
        return self

    def to_json(self) -> dict:
        j = self.base_json()
        j["parameters"] = self.parameters.to_json()
        j["rules"] = {k: r.to_json() for k, r in self.rules.items()}
        return j

    @classmethod
    def from_json(cls, j: dict) -> "WhiskTrigger":
        return cls(
            EntityPath(j["namespace"]), EntityName(j["name"]),
            Parameters.from_json(j.get("parameters")),
            {k: ReducedRule.from_json(r) for k, r in j.get("rules", {}).items()},
            SemVer.from_string(j.get("version", "0.0.1")),
            bool(j.get("publish", False)),
            Parameters.from_json(j.get("annotations")),
            (j.get("updated", 0) / 1000.0) or None,
        )


class WhiskRule(WhiskEntity):
    collection = "rules"

    def __init__(self, namespace: EntityPath, name: EntityName,
                 trigger: FullyQualifiedEntityName, action: FullyQualifiedEntityName,
                 version: Optional[SemVer] = None, publish: bool = False,
                 annotations: Optional[Parameters] = None,
                 updated: Optional[float] = None):
        super().__init__(namespace, name, version, publish, annotations, updated)
        self.trigger = trigger
        self.action = action

    def to_json(self) -> dict:
        j = self.base_json()
        j["trigger"] = str(self.trigger)
        j["action"] = str(self.action)
        return j

    @classmethod
    def from_json(cls, j: dict) -> "WhiskRule":
        return cls(
            EntityPath(j["namespace"]), EntityName(j["name"]),
            FullyQualifiedEntityName.parse(j["trigger"]),
            FullyQualifiedEntityName.parse(j["action"]),
            SemVer.from_string(j.get("version", "0.0.1")),
            bool(j.get("publish", False)),
            Parameters.from_json(j.get("annotations")),
            (j.get("updated", 0) / 1000.0) or None,
        )

"""Activation latency waterfall: per-activation stage timestamps.

The observability stack so far (flight recorder, telemetry, profiler,
anomaly planes — PRs 1-4) watches the balancer's *interior*. The end-to-end
path around it — accept → entitle → throttle → enqueue → assemble →
dispatch → readback → produce → pickup → acquire → run → ack → record —
was a black box: BENCH_r04 measured 342 activations/s with a 140 ms publish
p50 and nothing could say *where* the 140 ms lives. This plane answers
that: every activation carries a fixed-enum stage vector of monotonic-ns
stamps, folded at completion into per-stage log2 histograms, a
dominant-stage counter (tail attribution: which stage most often dominates
the slowest activations) and a slowest-exemplar ring joined to
flight-recorder trace ids.

Design (same shape as the tracer: one process-global instance, because the
stages span layers that do not share a balancer reference — the API
handler, the entitlement pipeline, the messaging producers, the invoker,
the container pool and the record batcher all stamp into it; the balancer's
CommonLoadBalancer hook owns rendering and the admin read side):

  ctx   = [t0_ns, trace_id, s_0 .. s_12]   one small list per activation
  stamp = first-wins write of monotonic_ns into the stage slot (first-wins
          makes re-sends / ack-vs-store races idempotent)
  finish (at completion_ack) folds deltas between consecutive *present*
          stamps into int64[13, B] histograms — absent stages simply do
          not contribute, so partial pipelines (echo invokers, CPU twins)
          stay honest and the per-activation deltas always telescope to
          exactly (last stamp - t0).

Hot-path budget: one dict get + one list write per stamp; finish is ~13
integer bucket folds under a lock. Disabled
(`CONFIG_whisk_waterfall_enabled=false`) is a true no-op: open() returns
None, stamps find no context, no dict entry or array is ever touched.

Clock note: t0 may be injected (the open-loop load generator anchors it at
the *scheduled* arrival time, so the first stage delta carries the
coordinated-omission send lag) and must share time.monotonic_ns()'s epoch.

Known race, by design: the invoker sends the completion ack *before* it
stores the activation record, and the controller consumes the ack
asynchronously — so `record_write` may stamp before `completion_ack`
(clamped to a 0 delta) or land after finish() (dropped). Every other stage
pair is causally ordered.
"""
from __future__ import annotations

import functools
import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from .config import load_config
from .eventlog import identity
from .ring_buffer import SeqRingBuffer

#: the fixed stage enum — index order IS the causal pipeline order
STAGES = (
    "api_accept",         # request routed + parsed at the REST handler
    "entitle",            # entitlement (rights) check passed
    "throttle",           # rate/concurrency throttle passed
    "spill_forward",      # overflow row forwarded to a peer controller
                          # (active/active spillover; origin-side terminal
                          # stage — the peer's books own the rest)
    "publish_enqueue",    # balancer accepted the activation into its queue
    "batch_assemble",     # micro-batch packed host-side (TPU balancer)
    "device_dispatch",    # device program dispatched
    "device_readback",    # placement read back from the device
    "produce",            # activation message handed to the bus
    "invoker_pickup",     # invoker parsed the message off its topic
    "container_acquire",  # container pool granted a proxy
    "run",                # user code finished (active ack sent)
    "completion_ack",     # controller processed the completion ack
    "record_write",       # activation record persisted (may race the ack)
)
(STAGE_API_ACCEPT, STAGE_ENTITLE, STAGE_THROTTLE, STAGE_SPILL_FORWARD,
 STAGE_PUBLISH_ENQUEUE,
 STAGE_BATCH_ASSEMBLE, STAGE_DEVICE_DISPATCH, STAGE_DEVICE_READBACK,
 STAGE_PRODUCE, STAGE_INVOKER_PICKUP, STAGE_CONTAINER_ACQUIRE, STAGE_RUN,
 STAGE_COMPLETION_ACK, STAGE_RECORD_WRITE) = range(len(STAGES))
N_STAGES = len(STAGES)

#: ctx layout: [t0_ns, trace_id] + one stamp slot per stage
_CTX_T0, _CTX_TRACE = 0, 1
_CTX_BASE = 2

#: how often (in finished activations) the tail-bucket threshold — the p99
#: bucket of the total-latency histogram — is recomputed
_TAIL_REFRESH = 64


@dataclass(frozen=True)
class WaterfallConfig:
    """`CONFIG_whisk_waterfall_*` env overrides."""
    enabled: bool = True
    #: completed-row ring (the recent/slowest exemplar source)
    ring: int = 512
    #: log2 stage-duration buckets: bucket i covers (2^(i-1), 2^i] us —
    #: 30 buckets span 1 us .. ~9 min (sub-ms resolution matters here:
    #: assembly/dispatch phases live around 100 us)
    buckets: int = 30
    #: slowest-activation exemplar rows kept
    exemplars: int = 8
    #: in-flight stamp-vector cap; past it the oldest context is evicted
    #: (counted) so abandoned activations cannot grow the map unboundedly
    max_active: int = 65536


def bucket_of_us(v: int, n_buckets: int) -> int:
    """Integer-exact log2 bucket: the smallest i with 2^i us >= v (v <= 1
    lands in bucket 0); the last bucket is the overflow."""
    if v <= 1:
        return 0
    return min(int(v - 1).bit_length(), n_buckets - 1)


@functools.lru_cache(maxsize=8)
def bucket_bounds_ms(n_buckets: int) -> List[float]:
    """Finite upper bounds in ms (2^i us); the implicit last is +Inf.
    Cached: tail_threshold_ms() reads the bounds once per completion
    verdict (ISSUE 18) — callers must not mutate the returned list."""
    return [(2 ** i) / 1000.0 for i in range(max(1, n_buckets - 1))]


class ActivationWaterfall:
    """The stage-timestamp plane. Stamps run on the event loop (or any
    thread — dict get/set and list writes are GIL-atomic); finish() and the
    read side serialize on one lock around the numpy aggregates."""

    def __init__(self, config: Optional[WaterfallConfig] = None):
        self.config = config or WaterfallConfig()
        self.enabled = self.config.enabled
        self.n_buckets = max(4, int(self.config.buckets))
        self._active: Dict[str, list] = {}
        self._lock = threading.Lock()
        self.evicted_active = 0
        self._reset_aggregates()

    def _reset_aggregates(self) -> None:
        b = self.n_buckets
        #: per-stage duration histograms (stage delta = time since the
        #: previous PRESENT stamp) + sums for `_sum`/mean. Plain Python
        #: int lists, NOT numpy: finish() does ~15 single-element
        #: increments per activation, where a numpy scalar index costs
        #: ~1-2 us each vs ~50 ns for a list slot — at hundreds of
        #: activations/s that difference IS the plane's overhead budget
        self._hist = [[0] * b for _ in range(N_STAGES)]
        self._sum_us = [0] * N_STAGES
        self._stage_count = [0] * N_STAGES
        #: end-to-end (t0 -> last stamp) histogram
        self._total_hist = [0] * b
        self._total_sum_us = 0
        #: dominant-stage counters: which stage carried the largest delta,
        #: over all activations and over the tail (total >= the p99 bucket)
        self._dominant = [0] * N_STAGES
        self._dominant_tail = [0] * N_STAGES
        self._tail_bucket = self.n_buckets - 1
        self._finished = 0
        self._ring: SeqRingBuffer[dict] = SeqRingBuffer(
            max(8, int(self.config.ring)))
        #: (total_us, tiebreak, row) kept sorted ascending, capped at
        #: config.exemplars (the counter keeps equal totals comparable)
        self._slowest: List[tuple] = []
        self._slow_seq = 0

    @classmethod
    def from_config(cls) -> "ActivationWaterfall":
        return cls(load_config(WaterfallConfig, env_path="waterfall"))

    def reset(self) -> None:
        """Drop all state (bench riders isolate measured windows)."""
        with self._lock:
            self._active.clear()
            self.evicted_active = 0
            self._reset_aggregates()

    # -- write side --------------------------------------------------------
    def open(self, t0_ns: Optional[int] = None,
             trace_id: Optional[str] = None) -> Optional[list]:
        """A fresh, not-yet-adopted stage vector anchored at `t0_ns`
        (default: now). The open-loop load generator anchors at the
        SCHEDULED arrival time so the first stage delta is
        coordinated-omission-correct. None when disabled."""
        if not self.enabled:
            return None
        return [t0_ns if t0_ns is not None else time.monotonic_ns(),
                trace_id] + [0] * N_STAGES

    def adopt(self, aid: str, ctx: Optional[list],
              trace_id: Optional[str] = None) -> None:
        """Register the context under its activation id (the id is minted
        after the first stamps: api_accept/entitle/throttle land on the
        un-adopted ctx)."""
        if ctx is None or not self.enabled:
            return
        if trace_id is not None:
            ctx[_CTX_TRACE] = trace_id
        if len(self._active) >= self.config.max_active:
            # insertion-ordered dict: the first key is the oldest context
            try:
                self._active.pop(next(iter(self._active)))
                self.evicted_active += 1
            except (StopIteration, KeyError):
                pass
        self._active[aid] = ctx

    def begin(self, aid: str, t0_ns: Optional[int] = None,
              trace_id: Optional[str] = None) -> Optional[list]:
        """open() + adopt() for callers that already know the id."""
        ctx = self.open(t0_ns=t0_ns, trace_id=trace_id)
        self.adopt(aid, ctx)
        return ctx

    @staticmethod
    def stamp_ctx(ctx: Optional[list], stage: int,
                  now_ns: Optional[int] = None) -> None:
        """Stamp a stage on an un-adopted context (first write wins)."""
        if ctx is not None and ctx[_CTX_BASE + stage] == 0:
            ctx[_CTX_BASE + stage] = (now_ns if now_ns is not None
                                      else time.monotonic_ns())

    def stamp(self, aid: str, stage: int,
              now_ns: Optional[int] = None) -> None:
        """Stamp a stage for an in-flight activation; silently ignores ids
        this process is not tracking (cross-process bus peers, finished or
        disabled activations) — that silence IS the off-switch."""
        ctx = self._active.get(aid)
        if ctx is not None and ctx[_CTX_BASE + stage] == 0:
            ctx[_CTX_BASE + stage] = (now_ns if now_ns is not None
                                      else time.monotonic_ns())

    def stamp_many(self, aids, stage: int,
                   now_ns: Optional[int] = None) -> None:
        """One shared timestamp for a whole micro-batch (the TPU balancer's
        assemble/dispatch/readback edges are batch events)."""
        if not self.enabled:
            return
        now = now_ns if now_ns is not None else time.monotonic_ns()
        slot = _CTX_BASE + stage
        active = self._active
        for aid in aids:
            ctx = active.get(aid)
            if ctx is not None and ctx[slot] == 0:
                ctx[slot] = now

    def discard(self, aid: str) -> None:
        """Forget an activation that will never complete (publish failure,
        throttle rejection) without polluting the histograms."""
        self._active.pop(aid, None)

    def ctx_of(self, aid: str) -> Optional[list]:
        return self._active.get(aid)

    @property
    def active(self) -> int:
        return len(self._active)

    # -- finish: fold one activation into the aggregates -------------------
    def _compute_row(self, aid: str, ctx: list) -> Optional[dict]:
        """The lock-free half of finish(): stage deltas + the row dict."""
        t0 = ctx[_CTX_T0]
        deltas_us = [0] * N_STAGES
        stamped = 0
        clamped = 0
        prev = t0
        for i in range(N_STAGES):
            s = ctx[_CTX_BASE + i]
            if s == 0:
                deltas_us[i] = -1  # absent
                continue
            stamped += 1
            # clamp: record_write may stamp before completion_ack (the
            # ack-vs-store race) — its delta reads 0, never negative.
            # Any OTHER out-of-order pair is counted: the pipeline stages
            # are causally ordered, so a clamp there is an
            # instrumentation bug the soak test asserts against.
            if s < prev and i != STAGE_RECORD_WRITE:
                clamped += 1
            deltas_us[i] = max(0, (s - prev) // 1000)
            prev = max(prev, s)
        if stamped == 0:
            return None
        total_us = max(0, (prev - t0) // 1000)
        return {
            "activation_id": aid,
            "trace_id": ctx[_CTX_TRACE],
            "ts": time.time(),
            "total_us": total_us,
            "deltas_us": deltas_us,
            "clamped": clamped,
        }

    def _fold_locked(self, row: dict) -> None:
        """Fold one computed row into the aggregates (self._lock held)."""
        nb = self.n_buckets
        deltas_us = row["deltas_us"]
        total_us = row["total_us"]
        dom, dom_delta = -1, -1
        for i in range(N_STAGES):
            d = deltas_us[i]
            if d < 0:
                continue
            self._hist[i][bucket_of_us(d, nb)] += 1
            self._sum_us[i] += d
            self._stage_count[i] += 1
            if d > dom_delta:
                dom, dom_delta = i, d
        tb = bucket_of_us(total_us, nb)
        self._total_hist[tb] += 1
        self._total_sum_us += total_us
        if dom >= 0:
            self._dominant[dom] += 1
            if tb >= self._tail_bucket:
                self._dominant_tail[dom] += 1
        self._finished += 1
        if self._finished % _TAIL_REFRESH == 0:
            self._tail_bucket = self._pctl_bucket(self._total_hist, 0.99)
        self._ring.append(row)
        self._note_slow(total_us, row)

    def finish(self, aid: str) -> Optional[dict]:
        """Fold the stage vector into the histograms and file the row.
        Called when the completion ack lands (the last causally-ordered
        stage); a record_write stamped later finds nothing and no-ops."""
        ctx = self._active.pop(aid, None)
        if ctx is None:
            return None
        row = self._compute_row(aid, ctx)
        if row is None:
            return None
        with self._lock:
            self._fold_locked(row)
        return row

    def finish_many(self, aids, rows_out: Optional[list] = None) -> int:
        """The batch-shaped completion path's fold: N finishes under ONE
        lock acquisition (the per-ack lock round trip was real work at
        thousands of completions/s). Semantically identical to calling
        finish() per id; returns how many rows folded. `rows_out` (ISSUE
        18) collects the computed rows for the caller — the trace store's
        completion verdict reads them without recomputing the vectors."""
        rows = []
        pop = self._active.pop
        for aid in aids:
            ctx = pop(aid, None)
            if ctx is not None:
                row = self._compute_row(aid, ctx)
                if row is not None:
                    rows.append(row)
        if rows_out is not None:
            rows_out.extend(rows)
        if not rows:
            return 0
        with self._lock:
            for row in rows:
                self._fold_locked(row)
        return len(rows)

    def _note_slow(self, total_us: int, row: dict) -> None:
        sl = self._slowest
        cap = self.config.exemplars
        if cap <= 0:  # exemplars disabled by config
            return
        if len(sl) < cap or total_us > sl[0][0]:
            import bisect
            self._slow_seq += 1
            bisect.insort(sl, (total_us, self._slow_seq, row))
            if len(sl) > self.config.exemplars:
                sl.pop(0)

    # -- read side ---------------------------------------------------------
    def tail_threshold_ms(self) -> Optional[float]:
        """The live tail threshold for the trace store's `slow` verdict
        (ISSUE 18): the upper bound of the host-side p99 bucket, already
        refreshed every `_TAIL_REFRESH` finishes by the fold — reading it
        is one GIL-atomic attribute load, no lock, no scan. None while
        the series is empty or the p99 sits in the overflow bucket (the
        caller falls back to the SLO e2e target)."""
        tb = self._tail_bucket
        bounds = bucket_bounds_ms(self.n_buckets)
        if self._finished == 0 or tb >= len(bounds):
            return None
        return bounds[tb]

    @staticmethod
    def _pctl_bucket(counts: List[int], q: float) -> int:
        total = sum(counts)
        if total == 0:
            return len(counts) - 1
        target = max(1, math.ceil(q * total))
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                return i
        return len(counts) - 1

    def _pctl_ms(self, counts: List[int], q: float) -> Optional[float]:
        """Upper bound (ms) of the bucket holding the q-quantile; None for
        an empty series or a quantile in the overflow bucket."""
        if not sum(counts):
            return None
        b = self._pctl_bucket(counts, q)
        bounds = bucket_bounds_ms(self.n_buckets)
        return bounds[b] if b < len(bounds) else None

    def stage_report(self) -> List[dict]:
        with self._lock:
            hist = [list(h) for h in self._hist]
            sums = list(self._sum_us)
            counts = list(self._stage_count)
        out = []
        for i, name in enumerate(STAGES):
            n = int(counts[i])
            out.append({
                "stage": name,
                "count": n,
                "mean_ms": round(float(sums[i]) / n / 1000.0, 3) if n else None,
                "p50_ms": self._pctl_ms(hist[i], 0.50),
                "p90_ms": self._pctl_ms(hist[i], 0.90),
                "p99_ms": self._pctl_ms(hist[i], 0.99),
            })
        return out

    def budget(self) -> dict:
        """The tail budget: per-stage medians vs the measured e2e median.
        Computed from the EXACT deltas of the last `ring` completed rows
        (not the log2 histograms — bucket upper-bound rounding could
        overstate a 13-term sum by up to 2x): per-activation deltas
        telescope to exactly (last stamp - t0), so on steady traffic the
        stage medians sum to ~the e2e median with no unaccounted gap."""
        with self._lock:
            rows = self._ring.last(self._ring.size)
        if not rows:
            return {"stage_medians_ms": {}, "stage_median_sum_ms": 0.0,
                    "e2e_p50_ms": None, "e2e_p99_ms": None,
                    "e2e_mean_ms": None, "count": 0, "window": 0,
                    "coverage_ratio": None}

        def pctl(xs: list, q: float) -> float:
            return xs[min(len(xs) - 1, int(q * len(xs)))]

        medians = {}
        for i, name in enumerate(STAGES):
            vals = sorted(r["deltas_us"][i] for r in rows
                          if r["deltas_us"][i] >= 0)
            if vals:
                medians[name] = round(pctl(vals, 0.50) / 1000.0, 3)
        budget_sum = sum(medians.values())

        # band decomposition: average the stage deltas of the activations
        # AROUND a quantile of the total. Per activation the deltas
        # telescope to exactly the total (absent stages contribute 0 and
        # their time is absorbed by the next present stage's delta), so
        # band sums match the band's e2e by construction — unlike raw
        # per-stage medians, which need not add up (stage durations are
        # not independent: a long queue wait pairs with a short assemble)
        by_total = sorted(rows, key=lambda r: r["total_us"])
        n = len(by_total)

        def band(sel: list) -> tuple:
            """(per-stage mean deltas, mean e2e) over the band's rows —
            per activation the deltas telescope to the total, so the
            stage sums match the band's own e2e up to clamp/rounding."""
            acc = [0] * N_STAGES
            tot = 0
            for r in sel:
                tot += r["total_us"]
                for i, d in enumerate(r["deltas_us"]):
                    if d > 0:
                        acc[i] += d
            return ({STAGES[i]: round(acc[i] / len(sel) / 1000.0, 3)
                     for i in range(N_STAGES) if acc[i]},
                    tot / len(sel) / 1000.0)

        mid = min(n - 1, n // 2)
        k = max(1, n // 20)
        p50_decomp, p50_band_e2e = band(
            by_total[max(0, mid - k): mid + k + 1])
        p99_decomp, p99_band_e2e = band(
            by_total[min(n - 1, int(0.99 * n)):])
        totals = sorted(r["total_us"] for r in rows)
        e2e_p50 = pctl(totals, 0.50) / 1000.0
        decomp_sum = sum(p50_decomp.values())
        return {
            "stage_medians_ms": medians,
            "stage_median_sum_ms": round(budget_sum, 3),
            #: where the MEDIAN-band activation's time goes
            "p50_decomposition_ms": p50_decomp,
            "p50_decomposition_sum_ms": round(decomp_sum, 3),
            "p50_band_e2e_ms": round(p50_band_e2e, 3),
            #: where the p99 tail's time goes (the stage to attack)
            "p99_decomposition_ms": p99_decomp,
            "p99_decomposition_sum_ms": round(sum(p99_decomp.values()), 3),
            "p99_band_e2e_ms": round(p99_band_e2e, 3),
            "e2e_p50_ms": round(e2e_p50, 3),
            "e2e_p99_ms": round(pctl(totals, 0.99) / 1000.0, 3),
            "e2e_mean_ms": round(sum(totals) / len(totals) / 1000.0, 3),
            "count": len(totals),
            "window": len(rows),
            #: the accounting check ("no unaccounted gap"): the band's
            #: stage sums vs the SAME band's e2e — deviates from 1 only
            #: through clamping (out-of-order stamps) or rounding, never
            #: through sampling skew. External comparisons (stage budget
            #: vs a generator's independently measured e2e) live with the
            #: measurement, e.g. tools/loadgen.py's budget_vs_measured_p50.
            "coverage_ratio": (round(decomp_sum / p50_band_e2e, 3)
                               if p50_band_e2e else None),
        }

    def tail_attribution(self) -> dict:
        with self._lock:
            dom = list(self._dominant)
            tail = list(self._dominant_tail)
            tb = self._tail_bucket
        bounds = bucket_bounds_ms(self.n_buckets)
        return {
            "tail_threshold_ms": bounds[tb] if tb < len(bounds) else None,
            "dominant": {STAGES[i]: int(dom[i])
                         for i in range(N_STAGES) if dom[i]},
            "dominant_tail": {STAGES[i]: int(tail[i])
                              for i in range(N_STAGES) if tail[i]},
        }

    def _row_json(self, row: dict) -> dict:
        out = {
            "activation_id": row["activation_id"],
            "trace_id": row["trace_id"],
            "ts": row["ts"],
            "total_ms": round(row["total_us"] / 1000.0, 3),
            "stages_ms": {STAGES[i]: round(d / 1000.0, 3)
                          for i, d in enumerate(row["deltas_us"]) if d >= 0},
            "clamped": row.get("clamped", 0),
        }
        # federation annotations (ISSUE 16): a merged fleet report marks
        # rows joined across a spill_forward boundary with both halves'
        # provenance — plain per-process rows never carry these keys
        for k in ("joined", "origin_instance", "peer_instance", "instance"):
            if k in row:
                out[k] = row[k]
        return out

    def slowest(self) -> List[dict]:
        with self._lock:
            rows = [r for _, _, r in reversed(self._slowest)]
        return [self._row_json(r) for r in rows]

    def recent(self, n: int = 20) -> List[dict]:
        with self._lock:
            rows = self._ring.last(n)
        return [self._row_json(r) for r in rows]

    def report(self, recent: int = 0) -> dict:
        """The `GET /admin/latency/waterfall` payload. Host-side numpy
        only — never a device sync, so it runs inline on the event loop."""
        if not self.enabled:
            # no identity on the disabled snapshot: the off-switch keeps
            # the payload byte-identical to pre-federation builds, and the
            # fleet mergers drop disabled members before keying anyway
            return {"enabled": False}
        out = {
            "enabled": True,
            # the federation's merge key (ISSUE 16): which process this
            # snapshot came from
            "identity": identity(),
            "stages": list(STAGES),
            "finished": self._finished,
            "active": len(self._active),
            "evicted_active": self.evicted_active,
            "buckets_le_ms": bucket_bounds_ms(self.n_buckets),
            "per_stage": self.stage_report(),
            "budget": self.budget(),
            "tail": self.tail_attribution(),
            "slowest": self.slowest(),
        }
        if recent:
            out["recent"] = self.recent(recent)
        return out

    def raw_counts(self, rows: int = 0) -> dict:
        """The exact-merge export behind `?raw=1` (ISSUE 16): integer
        bucket counts and sums, NOT percentiles — percentiles do not
        compose across processes, bucket counts merge bucket-wise
        bit-exactly. `rows` > 0 additionally ships the most recent ring
        rows (raw deltas_us), which the fleet merger needs to join a
        spilled activation's origin/peer halves by activation id."""
        with self._lock:
            out = {
                "identity": identity(),
                "enabled": self.enabled,
                "buckets": self.n_buckets,
                "stages": list(STAGES),
                "hist": [list(h) for h in self._hist],
                "sum_us": list(self._sum_us),
                "stage_count": list(self._stage_count),
                "total_hist": list(self._total_hist),
                "total_sum_us": int(self._total_sum_us),
                "dominant": list(self._dominant),
                "dominant_tail": list(self._dominant_tail),
                "finished": int(self._finished),
                "rows": ([dict(r) for r in self._ring.last(rows)]
                         if rows else []),
            }
        return out

    # -- exposition --------------------------------------------------------
    def prometheus_text(self, openmetrics: bool = False) -> str:
        """`openwhisk_activation_stage_duration_seconds{stage=...}` as a
        real cumulative-`le` histogram family plus the dominant-stage
        counter (rendering shared with the telemetry plane)."""
        if not self.enabled:
            return ""
        from ..controller.monitoring import (counter_family_text,
                                             histogram_family_text)
        with self._lock:
            hist = [list(h) for h in self._hist]
            sums = list(self._sum_us)
            dom = list(self._dominant)
            tail = list(self._dominant_tail)
        bounds = bucket_bounds_ms(self.n_buckets)
        rows = [(STAGES[i], hist[i], sums[i] / 1000.0)
                for i in range(N_STAGES) if sum(hist[i])]
        out = histogram_family_text(
            "openwhisk_activation_stage_duration_seconds", "stage",
            rows, bounds)
        out += counter_family_text(
            "openwhisk_activation_dominant_stage_total",
            [({"stage": STAGES[i], "scope": scope}, int(arr[i]))
             for scope, arr in (("all", dom), ("tail", tail))
             for i in range(N_STAGES) if arr[i]],
            openmetrics=openmetrics)
        return "\n".join(out)


#: the process-wide plane every layer stamps into (same pattern as
#: GLOBAL_TRACER): the API handler, entitlement, messaging producers,
#: invoker, container pool and record batcher have no balancer reference —
#: the balancer hook (CommonLoadBalancer) owns rendering and admin reads
GLOBAL_WATERFALL = ActivationWaterfall.from_config()

"""wsk: the user CLI, speaking the REST API.

The framework's counterpart of the reference's `wsk` client (driven in its
system tests via WskCliOperations): action/trigger/rule/package/activation
operations over /api/v1.

  export WSK_APIHOST=http://127.0.0.1:3233 WSK_AUTH=<uuid>:<key>
  python -m openwhisk_tpu.tools.wsk action create hello hello.py
  python -m openwhisk_tpu.tools.wsk action invoke hello -p name TPU -b -r
"""
from __future__ import annotations

import argparse
import asyncio
import base64
import json
import os
import sys

import aiohttp


class WskClient:
    def __init__(self, apihost: str, auth: str):
        self.base = apihost.rstrip("/") + "/api/v1"
        self.headers = {
            "Authorization": "Basic " + base64.b64encode(auth.encode()).decode(),
            "Content-Type": "application/json",
        }

    async def request(self, method: str, path: str, body=None, params=None):
        try:
            async with aiohttp.ClientSession() as s:
                async with s.request(method, self.base + path, json=body,
                                     params=params or {}, headers=self.headers) as r:
                    try:
                        data = await r.json()
                    except aiohttp.ContentTypeError:
                        data = {"raw": await r.text()}
                    return r.status, data
        except aiohttp.ClientConnectionError as e:
            return 503, {"error": f"cannot reach API host {self.base}: {e}"}


def _params_to_dict(pairs):
    out = {}
    for k, v in pairs or []:
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def _kv_list(d):
    return [{"key": k, "value": v} for k, v in d.items()]


def _feed_action_path(feed: str, ns: str):
    """Resolve a feed name to (namespace, action path): a leading slash
    means fully qualified (`/ns/name` or `/ns/pkg/name`); without it,
    `name` and `pkg/name` are relative to the caller's namespace and three
    segments are fully qualified (packages don't nest)."""
    qualified = feed.startswith("/")
    segs = [s for s in feed.strip("/").split("/") if s]
    if qualified and len(segs) < 2:
        raise ValueError(
            f"feed {feed!r}: a fully-qualified feed needs a namespace AND "
            "an action (/ns/name or /ns/pkg/name)")
    if qualified or len(segs) == 3:
        return segs[0], "/".join(segs[1:])
    return ns, "/".join(segs)


async def _invoke_feed(client, feed: str, lifecycle_event: str,
                       trigger_name: str, auth: str, params: dict):
    """Run the feed action with the standard feed-protocol arguments
    (lifecycleEvent, triggerName, authKey — ref docs/feeds.md:59-66)."""
    try:
        feed_ns, feed_path = _feed_action_path(feed, "_")
    except ValueError as e:
        return 400, {"error": str(e)}
    body = dict(params)
    body.update({"lifecycleEvent": lifecycle_event,
                 "triggerName": trigger_name, "authKey": auth})
    return await client.request(
        "POST", f"/namespaces/{feed_ns}/actions/{feed_path}", body,
        {"blocking": "true"})


async def run(args) -> int:
    apihost = args.apihost or os.environ.get("WSK_APIHOST", "http://127.0.0.1:3233")
    auth = args.auth or os.environ.get("WSK_AUTH", "")
    if not auth:
        print("error: no credentials (--auth or WSK_AUTH)", file=sys.stderr)
        return 2
    client = WskClient(apihost, auth)
    ns = "_"

    def show(status, data):
        try:
            print(json.dumps(data, indent=2))
        except BrokenPipeError:  # downstream pager/head closed the pipe
            try:
                sys.stdout.close()
            except OSError:
                pass
        return 0 if status < 400 else 1

    e = args.entity
    if e == "action":
        if args.cmd in ("create", "update"):
            if args.sequence and args.artifact:
                print("error: --sequence and a code artifact are mutually "
                      "exclusive", file=sys.stderr)
                return 2
            if args.sequence:
                # `wsk action create seq --sequence a,b,c` (reference CLI);
                # names resolve like feed references: leading slash =
                # qualified, else relative to the caller's namespace (so
                # `pkg/name` is a package in OUR namespace, not namespace
                # `pkg`)
                comps = []
                for raw in args.sequence.split(","):
                    c = raw.strip()
                    if not c:
                        print(f"error: empty component in --sequence "
                              f"{args.sequence!r}", file=sys.stderr)
                        return 2
                    try:
                        comp_ns, path = _feed_action_path(c, "_")
                    except ValueError as err:
                        print(f"error: {err}", file=sys.stderr)
                        return 2
                    comps.append(f"{comp_ns}/{path}")
                exec_ = {"kind": "sequence", "components": comps}
            elif args.artifact:
                code = open(args.artifact).read()
                kind = args.kind or ("python:3" if args.artifact.endswith(".py")
                                     else "nodejs:14")
                exec_ = {"kind": kind, "code": code}
            elif args.cmd == "update":
                exec_ = None  # field-only update inherits the stored exec
            else:
                print("error: an artifact file or --sequence is required",
                      file=sys.stderr)
                return 2
            # an update sends only the fields the user asked to change —
            # the API inherits everything omitted from the stored action
            body = {}
            if exec_ is not None:
                body["exec"] = exec_
            if args.cmd == "create" or args.param:
                body["parameters"] = _kv_list(_params_to_dict(args.param))
            if args.cmd == "create" or args.annotation or args.web:
                body["annotations"] = _kv_list(_params_to_dict(args.annotation))
            if args.web:
                if args.cmd == "update" and not args.annotation:
                    # --web alone must merge into the stored annotations, not
                    # wipe them (the API replaces the field when present)
                    st, doc = await client.request(
                        "GET", f"/namespaces/{ns}/actions/{args.name}")
                    if st == 200:
                        body["annotations"] = [
                            a for a in doc.get("annotations", [])
                            if a.get("key") != "web-export"]
                body["annotations"].append({"key": "web-export", "value": True})
            if args.memory:
                body.setdefault("limits", {})["memory"] = args.memory
            if args.timeout:
                body.setdefault("limits", {})["timeout"] = args.timeout
            params = {"overwrite": "true"} if args.cmd == "update" else {}
            return show(*await client.request(
                "PUT", f"/namespaces/{ns}/actions/{args.name}", body, params))
        if args.cmd == "invoke":
            params = {}
            if args.blocking:
                params["blocking"] = "true"
            if args.result:
                params["result"] = "true"
            return show(*await client.request(
                "POST", f"/namespaces/{ns}/actions/{args.name}",
                _params_to_dict(args.param), params))
        if args.cmd == "get":
            return show(*await client.request(
                "GET", f"/namespaces/{ns}/actions/{args.name}"))
        if args.cmd == "delete":
            return show(*await client.request(
                "DELETE", f"/namespaces/{ns}/actions/{args.name}"))
        if args.cmd == "list":
            return show(*await client.request("GET", f"/namespaces/{ns}/actions"))
    elif e == "activation":
        if args.cmd == "list":
            return show(*await client.request(
                "GET", f"/namespaces/{ns}/activations",
                params={"limit": str(args.limit)}))
        if args.cmd in ("get", "logs", "result"):
            suffix = "" if args.cmd == "get" else f"/{args.cmd}"
            return show(*await client.request(
                "GET", f"/namespaces/{ns}/activations/{args.name}{suffix}"))
    elif e == "trigger":
        if args.cmd in ("create", "update"):
            if args.feed and args.cmd == "update":
                # changing a feed means tearing one down and creating
                # another — not an in-place update (matches the wsk CLI)
                print("error: --feed is not supported on trigger update; "
                      "delete and re-create the trigger", file=sys.stderr)
                return 2
            # omit fields the user didn't pass: the controller keeps the
            # stored values on overwrite, so a bare `trigger update -p ...`
            # cannot erase the feed annotation
            body = {}
            if args.param:
                body["parameters"] = _kv_list(_params_to_dict(args.param))
            if args.annotation or args.feed:
                body["annotations"] = _kv_list(_params_to_dict(args.annotation))
            if args.feed:
                body["annotations"].append({"key": "feed", "value": args.feed})
            params = {"overwrite": "true"} if args.cmd == "update" else {}
            status, data = await client.request(
                "PUT", f"/namespaces/{ns}/triggers/{args.name}", body, params)
            if status < 400 and args.feed and args.cmd == "create":
                # the create+feed macro (ref docs/feeds.md, CLI behavior):
                # invoke the feed action with the CREATE lifecycle event; on
                # anything but a confirmed success (200) — failure, or a 202
                # blocking-invoke timeout whose outcome is unknown — roll
                # the trigger back so the two stay atomic
                fs, fd = await _invoke_feed(client, args.feed, "CREATE",
                                            f"/{ns}/{args.name}", auth,
                                            _params_to_dict(args.param))
                if fs != 200:
                    if fs == 202:
                        # outcome unknown: the slow CREATE may yet succeed
                        # provider-side, so best-effort tear it down before
                        # the trigger document (its handle) disappears
                        try:
                            await _invoke_feed(client, args.feed, "DELETE",
                                               f"/{ns}/{args.name}", auth, {})
                        except Exception as e:  # noqa: BLE001 — rollback must proceed
                            print(f"warning: feed teardown attempt failed: {e}",
                                  file=sys.stderr)
                    await client.request(
                        "DELETE", f"/namespaces/{ns}/triggers/{args.name}")
                    print(f"error: feed action did not succeed ({fs}); "
                          "trigger rolled back", file=sys.stderr)
                    return show(fs, fd) or 1
            return show(status, data)
        if args.cmd == "fire":
            return show(*await client.request(
                "POST", f"/namespaces/{ns}/triggers/{args.name}",
                _params_to_dict(args.param)))
        if args.cmd == "delete":
            # feed-annotated triggers tear their feed down first (DELETE
            # lifecycle event), then the trigger document goes
            gs, gd = await client.request(
                "GET", f"/namespaces/{ns}/triggers/{args.name}")
            feed = None
            if gs < 400:
                feed = next((a.get("value") for a in gd.get("annotations", [])
                             if a.get("key") == "feed"), None)
            feed_failed = False
            if feed:
                fs, _fd = await _invoke_feed(client, feed, "DELETE",
                                             f"/{ns}/{args.name}", auth, {})
                if fs >= 400:
                    feed_failed = True
                    print(f"warning: feed teardown failed ({fs}); the "
                          f"provider-side feed '{feed}' may still be live",
                          file=sys.stderr)
            rc = show(*await client.request(
                "DELETE", f"/namespaces/{ns}/triggers/{args.name}"))
            return 1 if feed_failed else rc
        if args.cmd in ("get", "list"):
            path = f"/namespaces/{ns}/triggers" + \
                ("" if args.cmd == "list" else f"/{args.name}")
            return show(*await client.request("GET", path))
    elif e == "rule":
        if args.cmd == "create":
            return show(*await client.request(
                "PUT", f"/namespaces/{ns}/rules/{args.name}",
                {"trigger": f"_/{args.trigger}", "action": f"_/{args.action}"}))
        if args.cmd in ("enable", "disable"):
            status = "active" if args.cmd == "enable" else "inactive"
            return show(*await client.request(
                "POST", f"/namespaces/{ns}/rules/{args.name}", {"status": status}))
        if args.cmd in ("get", "delete", "list"):
            method = {"get": "GET", "delete": "DELETE", "list": "GET"}[args.cmd]
            path = f"/namespaces/{ns}/rules" + \
                ("" if args.cmd == "list" else f"/{args.name}")
            return show(*await client.request(method, path))
    elif e == "package":
        if args.cmd in ("create", "update"):
            body = {"parameters": _kv_list(_params_to_dict(args.param))}
            params = {"overwrite": "true"} if args.cmd == "update" else {}
            return show(*await client.request(
                "PUT", f"/namespaces/{ns}/packages/{args.name}", body, params))
        if args.cmd == "bind":
            # wsk package bind PROVIDER BOUND_NAME [-p k v]: the binding
            # inherits the provider's parameters, overridden by -p
            # (ref Packages.scala binding semantics)
            if not (args.name and args.artifact):
                print("usage: wsk package bind <provider> <name> [-p k v]",
                      file=sys.stderr)
                return 2
            segs = [s for s in args.name.strip("/").split("/") if s]
            if len(segs) == 2:
                b_ns, b_name = segs
            elif len(segs) == 1:
                b_ns, b_name = ns, segs[0]
            else:
                print(f"error: invalid provider reference {args.name!r} "
                      "(want 'package' or '/namespace/package')",
                      file=sys.stderr)
                return 2
            body = {"binding": {"namespace": b_ns, "name": b_name},
                    "parameters": _kv_list(_params_to_dict(args.param))}
            return show(*await client.request(
                "PUT", f"/namespaces/{ns}/packages/{args.artifact}", body))
        if args.cmd in ("get", "delete", "list"):
            method = {"get": "GET", "delete": "DELETE", "list": "GET"}[args.cmd]
            path = f"/namespaces/{ns}/packages" + \
                ("" if args.cmd == "list" else f"/{args.name}")
            return show(*await client.request(method, path))
    elif e == "namespace":
        if args.cmd == "list":
            return show(*await client.request("GET", "/namespaces"))
    elif e == "api":
        # reference: wsk api create BASE_PATH API_PATH VERB ACTION — here the
        # positional slots map to name=basepath, artifact=relpath, with verb
        # and action from flags (ref core/routemgmt createApi)
        if args.cmd == "create":
            if not (args.name and args.artifact and args.verb and args.action):
                print("usage: wsk api create <basepath> <relpath> "
                      "--verb get --action <web-action>", file=sys.stderr)
                return 2
            apidoc = {"gatewayBasePath": args.name,
                      "gatewayPath": args.artifact,
                      "gatewayMethod": args.verb,
                      "action": {"name": args.action, "namespace": ns},
                      "responsetype": args.response_type}
            if args.apiname:
                apidoc["apiName"] = args.apiname
            return show(*await client.request(
                "POST", f"/namespaces/{ns}/apis", {"apidoc": apidoc}))
        if args.cmd in ("get", "list"):
            params = {}
            if args.name:
                params["basepath"] = args.name
            if args.artifact:
                params["relpath"] = args.artifact
            if args.verb:
                params["operation"] = args.verb
            return show(*await client.request(
                "GET", f"/namespaces/{ns}/apis", params=params))
        if args.cmd == "delete":
            if not args.name:
                print("usage: wsk api delete <basepath> [relpath] [--verb v]",
                      file=sys.stderr)
                return 2
            params = {"basepath": args.name}
            if args.artifact:
                params["relpath"] = args.artifact
            if args.verb:
                params["operation"] = args.verb
            return show(*await client.request(
                "DELETE", f"/namespaces/{ns}/apis", params=params))
    print("unknown command", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="wsk", description="OpenWhisk-TPU CLI")
    parser.add_argument("--apihost", default=None)
    parser.add_argument("--auth", "-u", default=None)
    parser.add_argument("entity", choices=("action", "activation", "trigger",
                                           "rule", "package", "api",
                                           "namespace"))
    parser.add_argument("cmd")
    parser.add_argument("name", nargs="?")
    parser.add_argument("artifact", nargs="?")
    parser.add_argument("--param", "-p", nargs=2, action="append", metavar=("K", "V"))
    parser.add_argument("--annotation", "-a", nargs=2, action="append",
                        metavar=("K", "V"))
    parser.add_argument("--kind", default=None)
    parser.add_argument("--sequence", default=None, metavar="A,B,C",
                        help="action create/update: comma-separated component "
                             "actions (creates a sequence)")
    parser.add_argument("--web", action="store_true")
    parser.add_argument("--memory", "-m", type=int, default=None)
    parser.add_argument("--timeout", "-t", type=int, default=None)
    parser.add_argument("--blocking", "-b", action="store_true")
    parser.add_argument("--result", "-r", action="store_true")
    parser.add_argument("--limit", "-l", type=int, default=30)
    parser.add_argument("--feed", default=None,
                        help="trigger create: feed action (name, pkg/name, "
                             "or /ns/pkg/name); invoked with the CREATE/"
                             "DELETE lifecycle events")
    parser.add_argument("--trigger", default=None, help="rule create: trigger name")
    parser.add_argument("--action", default=None,
                        help="rule/api create: target action name")
    parser.add_argument("--verb", default=None,
                        help="api: HTTP verb (get/post/...)")
    parser.add_argument("--apiname", default=None, help="api create: API name")
    parser.add_argument("--response-type", default="json",
                        help="api create: json|http|text|html|svg")
    args = parser.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())

"""Placement decision-quality scoring on device.

Nothing in the balancer measures whether the placement kernel's decisions
are actually GOOD — the telemetry plane sees realized completion latency,
but by then a bad placement is sunk cost and unattributable. This module
scores every committed micro-batch against the predictive signals the
balancer already holds on device (the anomaly plane's per-invoker latency
EWMAs and the post-commit capacity books), emitting three quantities:

  regret       per placed row, `max(0, cost[chosen] - min cost over the
               feasible alternatives)` where cost is the per-invoker
               predicted latency (EWMA, ms) and feasibility re-applies the
               production constraints (partition, health, spare warm permit
               OR free memory) against the POST-commit books. Regret is
               therefore a slight over-statement for rows whose chosen
               invoker's commit starved an alternative — the honest
               direction for an alerting signal. Invokers with no latency
               signal score cost 0 (optimistic): choosing a known-slow
               invoker while an unmeasured one was feasible counts as full
               regret, which is exactly the straggler-avoidance miss the
               shadow plane exists to measure.
  imbalance    the post-commit fleet occupancy CoV (stddev/mean of
               `1 - free/cap` over healthy, non-padding invokers): 0 is a
               perfectly level fleet, >1 means placement is piling load.
  attribution  forced / overflow (placed off the home invoker) / throttled
               / unplaced counts, plus a cold-start APPROXIMATION: placed
               rows whose action slot shows no spare warm permit at the
               chosen invoker post-commit (the exact per-row use_conc bit
               is not recoverable from the packed decision vector).

A shadow decision vector (the counterfactual kernel's output for the same
batch) folds in the same program: divergent-row counts, the predicted-cost
delta over divergent rows (positive = the shadow's choices predicted
faster), and per-invoker divergence attribution at the production choice.

Everything accumulates into a tiny on-device `QualityState` (one histogram
over the telemetry bucket grid so fleet federation can merge bucket-wise
bit-exactly, a counter vector, and two per-invoker vectors); the jitted
step returns a float32 summary row for the flight recorder. The NumPy twin
(`quality_step_np`) runs the identical arithmetic for the CPU balancers
and the parity fuzz: integer outputs match the jitted path exactly,
float32 accumulations match to reduction-order tolerance.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .telemetry import DEFAULT_BUCKETS, _bounds_us

#: counter-vector layout (int32[N_COUNTERS]); the plane exposes these by
#: name, the fleet merger sums them positionally
COUNTERS = ("rows", "placed", "forced", "overflow", "throttled", "unplaced",
            "cold_start", "shadow_rows", "shadow_divergent")
(C_ROWS, C_PLACED, C_FORCED, C_OVERFLOW, C_THROTTLED, C_UNPLACED,
 C_COLDSTART, C_SHADOW_ROWS, C_SHADOW_DIVERGENT) = range(len(COUNTERS))
N_COUNTERS = len(COUNTERS)

#: per-batch summary row (float32[N_SUMMARY]) returned by the step
(S_REGRET_SUM_MS, S_REGRET_MAX_MS, S_REGRET_ROWS, S_ROWS, S_IMBALANCE_COV,
 S_DIVERGENT, S_SHADOW_DELTA_MS, S_SHADOW_ROWS) = range(8)
N_SUMMARY = 8

#: regret clip before the us conversion: keeps `regret_ms * 1000` inside
#: int32 on both paths (2e6 ms ~ 33 min, far past any sane EWMA)
_REGRET_CLIP_MS = 2.0e6


class QualityState(NamedTuple):
    regret_hist: object     # int32[n_buckets]  (telemetry bucket grid)
    counters: object        # int32[N_COUNTERS]
    inv_regret_ms: object   # float32[N] cumulative regret at the chosen
    inv_divergence: object  # int32[N] shadow-divergent rows by prod choice


def init_quality_state(n_pad: int, n_buckets: int = DEFAULT_BUCKETS,
                       numpy: bool = False) -> QualityState:
    xp = np if numpy else jnp
    return QualityState(xp.zeros((n_buckets,), xp.int32),
                        xp.zeros((N_COUNTERS,), xp.int32),
                        xp.zeros((n_pad,), xp.float32),
                        xp.zeros((n_pad,), xp.int32))


def _decode(out_vec, xp):
    chosen = (out_vec >> 2) - 1
    forced = (out_vec & 1) > 0
    throttled = ((out_vec >> 1) & 1) > 0
    return chosen, forced, throttled


def _score_math(xp, free_post, conc_bn, health, ewma_ms, cap_mb,
                req, out_vec, shadow_vec, bounds_us):
    """The one copy of the scoring arithmetic, written against the numpy/
    jax.numpy common surface (`xp`); `conc_bn` arrives pre-gathered as
    [B, N] so the caller owns the [N, A]-vs-transposed layout difference.
    Scatter-adds differ in spelling (jnp `.at[].add`, np.add.at), so the
    accumulation happens in the two wrappers off the masks built here."""
    b = req.shape[1]
    n = free_post.shape[0]
    offset, size, home = req[0], req[1], req[2]
    need, slot = req[4], req[5]
    valid = req[8] > 0
    chosen, forced, throttled = _decode(out_vec[:b], xp)
    placed = valid & (chosen >= 0)
    chosen_c = xp.clip(chosen, 0, n - 1)

    idx = xp.arange(n, dtype=xp.int32)
    local = idx[None, :] - offset[:, None]
    in_part = (local >= 0) & (local < size[:, None])
    feasible = (in_part & health[None, :]
                & ((conc_bn > 0) | (free_post[None, :] >= need[:, None])))
    inf = xp.float32(3.0e38)
    cost = ewma_ms.astype(xp.float32)
    alt = xp.where(feasible, cost[None, :], inf)
    best = xp.min(alt, axis=1)
    any_feasible = best < inf
    regret_ms = xp.where(
        placed & any_feasible,
        xp.maximum(cost[chosen_c] - best, xp.float32(0.0)),
        xp.float32(0.0)).astype(xp.float32)
    regret_ms = xp.minimum(regret_ms, xp.float32(_REGRET_CLIP_MS))
    regret_us = (regret_ms * xp.float32(1000.0)).astype(xp.int32)
    bucket = xp.sum((regret_us[:, None] > bounds_us[None, :])
                    .astype(xp.int32), axis=1)

    home_g = offset + home
    overflow = placed & ~forced & (chosen != home_g)
    unplaced = valid & ~placed & ~throttled
    conc_at = xp.sum(xp.where(idx[None, :] == chosen_c[:, None], conc_bn, 0),
                     axis=1)
    cold = placed & (conc_at <= 0)

    m = health & (cap_mb > 0)
    k = xp.maximum(xp.sum(m.astype(xp.int32)), 1).astype(xp.float32)
    occ = xp.where(m, xp.float32(1.0)
                   - free_post.astype(xp.float32)
                   / xp.maximum(cap_mb, 1).astype(xp.float32),
                   xp.float32(0.0)).astype(xp.float32)
    mean = xp.sum(occ) / k
    var = xp.sum(xp.where(m, (occ - mean) * (occ - mean),
                          xp.float32(0.0))) / k
    cov = xp.sqrt(var) / xp.maximum(mean, xp.float32(1e-6))

    counters = [
        xp.sum(valid.astype(xp.int32)), xp.sum(placed.astype(xp.int32)),
        xp.sum((forced & valid).astype(xp.int32)),
        xp.sum(overflow.astype(xp.int32)),
        xp.sum(throttled.astype(xp.int32)),
        xp.sum(unplaced.astype(xp.int32)), xp.sum(cold.astype(xp.int32))]

    if shadow_vec is not None:
        s_chosen, _, _ = _decode(shadow_vec[:b], xp)
        divergent = valid & (s_chosen != chosen)
        both = divergent & placed & (s_chosen >= 0)
        s_c = xp.clip(s_chosen, 0, n - 1)
        delta_ms = xp.sum(xp.where(both, cost[chosen_c] - cost[s_c],
                                   xp.float32(0.0)))
        counters += [xp.sum(valid.astype(xp.int32)),
                     xp.sum(divergent.astype(xp.int32))]
    else:
        divergent = xp.zeros((b,), bool)
        delta_ms = xp.float32(0.0)
        counters += [xp.int32(0), xp.int32(0)]

    summary = [xp.sum(regret_ms), xp.max(regret_ms),
               xp.sum((placed & any_feasible).astype(xp.int32))
               .astype(xp.float32),
               xp.sum(valid.astype(xp.int32)).astype(xp.float32), cov,
               xp.sum(divergent.astype(xp.int32)).astype(xp.float32),
               delta_ms,
               (xp.sum(valid.astype(xp.int32)).astype(xp.float32)
                if shadow_vec is not None else xp.float32(0.0))]
    return (chosen_c, placed, bucket, regret_ms, divergent, counters,
            summary)


def make_quality_step(n_buckets: int = DEFAULT_BUCKETS,
                      transposed: bool = False):
    """Build the jitted per-micro-batch scorer.

    step(qstate, free_post, conc_post, health, ewma_ms, cap_mb, req,
         out_vec, shadow_vec) -> (new_qstate, summary float32[N_SUMMARY])

    All array inputs may be live device buffers — the step reads, never
    writes, and is dispatched asynchronously right after the production
    step (post-commit books). `shadow_vec=None` traces the no-shadow
    variant (pytree-static, so the two cadences are two cached programs).
    `transposed=True` consumes the Pallas kernels' [A, N] conc layout.
    """
    bounds = jnp.asarray(np.minimum(_bounds_us(n_buckets), 2 ** 31 - 1),
                         jnp.int32)

    @jax.jit
    def step(qstate: QualityState, free_post, conc_post, health, ewma_ms,
             cap_mb, req, out_vec, shadow_vec=None
             ) -> Tuple[QualityState, jax.Array]:
        slot = req[5]
        if transposed:
            conc_bn = conc_post[slot, :]
        else:
            conc_bn = conc_post[:, slot].T
        chosen_c, placed, bucket, regret_ms, divergent, counters, summary = \
            _score_math(jnp, free_post, conc_bn, health, ewma_ms,
                        cap_mb, req, out_vec, shadow_vec, bounds)
        hist = qstate.regret_hist.at[bucket].add(
            placed.astype(jnp.int32))
        ctr = qstate.counters + jnp.stack(counters)
        inv_r = qstate.inv_regret_ms.at[chosen_c].add(
            jnp.where(placed, regret_ms, 0.0))
        inv_d = qstate.inv_divergence.at[chosen_c].add(
            (divergent & placed).astype(jnp.int32))
        return (QualityState(hist, ctr, inv_r, inv_d),
                jnp.stack([jnp.asarray(s, jnp.float32) for s in summary]))

    return step


def quality_step_np(qstate: QualityState, free_post, conc_post, health,
                    ewma_ms, cap_mb, req, out_vec,
                    shadow_vec: Optional[np.ndarray] = None,
                    transposed: bool = False
                    ) -> Tuple[QualityState, np.ndarray]:
    """NumPy twin of `make_quality_step` for the CPU balancers and the
    parity fuzz: identical arithmetic over the same float32/int32 types.
    Mutates nothing; returns a fresh QualityState of numpy arrays."""
    bounds = np.minimum(_bounds_us(qstate.regret_hist.shape[0]),
                        2 ** 31 - 1).astype(np.int32)
    req = np.asarray(req, np.int32)
    out_vec = np.asarray(out_vec, np.int32)
    free_post = np.asarray(free_post, np.int32)
    health = np.asarray(health, bool)
    ewma_ms = np.asarray(ewma_ms, np.float32)
    cap_mb = np.asarray(cap_mb, np.int32)
    conc_post = np.asarray(conc_post, np.int32)
    if shadow_vec is not None:
        shadow_vec = np.asarray(shadow_vec, np.int32)
    slot = req[5]
    conc_bn = conc_post[slot, :] if transposed else conc_post[:, slot].T
    chosen_c, placed, bucket, regret_ms, divergent, counters, summary = \
        _score_math(np, free_post, conc_bn, health, ewma_ms,
                    cap_mb, req, out_vec, shadow_vec, bounds)
    hist = np.array(qstate.regret_hist, np.int32, copy=True)
    np.add.at(hist, bucket, placed.astype(np.int32))
    ctr = qstate.counters + np.stack(counters).astype(np.int32)
    inv_r = np.array(qstate.inv_regret_ms, np.float32, copy=True)
    np.add.at(inv_r, chosen_c, np.where(placed, regret_ms,
                                        np.float32(0.0)))
    inv_d = np.array(qstate.inv_divergence, np.int32, copy=True)
    np.add.at(inv_d, chosen_c, (divergent & placed).astype(np.int32))
    return (QualityState(hist, ctr, inv_r, inv_d),
            np.asarray(summary, np.float32))

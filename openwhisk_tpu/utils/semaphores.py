"""Capacity-model semaphores.

Behavioral rebuild of the reference's semaphore family, which is the load
balancer's capacity model (SURVEY §2.3):
  - ForcibleSemaphore  (common/scala/.../common/ForcibleSemaphore.scala):
    non-blocking tryAcquire + forceAcquire that may over-commit (go negative).
  - ResizableSemaphore (common/scala/.../common/ResizableSemaphore.scala):
    permits that shrink by `reduction_size` whenever a full container's worth
    of concurrency slots becomes free again.
  - NestedSemaphore    (common/scala/.../common/NestedSemaphore.scala:29-116):
    two-level permits — outer memory permits, inner per-action concurrency
    permits. Acquiring a slot for an action with maxConcurrent C either takes
    a spare concurrency slot of an existing container (no memory) or takes
    memory for a new container and mints C-1 spare concurrency slots.

The reference uses lock-free CAS loops; here a per-object lock suffices — all
hot-path scheduling state in this framework is either asyncio-confined or
device-resident (functional JAX arrays, race-free by construction).
"""
from __future__ import annotations

import threading
from typing import Dict, Generic, Hashable, Tuple, TypeVar

T = TypeVar("T", bound=Hashable)


class ForcibleSemaphore:
    """Non-blocking semaphore that can be forced into over-commit."""

    def __init__(self, max_allowed: int):
        if max_allowed <= 0:
            raise ValueError("max_allowed must be > 0")
        self._lock = threading.Lock()
        self._free = max_allowed

    @property
    def available_permits(self) -> int:
        return self._free

    def try_acquire(self, acquires: int = 1) -> bool:
        if acquires <= 0:
            raise ValueError("acquires must be > 0")
        with self._lock:
            if self._free >= acquires:
                self._free -= acquires
                return True
            return False

    def force_acquire(self, acquires: int = 1) -> None:
        """Acquire even past zero — used for forced placement on overload
        (ref ShardingContainerPoolBalancer.scala:417-424)."""
        if acquires <= 0:
            raise ValueError("acquires must be > 0")
        with self._lock:
            self._free -= acquires

    def release(self, acquires: int = 1) -> None:
        if acquires <= 0:
            raise ValueError("acquires must be > 0")
        with self._lock:
            self._free += acquires


class ResizableSemaphore:
    """Semaphore whose pool shrinks by `reduction_size` when a full
    container's worth of permits is free again.

    release() returns (memory_releasable, empty): memory_releasable is True
    when a reduction happened (one container fully idle -> its memory permits
    can be returned to the outer semaphore); empty is True when no permits
    remain tracked for the action (entry can be dropped).
    """

    def __init__(self, initial_permits: int, reduction_size: int):
        self._lock = threading.Lock()
        self._free = initial_permits
        self._reduction = reduction_size

    @property
    def available_permits(self) -> int:
        return self._free

    def try_acquire(self, acquires: int = 1) -> bool:
        with self._lock:
            if self._free >= acquires:
                self._free -= acquires
                return True
            return False

    def release(self, acquires: int = 1, maybe_reduce: bool = False) -> Tuple[bool, bool]:
        with self._lock:
            self._free += acquires
            reduced = False
            if maybe_reduce and self._free >= self._reduction:
                self._free -= self._reduction
                reduced = True
            return reduced, self._free == 0


class NestedSemaphore(ForcibleSemaphore, Generic[T]):
    """Two-level (memory x per-action-concurrency) permits.

    Ref semantics (NestedSemaphore.scala:29-116):
      try_acquire_concurrent(action, C, mem):
        C == 1       -> plain memory try_acquire(mem)
        C  > 1       -> spare concurrency slot for `action` if present (free);
                        else memory for a new container + mint C-1 spares.
      force_acquire_concurrent: same but memory acquisition is forced.
      release_concurrent(action, C, mem):
        C == 1       -> release(mem)
        C  > 1       -> return one concurrency slot; when C slots are free
                        again, one container is idle -> release its memory.
    """

    def __init__(self, max_allowed: int):
        super().__init__(max_allowed)
        self._actions_lock = threading.Lock()
        self._action_slots: Dict[T, ResizableSemaphore] = {}

    def _slots_for(self, actionid: T, max_concurrent: int) -> ResizableSemaphore:
        with self._actions_lock:
            s = self._action_slots.get(actionid)
            if s is None:
                s = ResizableSemaphore(0, max_concurrent)
                self._action_slots[actionid] = s
            return s

    def concurrent_slots_available(self, actionid: T) -> int:
        with self._actions_lock:
            s = self._action_slots.get(actionid)
        return s.available_permits if s else 0

    def try_acquire_concurrent(self, actionid: T, max_concurrent: int,
                               memory_permits: int) -> bool:
        if max_concurrent == 1:
            return self.try_acquire(memory_permits)
        return self._try_or_force(actionid, max_concurrent, memory_permits, force=False)

    def force_acquire_concurrent(self, actionid: T, max_concurrent: int,
                                 memory_permits: int) -> None:
        if max_concurrent == 1:
            self.force_acquire(memory_permits)
        else:
            self._try_or_force(actionid, max_concurrent, memory_permits, force=True)

    def _try_or_force(self, actionid: T, max_concurrent: int, memory_permits: int,
                      force: bool) -> bool:
        slots = self._slots_for(actionid, max_concurrent)
        if slots.try_acquire(1):
            return True
        if force:
            self.force_acquire(memory_permits)
            slots.release(max_concurrent - 1, maybe_reduce=False)
            return True
        if self.try_acquire(memory_permits):
            slots.release(max_concurrent - 1, maybe_reduce=False)
            return True
        return False

    def release_concurrent(self, actionid: T, max_concurrent: int,
                           memory_permits: int) -> None:
        if max_concurrent == 1:
            self.release(memory_permits)
            return
        slots = self._slots_for(actionid, max_concurrent)
        memory_releasable, empty = slots.release(1, maybe_reduce=True)
        if memory_releasable:
            self.release(memory_permits)
        if empty:
            with self._actions_lock:
                s = self._action_slots.get(actionid)
                if s is slots and s.available_permits == 0:
                    del self._action_slots[actionid]

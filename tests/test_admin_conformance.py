"""Admin surface conformance (ISSUE 19 satellite): the `GET /admin`
index enumerates every admin route with its gating knob and live
enabled state, and every listed route obeys one contract over real
HTTP:

  * anonymous requests answer 401 — no admin route leaks without auth,
  * authed requests never 5xx (`/admin/ready` may answer its deliberate
    503 ownership verdict),
  * a route the index reports `enabled: true` never answers the
    knob-404 (`disabled (CONFIG_...)`) — entity-404s (unknown
    activation/trace id), 409s (capture already armed / sampler down)
    and 400s (bad body) are all legitimate enabled answers,
  * a route the index reports `enabled: false` answers 404 (GET) or
    404/409 (POST captures) — it must not pretend to work.

The suite derives its expectations from the index itself, so adding an
admin route without indexing it (or indexing the wrong knob state) is
the failure mode this file exists to catch."""
import asyncio
import base64
import re

import pytest

from openwhisk_tpu.utils.blackbox import GLOBAL_INCIDENTS
from openwhisk_tpu.utils.eventlog import reset_identity

CTL_PORT = 13475

#: substitutions for parameterized index paths — ids no process knows
PARAMS = {"{activation_id}": "zzz-missing", "{trace_id}": "zzz-missing",
          "{incident_id}": "inc-zzz"}


def _controller():
    from openwhisk_tpu.controller.core import Controller
    from openwhisk_tpu.controller.loadbalancer.lean import LeanBalancer
    from openwhisk_tpu.core.entity import (ControllerInstanceId, Identity,
                                           MB)
    from openwhisk_tpu.messaging import MemoryMessagingProvider
    from openwhisk_tpu.utils.logging import NullLogging

    async def noop_factory(invoker_id, provider):
        class _Stub:
            async def stop(self):
                pass

        return _Stub()

    logger = NullLogging()
    provider = MemoryMessagingProvider()
    lb = LeanBalancer(provider, ControllerInstanceId("0"), noop_factory,
                      logger=logger, metrics=logger.metrics,
                      user_memory=MB(512))
    c = Controller(ControllerInstanceId("0"), provider, logger=logger,
                   load_balancer=lb)
    return c, Identity.generate("guest")


def _hdrs(ident):
    return {"Authorization": "Basic " + base64.b64encode(
        ident.authkey.compact.encode()).decode()}


def _probe_path(path):
    for k, v in PARAMS.items():
        path = path.replace(k, v)
    return path


async def _sweep(port, routes, hdrs):
    """Probe every indexed route anonymously and authed; returns
    {path: (anon_status, authed_status, authed_body_text)}."""
    import aiohttp
    out = {}
    base = f"http://127.0.0.1:{port}"
    async with aiohttp.ClientSession() as s:
        for row in routes:
            url = base + _probe_path(row["path"])
            kw = {}
            if row["method"] == "POST":
                # a body every enabled capture endpoint rejects with 400:
                # the sweep must never actually arm a capture window
                kw = {"json": {"steps": 0, "seconds": 0}}
            async with s.request(row["method"], url, **kw) as r:
                anon = r.status
            async with s.request(row["method"], url, headers=hdrs,
                                 **kw) as r:
                out[row["path"]] = (anon, r.status, await r.text())
    return out


class TestAdminConformance:
    def teardown_method(self):
        reset_identity()
        GLOBAL_INCIDENTS.uninstall()
        GLOBAL_INCIDENTS.enabled = False

    def _boot_and_sweep(self, port):
        from openwhisk_tpu.core.entity import WhiskAuthRecord

        async def go():
            c, ident = _controller()
            await c.auth_store.put(WhiskAuthRecord(
                ident.subject, [ident.namespace], [ident.authkey]))
            await c.start(port=port)
            try:
                import aiohttp
                base = f"http://127.0.0.1:{port}"
                async with aiohttp.ClientSession() as s:
                    async with s.get(base + "/admin") as r:
                        anon_index = r.status
                    async with s.get(base + "/admin",
                                     headers=_hdrs(ident)) as r:
                        assert r.status == 200
                        index = await r.json()
                routes = index["routes"]
                probes = await _sweep(port, routes, _hdrs(ident))
            finally:
                await c.stop()
            return anon_index, routes, probes

        return asyncio.run(go())

    def test_index_shape_and_every_route_conforms(self):
        anon_index, routes, probes = self._boot_and_sweep(CTL_PORT)
        assert anon_index == 401

        # -- index shape: unique paths, sane methods, knob convention
        paths = [r["path"] for r in routes]
        assert len(paths) == len(set(paths))
        assert "/admin" in paths
        for must in ("/admin/incidents", "/admin/incident/{incident_id}",
                     "/admin/fleet/incidents", "/admin/latency/waterfall",
                     "/admin/placement/explain/{activation_id}",
                     "/admin/trace/{trace_id}", "/admin/ready"):
            assert must in paths, must
        for row in routes:
            assert row["method"] in ("GET", "POST"), row
            assert isinstance(row["enabled"], bool), row
            assert row["knob"] is None or \
                row["knob"].startswith("CONFIG_whisk_"), row
        # the default boot exercises both branches of the contract
        assert any(r["enabled"] for r in routes)
        assert any(not r["enabled"] for r in routes)
        # the incidents plane defaults OFF (it writes disk bundles)
        by_path = {r["path"]: r for r in routes}
        assert by_path["/admin/incidents"]["enabled"] is False
        assert by_path["/admin/incidents"]["knob"] == \
            "CONFIG_whisk_incidents_enabled"

        # -- behavior: every listed route against its indexed state
        for row in routes:
            anon, status, text = probes[row["path"]]
            assert anon == 401, (row["path"], anon)
            if row["path"] == "/admin/ready":
                assert status in (200, 503), (row["path"], status)
                continue
            assert status < 500, (row["path"], status, text[:200])
            knob_404 = status == 404 and "disabled (CONFIG_" in text
            if row["enabled"]:
                assert not knob_404, (row["path"], text[:200])
            elif row["method"] == "POST":
                # disabled captures refuse with the knob-404 (no plane)
                # or 409 (plane present, knob off / sampler down)
                assert status in (404, 409), (row["path"], status,
                                              text[:200])
            else:
                assert status == 404, (row["path"], status, text[:200])

    def test_flipping_a_knob_flips_the_index_and_the_route(self, tmp_path,
                                                           monkeypatch):
        """The index reports LIVE state: arming the incident recorder
        turns its rows enabled and the endpoints start answering."""
        monkeypatch.setenv("CONFIG_whisk_incidents_enabled", "true")
        monkeypatch.setenv("CONFIG_whisk_incidents_directory",
                           str(tmp_path))
        tok = object()
        assert GLOBAL_INCIDENTS.install(owner=tok)  # env refresh
        try:
            _, routes, probes = self._boot_and_sweep(CTL_PORT + 2)
        finally:
            GLOBAL_INCIDENTS.uninstall(owner=tok)
        by_path = {r["path"]: r for r in routes}
        assert by_path["/admin/incidents"]["enabled"] is True
        _, status, text = probes["/admin/incidents"]
        assert status == 200
        # an unknown id on the armed plane is an entity miss, not a
        # knob-404
        _, status, text = probes["/admin/incident/{incident_id}"]
        assert status == 404 and "disabled (CONFIG_" not in text
        _, status, text = probes["/admin/incident/local/{incident_id}"]
        assert status == 200 and '"found": false' in text

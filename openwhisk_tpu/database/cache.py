"""Entity caching + cross-controller invalidation.

Rebuild of the reference's MultipleReadersSingleWriterCache
(common/scala/.../core/database/MultipleReadersSingleWriterCache.scala:30-80 —
a protocol-checked read-through cache) and RemoteCacheInvalidation
(RemoteCacheInvalidation.scala:45-101 — controllers broadcast entity updates
on the `cacheInvalidation` topic so peers evict stale entries).

The asyncio event loop single-threads cache transitions here, so the state
machine collapses to: an entry is either a settled value or an in-flight
Future readers await (read coalescing); any write/delete invalidates.
"""
from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Callable, Dict, Optional

CACHE_INVALIDATION_TOPIC = "cacheInvalidation"


class EntityCache:
    def __init__(self, max_entries: int = 10_000, ttl_seconds: Optional[float] = None):
        self.max_entries = max_entries
        self.ttl = ttl_seconds
        self._entries: Dict[str, tuple] = {}  # key -> (expires_at|None, future)
        self.hits = 0
        self.misses = 0

    async def get_or_load(self, key: str, loader: Callable[[], Any]):
        ent = self._entries.get(key)
        now = time.monotonic()
        if ent is not None and (ent[0] is None or ent[0] > now):
            self.hits += 1
            return await asyncio.shield(ent[1])
        self.misses += 1
        fut = asyncio.ensure_future(_call(loader))
        expires = now + self.ttl if self.ttl else None
        self._entries[key] = (expires, fut)
        if len(self._entries) > self.max_entries:
            # drop oldest-inserted entry (python dicts preserve order)
            self._entries.pop(next(iter(self._entries)))
        try:
            return await asyncio.shield(fut)
        except BaseException:
            self._entries.pop(key, None)
            raise

    def update(self, key: str, value: Any) -> None:
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        fut.set_result(value)
        expires = time.monotonic() + self.ttl if self.ttl else None
        self._entries[key] = (expires, fut)

    def invalidate(self, key: str) -> None:
        self._entries.pop(key, None)

    def clear(self) -> None:
        self._entries.clear()

    def __contains__(self, key: str) -> bool:
        return key in self._entries


async def _call(loader):
    r = loader()
    if asyncio.iscoroutine(r):
        return await r
    return r


class RemoteCacheInvalidation:
    """Bus-based cross-instance cache invalidation.

    Each controller publishes {key, instanceId} when it writes an entity;
    peers evict that key (messages from self are ignored by instance id).
    """

    def __init__(self, messaging_provider, instance_id: str,
                 caches: Optional[Dict[str, EntityCache]] = None, logger=None):
        self.provider = messaging_provider
        self.instance_id = instance_id
        self.caches = caches or {}
        self.logger = logger
        self._producer = messaging_provider.get_producer()
        self._feed = None

    def register(self, cache_name: str, cache: EntityCache) -> None:
        self.caches[cache_name] = cache

    async def notify_other_instances(self, cache_name: str, key: str) -> None:
        payload = json.dumps({"instanceId": self.instance_id,
                              "cache": cache_name, "key": key}).encode()
        await self._producer.send(CACHE_INVALIDATION_TOPIC, payload)

    def start(self) -> None:
        from ..messaging.connector import MessageFeed
        consumer = self.provider.get_consumer(
            CACHE_INVALIDATION_TOPIC, f"cacheInvalidation-{self.instance_id}")
        feed_ref = {}

        async def handle(payload: bytes):
            # swallow malformed payloads: signalling processed() AND raising
            # would double-credit the feed's capacity
            try:
                j = json.loads(payload)
                if j.get("instanceId") != self.instance_id:
                    cache = self.caches.get(j.get("cache", ""))
                    if cache is not None:
                        cache.invalidate(j.get("key", ""))
            except Exception:  # noqa: BLE001
                pass
            feed_ref["feed"].processed()

        self._feed = MessageFeed("cacheInvalidation", consumer, 128, handle,
                                 logger=self.logger)
        feed_ref["feed"] = self._feed
        self._feed.start()

    async def stop(self) -> None:
        if self._feed:
            await self._feed.stop()

"""TpuBalancer: placement decisions computed on TPU.

The north-star component (BASELINE.json): a LoadBalancerProvider whose
scheduling inner loop — the reference's per-activation CPU probe walk
(ShardingContainerPoolBalancer.schedule) — runs as a vectorized device
kernel over the live fleet state:

  publish() ──> micro-batch buffer ──┐ (adaptive window: flush at max_batch
                                     │  or after batch_window seconds)
  completion acks ──> release buffer ┤
  health transitions ─> health buffer┤
                                     ▼
            one device step: release_batch ∘ set_health ∘ schedule_batch
                                     │
             assignments ──> ActivationMessage dispatch over the bus

Design notes (SURVEY §7 "hard parts"):
  - batching vs latency: requests wait at most `batch_window` (default
    2 ms) or until `max_batch` queue; a single in-flight device step at a
    time keeps ordering and lets the next window fill while one computes.
  - host<->device coherence: acks and health flips never touch device state
    directly — they buffer host-side and fold in at the next step boundary
    (double-buffered deltas), so the kernel never races its own state.
  - dynamic fleets: arrays are padded to powers of two; fleet growth re-pads
    (a rare recompile) while health flips are O(1) device updates.
  - intra-batch contention: lax.scan preserves the reference's sequential
    read-modify-write semantics exactly (see ops/placement.py).

Fleet partitioning, hashing, coprime steps and cluster-share division all
reuse the CPU policy's formulas (models.sharding_policy) so the kernel stays
bit-for-bit parity-testable against the oracle.
"""
from __future__ import annotations

import asyncio
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.entity import ExecutableWhiskAction, InvokerInstanceId
from ...messaging.message import ActivationMessage
from ...models.sharding_policy import (MIN_SLOT_MB, generate_hash,
                                       pairwise_coprimes)
from ...ops.anomaly import S_EWMA_MS, S_STRAGGLER_FLAG
from ...ops.decision_quality import (S_DIVERGENT, S_IMBALANCE_COV,
                                     S_REGRET_SUM_MS, init_quality_state)
from ...ops.placement import (PlacementState, RequestBatch, init_state,
                              make_fused_admit_step_packed,
                              make_fused_step_packed, make_release_packed,
                              make_shadow_admit_step_packed,
                              make_shadow_step_packed,
                              release_batch, release_batch_vector,
                              schedule_batch, schedule_batch_repair,
                              set_health, unpack_chosen, unpack_step_output)
from .journal import decode_array, encode_array
from ...ops.throttle import init_buckets
from ...utils.config import load_config
from ...utils.eventlog import GLOBAL_EVENT_LOG
from ...utils.ring_buffer import ColumnRing
from ...messaging.coalesce import export_coalesce_gauges
from ...messaging.tcp import export_bus_gauges
from ...utils.hostprof import GLOBAL_HOST_OBSERVATORY
from ...utils.tracing import export_tracing_gauges, trace_id_of
from ...utils.waterfall import (STAGE_BATCH_ASSEMBLE, STAGE_DEVICE_DISPATCH,
                                STAGE_DEVICE_READBACK, STAGE_PUBLISH_ENQUEUE,
                                STAGE_SPILL_FORWARD)
from .base import (HEALTHY, CommonLoadBalancer, InvokerHealth,
                   LoadBalancerException, LoadBalancerThrottleException)
from .flight_recorder import (BatchRecord, free_slot_histogram,
                              occupancy_json)
from .supervision import InvokerPool


@dataclass(frozen=True)
class PlacementPathConfig:
    """`CONFIG_whisk_loadBalancer_*` hot-path knobs (constructor arguments
    override the env).

    placement_kernel: which BATCH ALGORITHM schedules a micro-batch on the
      XLA path — "scan" (the reference lax.scan: sequential depth B, the
      bit-exact legacy path), "repair" (speculate-and-repair: sequential
      depth ~ the intra-batch conflict count; bit-exact with the scan, see
      ops/placement.schedule_batch_repair), or "auto" (repair on the XLA
      path; the pallas and sharded schedules keep their own kernels).
      Orthogonal to the `kernel` knob (xla/pallas device implementation).
    donate_state: donate the PlacementState (and token-bucket carry) to the
      fused step via donate_argnums, so the [N, A] concurrency matrix stops
      round-tripping through fresh HBM allocations every step. Holders of
      the pre-call state must copy first (see _materialize_state).
    ring_assembly: assemble the packed request/release matrices from
      preallocated int32 column rings written at enqueue time (O(1) per
      activation) instead of per-flush list-of-tuples np.array transposes.
    prewarm: compile successor bucket signatures ahead of traffic on a
      background drainer thread (see _prewarm_buckets). Off = every new
      bucket shape compiles synchronously inside a live dispatch — the
      legacy behavior, also the right setting for latency-measurement
      harnesses that can't tolerate background-compile GIL hiccups.
    """
    placement_kernel: str = "auto"   # scan | repair | auto
    #: kernel: the device BACKEND (xla | pallas | auto) — orthogonal to
    #: placement_kernel; "auto" resolves by cached measured rate (see
    #: calibrate_kernel) with resolve_auto_kernel as the pre-calibration
    #: guess. Constructor argument overrides the env, like the rest.
    kernel: str = "auto"             # xla | pallas | auto
    donate_state: bool = True
    ring_assembly: bool = True
    prewarm: bool = True
    #: calibrate_kernel: how `kernel="auto"` picks the device backend
    #: (xla vs pallas). "auto" (default): on a real TPU, a one-shot cached
    #: per-bucket-shape microbench rides the prewarm drainer and the
    #: MEASURED packed-step rate picks the backend (never on the event
    #: loop; on non-TPU backends the static resolver stands — pallas only
    #: has interpret mode there). "force": calibrate even on the CPU twin
    #: (tests / bench's auto_pick row). "off": static resolver only.
    calibrate_kernel: str = "auto"   # auto | force | off
    #: adaptive_window: under arrival pressure, trade a bounded
    #: accumulation delay (ADAPTIVE_WINDOW_MS) for bigger micro-batches
    #: instead of eager per-arrival dispatch. An idle or slow-trickle
    #: balancer keeps the eager fast path (zero added latency); a loaded
    #: one stops paying one fixed-cost device dispatch per 1-3 arrivals —
    #: the dominant per-activation tax at high open-loop rates on the CPU
    #: twin. Off = the exact pre-coalescing eager/window policy.
    adaptive_window: bool = True
    #: fleet_mesh: shard the invoker axis of the placement state over a
    #: ('fleet',) device mesh (parallel/fleet_mesh.py) — the horizontal-
    #: scale mode where fleet capacity grows with chips instead of one
    #: device's HBM. Per-shard speculate-and-repair with a per-round
    #: global-occupancy exchange; bit-exact with the single-device
    #: kernels at any shard count. Default OFF = today's single-device
    #: path, bit-exact.
    fleet_mesh: bool = False
    #: fleet_shards: shard count for fleet_mesh (power of two; 0 = every
    #: visible device, rounded down to a power of two). On a meshless
    #: container the virtual CPU devices from
    #: --xla_force_host_platform_device_count are the honest fallback.
    fleet_shards: int = 0
    #: batch_publish: the batch-shaped publish SPI (ISSUE 14).
    #: `publish_many` takes a whole admission batch in ONE call — one
    #: clock read, one arrival-EWMA pass, one stamp_many, one NumPy
    #: column pass into the request ring, one shared flush decision —
    #: with per-row continuations as done-callbacks (zero tasks per
    #: activation) instead of one publish coroutine (plus timer arm and
    #: stamp) each. False routes publish_many through the serial
    #: per-pair path, bit-exact; serial `publish` itself is untouched
    #: either way.
    batch_publish: bool = True


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _mod_inverse(step: int, m: int) -> int:
    return pow(step, -1, m) if m > 1 else 0


def resolve_auto_kernel(n_pad: int, action_slots: int) -> str:
    """The STATIC half of the kernel="auto" policy, shared with bench.py's
    headline selection: the pallas schedule on real TPU hardware when the
    (n_pad, action_slots) state fits its VMEM budget — across rounds it
    matches the XLA kernel's median rate with 3-5x lower run-to-run spread
    (r04: pallas 3.58M/s +-12% vs xla 2.13M/s +-69%; BASELINE.md) at
    bit-exact parity. On non-TPU backends pallas only has interpret mode
    (a debugging path, orders of magnitude slower), and past the VMEM
    budget only the XLA kernel scales — both resolve to "xla".

    This is only the pre-calibration guess: once the prewarm drainer's
    calibration microbench has MEASURED both backends at a live bucket
    shape (`calibrate_backend_rates`), the cached measured rate replaces
    this heuristic as the tiebreak (`cached_backend_choice`)."""
    if jax.default_backend() != "tpu":
        return "xla"
    from ...ops.placement_pallas import fits_vmem
    return "pallas" if fits_vmem(n_pad, action_slots) else "xla"


#: batch-bucket width from which placement_kernel="auto" swaps the scan
#: program for the speculate-and-repair kernel (either backend). Below it
#: the scan both EXECUTES fine (a handful of sequential probe steps) and
#: COMPILES ~3x faster (~0.45 s vs ~1.2 s per bucket signature on a dev
#: box) — and compile latency is what light traffic actually feels, since
#: a new bucket shape jit-compiles inside a live dispatch. At and above it
#: the scan's B-length dependency chain dominates and repair wins outright.
REPAIR_MIN_BATCH = 32
#: extra CPU-twin fleet gate for "auto" — now 0 (no gate): the PR 5
#: measurement that justified 256 ("scan beats repair ~4x at N=64,
#: B<=64") predates PR 9's repair_commit_masks refactor and no longer
#: reproduces — re-measured on the 1-core twin for ISSUE 12: at N_pad=64
#: the scan's B-length sequential chain costs 0.6 ms (B=64) to 2.9 ms
#: (B=256) per step while repair runs the same batches in 0.14-0.52 ms
#: (rounds=1 on memory-dominant mixes, incl. same-action bursts of 32).
#: At the batch-shaped hot path's B=256 buckets the scan chain was ~25%
#: of the 1-core twin's wall. The convoy worst case (overflow chains
#: serializing the repair loop) remains documented in the repair_vs_scan
#: rider; REPAIR_MIN_BATCH still routes small batches to the scan for
#: its 3x faster compiles.
REPAIR_MIN_FLEET_CPU = 0


def _xla_pair(placement_kernel: str):
    """(schedule_fn, release_fn, resolved_kernel) for the XLA backend,
    honoring the placement-kernel knob. "repair" pins the speculate-and-
    repair schedule + vectorized release fold at every size; "scan" keeps
    the reference lax.scan pair (the true-no-op legacy path); "auto" picks
    PER BUCKET — batch/release widths are static per jit signature, so the
    branch resolves at trace time and each compiled program contains
    exactly one kernel: scan below REPAIR_MIN_BATCH, repair at and above
    it. All pairs are bit-exact (the fuzz suite asserts it), so the knob
    only moves compile/run cost, never placements."""
    if placement_kernel == "repair":
        return schedule_batch_repair, release_batch_vector, "repair"
    if placement_kernel == "auto":
        threshold = REPAIR_MIN_BATCH
        min_fleet = (REPAIR_MIN_FLEET_CPU
                     if jax.default_backend() == "cpu" else 0)

        def auto_schedule(state, batch):
            # both shapes are static at trace time
            if (batch.valid.shape[0] >= threshold
                    and state.free_mb.shape[0] >= min_fleet):
                return schedule_batch_repair(state, batch)
            return schedule_batch(state, batch)

        def auto_release(state, inv, slot, need_mb, max_conc, valid):
            if (inv.shape[0] >= threshold
                    and state.free_mb.shape[0] >= min_fleet):
                return release_batch_vector(state, inv, slot, need_mb,
                                            max_conc, valid)
            return release_batch(state, inv, slot, need_mb, max_conc,
                                 valid)

        auto_schedule._placement_hybrid = True
        auto_release._placement_hybrid = True
        return auto_schedule, auto_release, "repair"
    return schedule_batch, release_batch, "scan"


def _pallas_pair(placement_kernel: str):
    """(schedule_fn, release_fn, resolved_kernel) for the pallas backend.
    "scan" is the PR-4 VMEM-resident sequential kernel; "repair" is the
    fused speculate-and-repair kernel (`schedule_batch_repair_pallas`) —
    probe + conflict detect + commit + the residue loop in ONE pallas_call
    with the books resident in VMEM, sharing the conflict rules with the
    XLA kernel so the two cannot drift; "auto" is the same per-bucket
    static-branch hybrid as the XLA pair (scan below REPAIR_MIN_BATCH).
    The kernel layout is conc-transposed; state everywhere else stays
    [N, A] — converting inside jit keeps both transposes on-device in the
    same program as the kernel call. The release fold is the XLA pair's
    (it fuses into the same program around the pallas call)."""
    from ...ops.placement_pallas import (schedule_batch_pallas,
                                         schedule_batch_repair_pallas,
                                         to_transposed)
    interpret = jax.default_backend() == "cpu"

    @jax.jit
    def sched_scan(st, batch):
        ts, chosen, forced = schedule_batch_pallas(
            to_transposed(st), batch, interpret=interpret)
        return (PlacementState(ts.free_mb, ts.conc_free.T, ts.health),
                chosen, forced)

    @jax.jit
    def sched_repair(st, batch):
        ts, chosen, forced, rounds = schedule_batch_repair_pallas(
            to_transposed(st), batch, interpret=interpret)
        return (PlacementState(ts.free_mb, ts.conc_free.T, ts.health),
                chosen, forced, rounds)

    sched_scan._pallas_kind = "scan"
    sched_repair._pallas_kind = "repair"
    if placement_kernel == "scan":
        return sched_scan, release_batch, "scan"
    if placement_kernel == "repair":
        return sched_repair, release_batch_vector, "repair"
    threshold = REPAIR_MIN_BATCH

    def auto_schedule(state, batch):
        if batch.valid.shape[0] >= threshold:
            return sched_repair(state, batch)
        return sched_scan(state, batch)

    def auto_release(state, inv, slot, need_mb, max_conc, valid):
        if inv.shape[0] >= threshold:
            return release_batch_vector(state, inv, slot, need_mb,
                                        max_conc, valid)
        return release_batch(state, inv, slot, need_mb, max_conc, valid)

    auto_schedule._placement_hybrid = True
    auto_schedule._pallas_kind = "auto"
    auto_release._placement_hybrid = True
    return auto_schedule, auto_release, "repair"


#: one-shot calibration results: (platform, SHARD_ROWS, action_slots,
#: placement_kernel, R, H, B) -> {"rates": {...}, "winner": ...}. Keyed by
#: PER-SHARD rows (n_pad // n_shards), not global fleet size: a 256k-
#: invoker fleet over 8 shards runs a 32k-row program per device, so that
#: is the shape worth measuring — and a measurement taken single-device at
#: 32k rows is the same program. Module-level on purpose — a restarted
#: balancer (or a standby promoting) with the same PER-SHARD geometry
#: adopts the measured choice without re-benching.
_KERNEL_CALIBRATION: Dict[tuple, dict] = {}

#: a backend must measure this much faster to displace the incumbent —
#: damps flip-flopping between buckets whose rates are within noise
CALIBRATION_HYSTERESIS = 1.1


def _calibration_batch_buffer(n_pad: int, action_slots: int, r: int, h: int,
                              b: int) -> np.ndarray:
    """A packed (rel ++ health ++ req) buffer for the calibration
    microbench: a realistic all-valid batch over the whole (healthy) pad —
    memory-dominant traffic with spread homes/slots, the production bulk
    the kernels are picked for."""
    rng = np.random.RandomState(1234)
    rel = np.zeros((5, r), np.int32)
    rel[3] = 1  # padded rows: maxc=1
    health = np.zeros((3, h), np.int32)
    req = np.zeros((9, b), np.int32)
    req[1] = n_pad                       # size: the whole pad
    req[2] = rng.randint(0, n_pad, b)    # home
    req[3] = 1                           # step_inv (step 1 is coprime)
    req[4] = 128                         # need_mb
    req[5] = rng.randint(0, max(1, min(64, action_slots)), b)
    req[6] = 1                           # max_conc
    req[7] = rng.randint(0, n_pad, b)    # rand
    req[8] = 1                           # valid
    return np.concatenate([rel.ravel(), health.ravel(), req.ravel()])


def calibrate_backend_rates(n_pad: int, action_slots: int, r: int, h: int,
                            b: int, *, placement_kernel: str = "auto",
                            include_pallas: bool = True, iters: int = 4,
                            warmup: int = 1, use_cache: bool = True,
                            n_shards: int = 1) -> dict:
    """The kernel="auto" tiebreak: measure the fused packed step's rate for
    both device backends at ONE bucket signature and cache the result
    (one-shot per shape — `_KERNEL_CALIBRATION`). Runs wherever the caller
    is (the balancer calls it on the prewarm drainer thread, bench.py's
    auto_pick row inline); compiles its own non-donated fn instances, so
    it never touches a live balancer's jit caches or donated buffers. The
    plain (non-admit) step is measured even when device rate-admission is
    on: the admission fold is identical XLA on both backends, so the
    relative rate is what matters. A backend that fails to build or run
    reports a null rate and simply cannot win.

    `n_shards`: the microbench builds and keys the PER-SHARD program —
    `n_pad // n_shards` invoker rows, the shape one device of a
    fleet-mesh balancer actually runs. n_shards=1 (the default) is the
    single-device balancer, where shard_rows == n_pad."""
    platform = jax.default_backend()
    shard_rows = max(1, n_pad // max(1, n_shards))
    key = (platform, shard_rows, action_slots, placement_kernel, r, h, b)
    if use_cache:
        hit = _KERNEL_CALIBRATION.get(key)
        if hit is not None:
            if (hit.get("n_pad") != n_pad
                    or hit.get("n_shards") != n_shards):
                # same per-shard program measured under a different
                # topology (the key deliberately omits n_pad/n_shards):
                # re-stamp the CALLER's view so admin planes report their
                # own geometry, not the first measurer's
                hit = dict(hit, n_pad=n_pad, n_shards=n_shards)
            return hit
    buf = _calibration_batch_buffer(shard_rows, action_slots, r, h, b)
    rates: Dict[str, Optional[float]] = {}
    errors: Dict[str, str] = {}
    backends = ["xla"] + (["pallas"] if include_pallas else [])
    for backend in backends:
        try:
            sched, release, _ = (_pallas_pair if backend == "pallas"
                                 else _xla_pair)(placement_kernel)
            fn = make_fused_step_packed(release, sched)
            state = init_state(shard_rows, [1 << 20] * shard_rows,
                               n_pad=shard_rows, action_slots=action_slots)
            out = None
            for _ in range(max(1, warmup)):
                _st, out = fn(state, buf, r, h, b)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(max(1, iters)):
                _st, out = fn(state, buf, r, h, b)
                jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            rates[backend] = round(b * max(1, iters) / dt, 1)
        except Exception as e:  # noqa: BLE001 — a backend that cannot run
            # cannot win; the caller sees why in `errors`
            rates[backend] = None
            errors[backend] = repr(e)
    live = {k: v for k, v in rates.items() if v}
    winner = max(live, key=live.get) if live else "xla"
    if (winner == "pallas" and live.get("xla")
            and live["pallas"] < live["xla"] * CALIBRATION_HYSTERESIS):
        winner = "xla"  # incumbent keeps ties-within-noise
    out = {"rates": rates, "winner": winner, "platform": platform,
           "n_pad": n_pad, "shard_rows": shard_rows, "n_shards": n_shards,
           "action_slots": action_slots,
           "placement_kernel": placement_kernel, "sig": [r, h, b],
           "iters": iters}
    if errors:
        out["errors"] = errors
    _KERNEL_CALIBRATION[key] = out
    return out


def cached_backend_choice(n_pad: int, action_slots: int,
                          placement_kernel: str,
                          n_shards: int = 1) -> Optional[str]:
    """The cached calibration verdict for a geometry (largest measured
    batch bucket wins — most representative of loaded traffic), or None
    when nothing was measured yet. The restart rule is PER-SHARD-SHAPE:
    the lookup keys on `n_pad // n_shards`, so a 256k-invoker fleet over
    8 shards calibrates the 32k-row program it actually runs and the
    verdict transfers to whoever next needs that shape's backend choice —
    a single-device balancer at 32k rows resolving kernel="auto", or a
    prior fleet run / bench auto_pick row seeding it. (A fleet-mesh
    balancer itself never swaps on the verdict: its sharded pair has no
    xla/pallas choice, so it calibrates advisorily — see
    _maybe_calibrate.)"""
    platform = jax.default_backend()
    shard_rows = max(1, n_pad // max(1, n_shards))
    best = None
    # snapshot: the warm-drainer thread inserts concurrently
    for key, cal in list(_KERNEL_CALIBRATION.items()):
        if key[:4] == (platform, shard_rows, action_slots, placement_kernel):
            if best is None or cal["sig"][2] > best["sig"][2]:
                best = cal
    return best["winner"] if best else None


class _SlotAllocator:
    """Host-side collision-free action->concurrency-slot mapping (the inner
    NestedSemaphore level is dense on device; slots recycle when no
    in-flight activation references them).

    Saturation: the balancer grows the slot axis before this allocator ever
    runs dry (see TpuBalancer._ensure_slot_capacity); only past the hard cap
    does a key land in `overflow` — a stable CRC32-hashed slot (restart-safe,
    unlike builtin hash() under PYTHONHASHSEED) shared with whatever
    dedicated key owns it, refcounted so release stays balanced, and counted
    by the saturation metric so conflated pools are never silent."""

    def __init__(self, n_slots: int):
        self.n_slots = n_slots
        self.slots: Dict[str, int] = {}
        self.refcount: Dict[str, int] = {}
        self.free: List[int] = list(range(n_slots - 1, -1, -1))
        #: key -> [slot, refcount]; the slot is pinned at first acquire so
        #: every in-flight activation of the key releases the slot it took,
        #: even if n_slots grows (which would move the CRC32 residue)
        self.overflow: Dict[str, List[int]] = {}

    def _stable_slot(self, key: str) -> int:
        return zlib.crc32(key.encode()) % self.n_slots

    @property
    def saturated(self) -> bool:
        return not self.free

    def needs_slot(self, key: str) -> bool:
        """Would acquiring `key` want a slot it doesn't own? (Overflowed keys
        count: their next acquire migrates to a dedicated slot if one is
        free.)"""
        return key not in self.slots

    def acquire(self, key: str) -> int:
        of = self.overflow.get(key)
        if of is not None and not self.free and key not in self.slots:
            of[1] += 1  # still capped: pile on the pinned shared slot
            return of[0]
        if key not in self.slots:
            if not self.free:
                slot = self._stable_slot(key)
                self.overflow[key] = [slot, 1]
                return slot
            # fresh key — or an overflowed key migrating now that capacity
            # freed (its old in-flight releases still land on the pinned
            # slot: every release carries the slot its acquire returned)
            self.slots[key] = self.free.pop()
        self.refcount[key] = self.refcount.get(key, 0) + 1
        return self.slots[key]

    def lookup(self, key: str) -> int:
        """Best-effort slot for `key` (fallback when a release arrives
        without its acquire-time slot, e.g. after a pre-upgrade snapshot)."""
        slot = self.slots.get(key)
        if slot is not None:
            return slot
        of = self.overflow.get(key)
        return of[0] if of is not None else self._stable_slot(key)

    def release(self, key: str, slot: Optional[int] = None) -> None:
        """Balance the acquire that returned `slot` (None = best guess)."""
        ded = self.slots.get(key)
        of = self.overflow.get(key)
        use_dedicated = (ded is not None and self.refcount.get(key, 0) > 0
                         and (slot is None or slot == ded or of is None))
        if not use_dedicated and of is not None:
            of[1] -= 1
            if of[1] <= 0:
                self.overflow.pop(key)
            return
        n = self.refcount.get(key, 0) - 1
        if n <= 0:
            self.refcount.pop(key, None)
            s = self.slots.pop(key, None)
            if s is not None:
                self.free.append(s)
        else:
            self.refcount[key] = n

    def grow(self, new_n: int) -> None:
        """Extend the slot axis (the balancer grew the device array to
        match). Existing assignments — including pinned overflow slots —
        stay put; only fresh capacity is added."""
        assert new_n > self.n_slots
        self.free = list(range(new_n - 1, self.n_slots - 1, -1)) + self.free
        self.n_slots = new_n


class TpuBalancer(CommonLoadBalancer):
    def __init__(self, messaging_provider, controller_instance, logger=None,
                 metrics=None, cluster_size: int = 1,
                 managed_fraction: float = 0.9, blackbox_fraction: float = 0.1,
                 batch_window: float = 0.002, max_batch: int = 256,
                 action_slots: int = 4096, max_action_slots: int = 65536,
                 initial_pad: int = 64, mesh=None,
                 kernel: Optional[str] = None,
                 pipeline_depth: int = 4,
                 rate_limit_per_minute: Optional[int] = None,
                 placement_kernel: Optional[str] = None,
                 donate_state: Optional[bool] = None,
                 ring_assembly: Optional[bool] = None,
                 prewarm: Optional[bool] = None,
                 adaptive_window: Optional[bool] = None,
                 calibrate_kernel: Optional[str] = None,
                 fleet_mesh: Optional[bool] = None,
                 fleet_shards: Optional[int] = None,
                 batch_publish: Optional[bool] = None,
                 profiler=None, anomaly=None, waterfall=None, quality=None):
        super().__init__(messaging_provider, controller_instance, logger,
                         metrics, profiler=profiler, anomaly=anomaly,
                         waterfall=waterfall, quality=quality)
        self._cluster_size = cluster_size
        path_cfg = load_config(PlacementPathConfig, env_path="load_balancer")
        #: "auto" | "xla" | "pallas" (single-device backend knob)
        self.kernel = kernel if kernel is not None else path_cfg.kernel
        if self.kernel not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"kernel must be auto|xla|pallas, got {self.kernel!r}")
        #: scan | repair | auto — the batch algorithm on the XLA path
        self.placement_kernel = (placement_kernel if placement_kernel
                                 is not None else path_cfg.placement_kernel)
        if self.placement_kernel not in ("scan", "repair", "auto"):
            raise ValueError(
                f"placement_kernel must be scan|repair|auto, "
                f"got {self.placement_kernel!r}")
        self.donate_state = (donate_state if donate_state is not None
                             else path_cfg.donate_state)
        #: explicit constructor True pins donation even where the backend
        #: auto-gate would drop it (tests exercising materialize
        #: boundaries on the CPU twin)
        self._donate_pinned = donate_state is True
        self.ring_assembly = (ring_assembly if ring_assembly is not None
                              else path_cfg.ring_assembly)
        self.prewarm = (prewarm if prewarm is not None
                        else path_cfg.prewarm)
        self.calibrate_kernel = (calibrate_kernel if calibrate_kernel
                                 is not None else path_cfg.calibrate_kernel)
        if self.calibrate_kernel not in ("auto", "force", "off"):
            raise ValueError(
                f"calibrate_kernel must be auto|force|off, "
                f"got {self.calibrate_kernel!r}")
        #: how the running backend was picked: "explicit" (kernel knob),
        #: "static" (resolve_auto_kernel guess), "calibration" (measured
        #: rate), or "fallback" (pallas outgrew its VMEM budget)
        self._kernel_chosen_by = ("explicit" if self.kernel != "auto"
                                  else "static")
        #: the latest calibration result applied/considered (admin/bench)
        self._calibration: Optional[dict] = None
        self.adaptive_window = (adaptive_window if adaptive_window is not None
                                else path_cfg.adaptive_window)
        #: batch-shaped publish SPI (ISSUE 14): advertised to the front
        #: end (maybe_batch_publish builds a PublishCoalescer off it)
        self.batch_publish = (batch_publish if batch_publish is not None
                              else path_cfg.batch_publish)
        #: pure-function memos on the publish hot path: (ns, fqn) -> crc32
        #: home hash and (step, size) -> modular inverse. Both are
        #: deterministic (never invalidated); bounded by a clear at 64k.
        self._hash_cache: Dict[tuple, int] = {}
        self._modinv_cache: Dict[tuple, int] = {}
        #: batched-publish send tasks — ONLY the raw-producer fallback
        #: mints these (the coalescing producer's send_nowait path is
        #: task-free; see _row_placed). close() drains them AFTER
        #: failing queued publishers, so every caller-facing future
        #: resolves before the producer goes away.
        self._publish_finishers: set = set()
        #: publish inter-arrival EWMA (ms) — the adaptive window's pressure
        #: signal. Initialized sparse so a fresh balancer is eager.
        self._gap_ewma_ms = 1000.0
        self._last_gap_ms = 1e9
        self._last_pub_t = time.monotonic()
        self.managed_fraction = managed_fraction
        self.blackbox_fraction = blackbox_fraction
        self.batch_window = batch_window
        self.max_batch = max_batch
        self.action_slots = action_slots
        self.max_action_slots = max(max_action_slots, action_slots)
        #: fleet-mesh mode (CONFIG_whisk_loadBalancer_fleetMesh): build the
        #: ('fleet',) mesh here unless the caller handed one in (the legacy
        #: mesh= constructor path keeps working; its axis name is adopted
        #: whatever it is). Default OFF = the single-device path, bit-exact.
        self.fleet_mesh = (fleet_mesh if fleet_mesh is not None
                           else path_cfg.fleet_mesh)
        if mesh is None and self.fleet_mesh:
            from ...parallel.fleet_mesh import make_fleet_mesh
            shards_cfg = (fleet_shards if fleet_shards is not None
                          else path_cfg.fleet_shards)
            mesh = make_fleet_mesh(shards_cfg or None)
        self.mesh = mesh
        #: mesh axis name and shard count (1 without a mesh) — the admin/
        #: occupancy planes, journal topology records and per-shard
        #: calibration keying all read these
        self.fleet_axis = mesh.axis_names[0] if mesh is not None else None
        self.n_shards = (int(np.prod(list(mesh.shape.values())))
                         if mesh is not None else 1)
        #: opt-in bulk ACTIVATE admission ON DEVICE (ops.throttle token
        #: buckets fused into the placement step): per-namespace platform
        #: rate as a bus-boundary backstop. The HTTP front door's
        #: entitlement RateThrottler (with per-user overrides) remains the
        #: primary enforcement; this catches traffic that bypasses it
        #: (direct bus publishers, misconfigured edges).
        self.rate_limit_per_minute = rate_limit_per_minute
        self._ns_slots: Dict[str, int] = {}
        self._bucket_state = None
        self._t0_mono = time.monotonic()
        self._n_pad = max(initial_pad, self.n_shards)
        if mesh is not None:
            # power-of-two pad so the invoker axis always divides evenly
            # over the (power-of-two) shard count; single-device pads keep
            # the caller's exact value (bit-exact legacy behavior)
            self._n_pad = _next_pow2(self._n_pad)

        self._registry: List[InvokerInstanceId] = []
        self._healthy: List[bool] = []
        self._slots = _SlotAllocator(action_slots)
        self._rand_counter = 0

        self.state: Optional[PlacementState] = None
        self._sched_fn = None
        self._release_fn = None
        #: write-ahead placement journal (loadbalancer/journal.py): None =
        #: journaling off, the bit-exact legacy path. Every committed
        #: device-state mutation appends one record; a restored controller
        #: replays the tail on top of the snapshot (replay_journal).
        self.journal = None
        self._journal_seq = 0
        #: True while replay_journal re-applies records, so the re-applied
        #: mutations don't journal themselves again
        self._journal_mute = False
        #: a fleet-mesh writer stamps ONE `mesh` topology record ahead of
        #: its first append (per process / per promotion), so a replayer
        #: on a different device count cold-starts with a logged reason
        #: instead of silently mis-sharding
        self._journal_mesh_stamped = False
        #: cross-partition spillover (active/active only; spillover.py):
        #: with a sink attached, publish_many diverts its non-blocking
        #: overflow past `spillover_depth` pending rows to the
        #: least-loaded peer instead of deepening the local queue
        self.spillover_sink = None
        self.spillover_depth = 256
        self.spilled_rows = 0
        #: host numpy copy of free_mb from the last readback/state install —
        #: occupancy() serves from this, never the live device buffer.
        #: Installs are sequence-guarded: readback worker threads finish
        #: out of order under the pipeline, and last-writer-wins would let
        #: an older step's books stick until the next dispatch.
        self._books_cache: Optional[np.ndarray] = None
        self._books_seq = 0
        self._books_cache_seq = 0
        #: placement-quality plane inputs, host-refreshed on the 1 Hz
        #: supervision tick from the anomaly plane's harvested scores:
        #: padded per-invoker cost (latency EWMA) and capacity vectors for
        #: the scorer, and the straggler-flag penalty for the shadow
        #: kernel (uploaded to device lazily, only when the flags change)
        self._quality_ewma_np = np.zeros(self._n_pad, np.float32)
        self._quality_caps_np = np.zeros(self._n_pad, np.int32)
        self._quality_ewma = None
        self._quality_caps = None
        self._shadow_penalty_np = np.zeros(self._n_pad, np.int32)
        self._shadow_penalty = None
        self._shadow_fn = None
        self._quality_batches = 0
        self._init_device_state()

        # pending request queue + delta buffers; with ring_assembly the int
        # fields mirror into preallocated column rings at enqueue time so
        # the per-flush packed matrices assemble with two slice copies
        # instead of a list-of-tuples np.array transpose
        self._pending: List[tuple] = []      # (req_tuple, future, slot_key)
        self._releases: List[tuple] = []     # (inv_idx, slot, mem, maxc, key)
        self._req_ring = ColumnRing(10, max_batch * 4)
        self._rel_ring = ColumnRing(4, max_batch * 4)
        self._health_updates: Dict[int, bool] = {}
        self._flush_task: Optional[asyncio.Task] = None
        self._step_lock = asyncio.Lock()
        # device-step pipelining: dispatch is async (JAX returns future
        # arrays immediately), so batch N+1 can be dispatched while batch
        # N's readback is still crossing the wire — the counter bounds
        # in-flight readbacks (the event wakes waiters when one lands),
        # the task set tracks them for close()
        self.pipeline_depth = max(1, pipeline_depth)
        self._inflight_steps = 0
        self._capacity_free = asyncio.Event()
        self._readbacks: set = set()
        #: EWMA of the device readback round trip — picks the eager-vs-
        #: batching dispatch policy (tunnel RTTs serialize; local ones
        #: don't). Starts ABOVE the fast threshold: unknown counts as slow,
        #: because misclassifying a tunnel as fast costs a serialized wire
        #: round trip while the reverse costs one event-loop tick.
        self._rtt_ewma_ms = 2 * self.RTT_FAST_MS

        # group is per-controller: every controller needs its OWN full view
        # of the ping stream (a shared group would split pings between
        # controllers; ref: each controller runs its own InvokerPool)
        self.supervision = InvokerPool(
            messaging_provider, on_status_change=self._status_change,
            logger=logger, group=f"health-{controller_instance.as_string}",
            on_tick=self._telemetry_tick)
        # advisory unhealthy hints from the anomaly plane land on the
        # supervision pool (pushed only when hintUnhealthy is configured)
        self.anomaly.hint_sink = self.supervision.set_unhealthy_hints
        # completion telemetry accumulates ON DEVICE for this balancer: the
        # buffered event rows fold into the accumulator as one scatter-add
        # per dispatch cycle (_dispatch_batch / idle _device_step)
        if self.telemetry.enabled:
            self.telemetry.use_device(self._n_pad)
        self._recompute_partitions()

    def _telemetry_tick(self) -> None:
        # the supervision watchdog also drains completion events that
        # arrived while no placement traffic was flowing (idle fleets must
        # still converge their device counts)
        self.telemetry.device_fold()
        self.telemetry.tick(self.metrics)
        # anomaly detection rides the same tick: the device program
        # dispatches now and its scores harvest NEXT tick (no device sync
        # on the event loop, same rule as the burn-rate math)
        self.anomaly.tick(self.metrics)
        # the quality plane rides the same cadence: refresh its cost/
        # penalty vectors from the scores the anomaly tick just harvested,
        # then its gauges (host aggregates only — no device sync)
        if self.quality.enabled:
            self._refresh_quality_signals()
            self.quality.tick(self.metrics)
        # HBM watermark gauges ride the same 1 Hz tick (guarded no-op on
        # backends without memory_stats, e.g. CPU)
        self.profiler.refresh_memory(self.metrics)
        export_tracing_gauges(self.metrics)
        # bus-client health rides the same cadence: coalescing batch sizes
        # and consumer reconnects (messaging/{coalesce,tcp}.py)
        export_coalesce_gauges(self.metrics)
        export_bus_gauges(self.metrics)
        # journal durability lag / size / fsync tail (HA plane) ride the
        # same 1 Hz cadence
        if self.journal is not None:
            self.journal.export_gauges(self.metrics)
        # fleet-mesh visibility: shard count + per-shard occupancy from
        # the cached books (host-side only)
        if self.mesh is not None:
            self._export_shard_gauges()

    # -- device state ------------------------------------------------------
    def _resolve_kernel(self) -> str:
        if self.kernel != "auto":
            return self.kernel
        # a cached MEASURED rate beats the static heuristic: a restarted
        # balancer (or a promoted standby) with the same geometry adopts
        # the calibration verdict immediately
        cal = cached_backend_choice(self._n_pad, self.action_slots,
                                    self.placement_kernel, self.n_shards)
        if cal is not None:
            self._kernel_chosen_by = "calibration"
            return cal
        return resolve_auto_kernel(self._n_pad, self.action_slots)

    def _init_device_state(self) -> None:
        n = len(self._registry)
        slot_mb = [self._slot_mb(i.user_memory.to_mb) for i in self._registry]
        state = init_state(n or 1, slot_mb or [0], n_pad=self._n_pad,
                           action_slots=self.action_slots)
        health = jnp.zeros_like(state.health)
        if self._healthy:
            health = health.at[jnp.arange(len(self._healthy))].set(
                jnp.asarray(self._healthy, bool))
        state = state._replace(health=health)
        self.kernel_resolved = (
            "sharded" if self.mesh is not None else self._resolve_kernel())
        installed = False
        if self.mesh is not None:
            from ...parallel.fleet_mesh import fleet_pair
            from ...parallel.sharded_state import shard_state
            self.state = shard_state(state, self.mesh, axis=self.fleet_axis)
            # the full placementKernel knob works on the mesh: scan keeps
            # the prototype sharded scan, repair installs the per-shard
            # speculate-and-repair kernel with the global-occupancy
            # exchange, auto is the shared per-bucket static hybrid
            (self._sched_fn, self._release_fn,
             self.placement_kernel_resolved) = fleet_pair(
                self.mesh, self.placement_kernel,
                repair_min_batch=self.REPAIR_MIN_BATCH,
                axis=self.fleet_axis)
            installed = True
        elif self.kernel_resolved == "pallas":
            plan = self._pallas_plan()
            if plan is not None:
                self.state = state
                pk = self.placement_kernel if plan == "repair" else "scan"
                (self._sched_fn, self._release_fn,
                 self.placement_kernel_resolved) = _pallas_pair(pk)
                installed = True
        if not installed and self.mesh is None:
            self.state = state
            self._sched_fn, self._release_fn = self._xla_fns()
            if self.kernel_resolved == "pallas":
                # explicit kernel="pallas" that failed the VMEM fit:
                # report what actually runs
                self.kernel_resolved = "xla"
        # release + health-fold + schedule as ONE compiled program (vs
        # three dispatches per micro-batch), fed through the transfer-packed
        # wrappers (3 host->device transfers per step instead of 16)
        self._build_packed_fns()
        self._export_kernel_gauge()
        self._set_books_now(np.asarray(self.state.free_mb))
        # placement-quality plane: device accumulator + jitted scorer keyed
        # to the current invoker pad (a geometry rebuild restarts the
        # accumulated quality counts — different arrays, like the anomaly
        # plane's kernel swaps). Live state keeps conc in [N, A] on every
        # backend (the pallas pair transposes inside its own program), so
        # the scorer never needs the transposed layout here.
        if self.quality.enabled:
            self.quality.use_device(self._n_pad)
            self._refresh_quality_signals()

    #: class aliases of the module constants (tests and subclasses key off
    #: these; the schedule-pair builders live at module level so the
    #: calibration microbench can build pairs without a balancer)
    REPAIR_MIN_BATCH = REPAIR_MIN_BATCH
    REPAIR_MIN_FLEET_CPU = REPAIR_MIN_FLEET_CPU

    def _xla_fns(self):
        """(schedule_fn, release_fn) for the XLA backend — see
        `_xla_pair`; this wrapper records the resolved algorithm."""
        sched, release, resolved = _xla_pair(self.placement_kernel)
        self.placement_kernel_resolved = resolved
        return sched, release

    def _make_packed_fns(self, sched_fn, release_fn):
        """Build (packed_step, release_packed) for a schedule pair —
        profiler-wrapped, donation per the current gate — WITHOUT
        installing them, so the calibration path can compile a candidate
        backend's fns on the drainer thread and hand the loop finished
        programs. The profiler interposes on every jitted entry point:
        compile events classify by first-call / expect-window / rebuild
        window / pow2-bucketed statics (the only shapes _bucket may
        produce) — anything else is shape churn and trips the recompile
        watchdog."""
        from ...ops.profiler import pow2_statics
        # buffer donation: XLA reuses the state's buffers for the output, so
        # the [N, A] concurrency matrix stops round-tripping HBM every step.
        # Off on a mesh (sharded buffers stay owned by their own path) and
        # on the CPU backend: XLA:CPU cannot alias donated buffers and runs
        # the donated program SYNCHRONOUSLY at dispatch — the event loop
        # blocks for the whole step, the RTT EWMA reads ~0 and flips the
        # dispatch regime to eager micro-batches (measured 5x rate loss on
        # the CPU twin) — all cost, no HBM to save. An explicit
        # donate_state=True constructor argument pins it on anyway.
        self._donate = (self.donate_state and self.mesh is None
                        and (jax.default_backend() != "cpu"
                             or self._donate_pinned))
        if self.rate_limit_per_minute is not None:
            packed = self.profiler.wrap(
                "fused_admit_step",
                make_fused_admit_step_packed(release_fn, sched_fn,
                                             donate=self._donate),
                expected=pow2_statics)
            # bucket state is SOFT (a rolling rate window, never
            # checkpointed) but it CARRIES across kernel swaps and growth
            # rebuilds — re-initializing here would grant every namespace a
            # fresh full burst whenever the fleet grows mid-minute
            if self._bucket_state is None:
                self._bucket_state = init_buckets(self.RATE_NS_BUCKETS,
                                                  self.rate_limit_per_minute)
        else:
            packed = self.profiler.wrap(
                "fused_step",
                make_fused_step_packed(release_fn, sched_fn,
                                       donate=self._donate),
                expected=pow2_statics)
        release_packed = self.profiler.wrap(
            "release_packed",
            make_release_packed(release_fn, donate=self._donate),
            expected=lambda st, rel: _next_pow2(rel.shape[1]) == rel.shape[1])
        return packed, release_packed

    def _build_packed_fns(self) -> None:
        self._packed_fn, self._release_packed_fn = self._make_packed_fns(
            self._sched_fn, self._release_fn)
        # fn rebuild = fresh jit caches: everything needs re-warming (the
        # queue entries pin the fn they were enqueued for, so stale warms
        # drain harmlessly against the abandoned cache)
        self._warm_sigs = set()
        self._warm_queue = []
        self._warm_task = getattr(self, "_warm_task", None)
        self._build_shadow_fn()

    def _build_shadow_fn(self) -> None:
        """(Re)build the decision-only shadow twin for the resolved
        backend (quality plane). The twin runs the penalty-augmented
        variant of the PRODUCTION kernel family over the same packed
        buffer and release/health folds, so divergence measures the
        penalty, not a kernel swap; it never donates and writes nothing
        back — production stays bit-exact with the plane on."""
        self._shadow_fn = None
        if not (self.quality.enabled and self.quality.shadow_every_n > 0):
            return
        if self.mesh is not None:
            # every schedule pair is bit-exact with every other, so the
            # mesh shadow always runs the penalized sharded repair kernel
            # regardless of which pair fleet_pair resolved for production
            from ...parallel.fleet_mesh import make_fleet_repair_schedule
            sched = make_fleet_repair_schedule(self.mesh,
                                               axis=self.fleet_axis,
                                               penalized=True)
        elif self.kernel_resolved == "pallas":
            from ...ops.placement_pallas import (
                schedule_batch_pallas, schedule_batch_repair_pallas,
                to_transposed)
            interpret = jax.default_backend() == "cpu"
            repair = self.placement_kernel_resolved == "repair"

            def sched(st, batch, penalty, _repair=repair):
                # the transposed result state is dead in the shadow
                # program (decisions only) — XLA drops the transposes
                fn = (schedule_batch_repair_pallas if _repair
                      else schedule_batch_pallas)
                return fn(to_transposed(st), batch, interpret=interpret,
                          penalty=penalty)
        elif self.placement_kernel_resolved == "repair":
            sched = schedule_batch_repair
        else:
            sched = schedule_batch
        if self.rate_limit_per_minute is not None:
            self._shadow_fn = make_shadow_admit_step_packed(
                self._release_fn, sched)
        else:
            self._shadow_fn = make_shadow_step_packed(self._release_fn,
                                                      sched)

    def _refresh_quality_signals(self) -> None:
        """Host-side refresh of the quality-plane input vectors (1 Hz
        supervision tick + geometry rebuilds): the anomaly plane's
        latency EWMAs become the scorer's cost vector, its straggler
        flags the shadow penalty. All three vectors re-upload to device
        only when they actually change — the scorer runs every batch, so
        a per-batch host->device transfer of 1 Hz signals would tax the
        dispatch path for nothing; steady fleets pay nothing."""
        n = self._n_pad
        caps = np.zeros(n, np.int32)
        reg_caps = getattr(self, "_caps_mb", None)
        if reg_caps is not None:
            m = min(n, len(reg_caps))
            caps[:m] = np.minimum(reg_caps[:m], 2 ** 31 - 1)
        if (self._quality_caps is None
                or not np.array_equal(caps, self._quality_caps_np)):
            self._quality_caps_np = caps
            self._quality_caps = jnp.asarray(caps)
        ewma = np.zeros(n, np.float32)
        pen = np.zeros(n, np.int32)
        sc = getattr(self.anomaly, "_scores", None)
        if sc is not None:
            k = min(n, sc.shape[1])
            ewma[:k] = sc[S_EWMA_MS, :k]
            pen[:k] = sc[S_STRAGGLER_FLAG, :k].astype(np.int32)
        if (self._quality_ewma is None
                or not np.array_equal(ewma, self._quality_ewma_np)):
            self._quality_ewma_np = ewma
            self._quality_ewma = jnp.asarray(ewma)
        if (self._shadow_penalty is None
                or not np.array_equal(pen, self._shadow_penalty_np)):
            self._shadow_penalty_np = pen
            self._shadow_penalty = jnp.asarray(pen)

    def _prewarm_buckets(self, r: int, h: int, b: int) -> None:
        """Compile-ahead for the packed step's SUCCESSOR bucket shapes. A
        new (R, H, B) signature otherwise compiles synchronously inside a
        live dispatch — ~0.5 s for the scan program and ~1.2 s for the
        repair kernel on a dev box — stalling the event loop and inflating
        the e2e latency of every in-flight activation. XLA compiles
        release the GIL, so warming on a worker thread costs the loop only
        millisecond hiccups while the jit cache fills for the real call.
        Buckets grow by doubling, so (2R, H, B) and (R, H, 2B) keep the
        compiled set one step ahead of traffic growth; already-warmed
        signatures de-dup in _warm_sigs (reset when the fns rebuild).
        On a fleet mesh the warm dummies are sharded like the live state
        (same NamedSharding → same jit cache key), so the mesh pays the
        same zero in-dispatch compile stalls as the single-device path.
        `prewarm=False` disables the whole plane (legacy compile-on-demand
        behavior)."""
        if not self.prewarm:
            return
        self._warm_sigs.add((r, h, b))  # the live call just compiled it
        cand = []
        if r < self.max_batch * 4:
            cand.append((min(r * 2, self.max_batch * 4), h, b))
        if b < self.max_batch:
            cand.append((r, h, min(b * 2, self.max_batch)))
        self._spawn_warm([s for s in cand if s not in self._warm_sigs])

    def _spawn_warm(self, todo: list) -> None:
        """Queue signatures for the single warm drainer. ONE compile runs
        at a time: concurrent warm compiles multiply the GIL hiccups the
        event loop feels, without finishing the ladder any sooner."""
        if not todo or getattr(self, "_closing", False):
            return
        self._warm_sigs.update(todo)
        self._warm_queue.extend((sig, self._packed_fn) for sig in todo)
        if self._warm_task is not None and not self._warm_task.done():
            return

        async def _drain():
            while self._warm_queue and not getattr(self, "_closing", False):
                sig, fn = self._warm_queue.pop(0)
                decision = await asyncio.to_thread(self._warm_one, sig, fn)
                if decision is not None:
                    # calibration picked a different backend: the swap
                    # applies HERE, back on the event loop, with fns that
                    # compiled on the drainer thread — the loop never
                    # compiles or calibrates
                    self._apply_backend_decision(decision)

        self._warm_task = asyncio.get_event_loop().create_task(_drain())
        self._readbacks.add(self._warm_task)
        self._warm_task.add_done_callback(self._readbacks.discard)

    def _warm_fns(self, sig: tuple, fn, release_packed_fn) -> None:
        """Compile one (R, H, B) signature of a packed step + its
        release-only program (drainer thread; XLA compiles drop the GIL)."""
        wr, wh, wb = sig
        rate_on = self.rate_limit_per_minute is not None
        rows = 10 if rate_on else 9
        buf = jnp.asarray(np.zeros(5 * wr + 3 * wh + rows * wb, np.int32))

        # all-zero dummies: valid masks are 0, so nothing places or
        # releases — only the compile (keyed on shapes + statics) matters.
        # Donation consumes the dummies, nothing else; each warmed entry
        # point gets its own. On a mesh the dummy is sharded exactly like
        # the live state so the warm compile keys the live cache entry.
        def dummy_state():
            st = PlacementState(
                jnp.zeros((self._n_pad,), jnp.int32),
                jnp.zeros((self._n_pad, self.action_slots), jnp.int32),
                jnp.zeros((self._n_pad,), bool))
            if self.mesh is not None:
                from ...parallel.sharded_state import shard_state
                st = shard_state(st, self.mesh, axis=self.fleet_axis)
            return st

        buckets = None
        if rate_on:
            buckets = init_buckets(self.RATE_NS_BUCKETS,
                                   self.rate_limit_per_minute)
            (st_w, _bk), out_w = fn(
                (dummy_state(), buckets), buf,
                np.float32(time.monotonic() - self._t0_mono), wr, wh, wb)
        else:
            st_w, out_w = fn(dummy_state(), buf, wr, wh, wb)
        # the idle release fold compiles its own release-only program
        # per R bucket — warm it too, or a drain-only lull still eats
        # the in-dispatch compile stall this plane exists to avoid
        release_packed_fn(dummy_state(), np.zeros((5, wr), np.int32))
        # shadow + quality-scorer programs ride the same warm ladder: a
        # first-sight compile inside a live dispatch would stall the loop
        # exactly like an unwarmed packed step. The warm step's own
        # post-state/decision outputs key the scorer's cache entry (same
        # shapes and shardings as the live call).
        sv = None
        if self._shadow_fn is not None:
            pen = jnp.zeros((self._n_pad,), jnp.int32)
            if rate_on:
                sv = self._shadow_fn((dummy_state(), buckets), buf, pen,
                                     np.float32(0.0), wr, wh, wb)
            else:
                sv = self._shadow_fn(dummy_state(), buf, pen, wr, wh, wb)
        step = getattr(self.quality, "_step", None)
        if step is not None:
            qs = init_quality_state(self._n_pad, self.quality.n_buckets)
            req9 = np.zeros((9, wb), np.int32)
            ewma = np.zeros(self._n_pad, np.float32)
            caps = np.zeros(self._n_pad, np.int32)
            step(qs, st_w.free_mb, st_w.conc_free, st_w.health, ewma,
                 caps, req9, out_w, None)
            if sv is not None:
                step(qs, st_w.free_mb, st_w.conc_free, st_w.health, ewma,
                     caps, req9, out_w, sv)

    def _warm_one(self, sig: tuple, fn) -> Optional[dict]:
        """One warm-drainer unit of work (worker thread): compile the
        signature, then — for kernel="auto" — run the one-shot calibration
        microbench for it. Returns a backend-swap decision for the loop to
        apply, or None."""
        try:
            self._warm_fns(sig, fn, self._release_packed_fn)
        except Exception as e:  # noqa: BLE001 — warming is best-effort;
            # the live path compiles on demand anyway. But a SILENT fail
            # would make a systematically broken prewarm (dummy inputs
            # drifting from the real signature) look identical to a
            # working one, so say why.
            if self.logger:
                self.logger.warn(None, f"bucket prewarm {sig} failed: {e!r}",
                                 "TpuBalancer")
            return None
        try:
            return self._maybe_calibrate(sig)
        except Exception as e:  # noqa: BLE001 — calibration is advisory:
            # a failed microbench must never take the warm drainer down
            if self.logger:
                self.logger.warn(None, f"kernel calibration {sig} failed: "
                                 f"{e!r}", "TpuBalancer")
            return None

    def _calibration_enabled(self) -> bool:
        """Calibration requires an auto kernel knob and a backend where
        the pallas kernels actually compile (a TPU) — unless "force"
        overrides for the CPU-twin tests/bench. A FLEET-MESH balancer
        calibrates too — the microbench measures the single-device fused
        step at the PER-SHARD shape, the compute each of its devices
        runs — but only advisorily (see _maybe_calibrate): the sharded
        pair is not swappable, so the measurement populates the shared
        per-shard cache and the admin plane without ever moving the
        running kernels."""
        if self.kernel != "auto" or self.calibrate_kernel == "off":
            return False
        if self.calibrate_kernel == "force":
            return True
        return jax.default_backend() == "tpu"

    def _maybe_calibrate(self, sig: tuple) -> Optional[dict]:
        """Drainer-thread half of the measured-rate auto policy: run (or
        look up) the one-shot calibration for this bucket signature; when
        the measured winner differs from the running backend, build AND
        prewarm the winner's packed fns here so the loop-side swap
        installs finished programs."""
        if not self._calibration_enabled():
            return None
        from ...ops.placement_pallas import (HAS_PALLAS, fits_vmem,
                                             fits_vmem_repair)
        # the fit (like the microbench itself) is judged at the PER-SHARD
        # shape — the rows one device actually holds
        rows = max(1, self._n_pad // self.n_shards)
        pallas_ok = HAS_PALLAS and (
            fits_vmem_repair(rows, self.action_slots, self.max_batch)
            if self.placement_kernel != "scan"
            else fits_vmem(rows, self.action_slots))
        if not pallas_ok:
            # one-sided measurement cannot pick a winner: an xla-only
            # bench would "win" by default and demote a statically-chosen
            # (and unmeasured) pallas scan. The fit-based choice stands.
            return None
        r, h, b = sig
        cal = calibrate_backend_rates(
            self._n_pad, self.action_slots, r, h, b,
            placement_kernel=self.placement_kernel,
            iters=2 if self.calibrate_kernel == "force" else 5,
            n_shards=self.n_shards)
        self._calibration = cal
        if self.mesh is not None:
            # ADVISORY on a fleet mesh: the sharded pair has no backend
            # swap, so the per-shard measurement only feeds the shared
            # cache (a restarted balancer whose shard shape matches — at
            # any topology — adopts it) and /admin/profile/kernel
            return None
        # the SWAP decision follows the largest measured bucket for this
        # geometry (cached_backend_choice — the same rule a restarted
        # balancer applies at construction), not this signature's own row:
        # a small bucket's noise verdict must not ping-pong the backend,
        # since every swap flushes the warm jit caches
        winner = (cached_backend_choice(self._n_pad, self.action_slots,
                                        self.placement_kernel,
                                        self.n_shards)
                  or cal["winner"])
        if winner == self.kernel_resolved:
            self._kernel_chosen_by = "calibration"
            self._export_kernel_gauge()
            return None
        pair = (_pallas_pair if winner == "pallas"
                else _xla_pair)(self.placement_kernel)
        packed, release_packed = self._make_packed_fns(pair[0], pair[1])
        self._warm_fns(sig, packed, release_packed)
        return {"kernel": winner, "pair": pair, "packed": packed,
                "release_packed": release_packed, "sig": sig,
                "n_pad": self._n_pad, "action_slots": self.action_slots,
                "cal": cal}

    def _apply_backend_decision(self, decision: dict) -> None:
        """Event-loop half of the measured-rate auto policy: install a
        calibration-chosen backend whose fns arrived compiled from the
        drainer. Dropped when the world moved while calibration ran (fleet
        growth re-keyed the geometry, the knobs changed, close() started).
        The swap compiles nothing on the loop; the profiler's expect
        window + rebuild-window classification keep the recompile watchdog
        quiet through it."""
        if (getattr(self, "_closing", False) or self.kernel != "auto"
                or self.mesh is not None
                or decision["n_pad"] != self._n_pad
                or decision["action_slots"] != self.action_slots
                or decision["kernel"] == self.kernel_resolved):
            return
        self.profiler.expect("kernel_swap")
        GLOBAL_EVENT_LOG.record("kernel_swap",
                                instance=self.controller.instance,
                                to=decision["kernel"], why="auto_calibrated")
        sched, release, resolved = decision["pair"]
        self.kernel_resolved = decision["kernel"]
        self.placement_kernel_resolved = resolved
        self._sched_fn, self._release_fn = sched, release
        self._packed_fn = decision["packed"]
        self._release_packed_fn = decision["release_packed"]
        # the shadow twin tracks the production kernel family
        self._build_shadow_fn()
        # fresh jit caches behind the installed fns: only the calibrated
        # signature is warm; successor shapes re-enter the drainer as
        # traffic hits them
        self._warm_sigs = {decision["sig"]}
        self._warm_queue = []
        self._kernel_chosen_by = "calibration"
        self._calibration = decision["cal"]
        self._export_kernel_gauge()
        if self.logger:
            rates = decision["cal"]["rates"]
            self.logger.info(
                None, f"kernel calibration swapped the placement backend "
                f"to {decision['kernel']} at sig={decision['sig']} "
                f"(measured rates: {rates})", "TpuBalancer")

    def _export_kernel_gauge(self) -> None:
        """Info-style backend gauge: exactly one live
        `loadbalancer_kernel_backend{backend,placement,chosen_by} 1`
        series; the superseded combination is zeroed on swaps so a scrape
        sees the flip, not two live backends."""
        tags = {"backend": self.kernel_resolved,
                "placement": getattr(self, "placement_kernel_resolved",
                                     self.placement_kernel),
                "chosen_by": getattr(self, "_kernel_chosen_by", "static")}
        prev = getattr(self, "_kernel_gauge_tags", None)
        if prev is not None and prev != tags:
            self.metrics.gauge("loadbalancer_kernel_backend", 0, tags=prev)
        self._kernel_gauge_tags = tags
        self.metrics.gauge("loadbalancer_kernel_backend", 1, tags=tags)

    def _ns_slot(self, ns_id: str) -> int:
        slot = self._ns_slots.get(ns_id)
        if slot is None:
            dedicated = self.RATE_NS_BUCKETS - self.RATE_NS_SHARED_BUCKETS
            if len(self._ns_slots) < dedicated:
                # dedicated slot — memoized (bounds the dict at the axis)
                slot = len(self._ns_slots)
                self._ns_slots[ns_id] = slot
            else:  # dedicated range full: hash into the reserved SHARED
                # tail sub-range, NOT the full axis — overflow namespaces
                # conflate only with each other, never draining a dedicated
                # tenant's tokens. NOT memoized: crc32 is cheaper than
                # unbounded dict growth.
                slot = dedicated + (zlib.crc32(ns_id.encode())
                                    % self.RATE_NS_SHARED_BUCKETS)
        return slot

    def _use_xla_kernels(self) -> None:
        """Swap the XLA schedule/release kernels in (pallas state outgrew
        the VMEM budget, via growth or snapshot restore)."""
        self.profiler.expect("kernel_swap")
        GLOBAL_EVENT_LOG.record("kernel_swap",
                                instance=self.controller.instance,
                                to="xla", why="vmem_fallback")
        self.kernel_resolved = "xla"
        self._kernel_chosen_by = "fallback"
        self._sched_fn, self._release_fn = self._xla_fns()
        self._build_packed_fns()
        self._export_kernel_gauge()

    def _pallas_plan(self) -> Optional[str]:
        """What the pallas backend can run at the current geometry:
        "repair" (state + the repair kernel's residue scratch fit VMEM),
        "scan" (only the resident state fits — placement_kernel="auto"
        downgrades to the VMEM scan, which needs no [B, N] scratch), or
        None (nothing fits, or pallas is unimportable). Explicit
        placement_kernel="repair" never silently downgrades to the pallas
        scan — it falls through to the XLA repair kernel instead. On None
        the explicit-pallas fall-back-and-log contract applies: say why,
        run XLA."""
        from ...ops.placement_pallas import (PALLAS_IMPORT_ERROR, fits_vmem,
                                             fits_vmem_repair)
        repair_ok = (self.placement_kernel != "scan"
                     and fits_vmem_repair(self._n_pad, self.action_slots,
                                          self.max_batch))
        if repair_ok:
            return "repair"
        scan_ok = (self.placement_kernel != "repair"
                   and fits_vmem(self._n_pad, self.action_slots))
        if scan_ok:
            return "scan"
        if self.logger:
            why = (f"pallas unavailable: {PALLAS_IMPORT_ERROR}"
                   if PALLAS_IMPORT_ERROR is not None else
                   f"pallas kernel needs VMEM-resident state; "
                   f"{self._n_pad}x{self.action_slots} "
                   f"(placement_kernel={self.placement_kernel}, "
                   f"max_batch={self.max_batch}) does not fit")
            self.logger.warn(None, f"{why} — using the XLA kernel")
        self.kernel = "xla"
        return None

    def _slot_mb(self, user_memory_mb: int) -> int:
        return max(user_memory_mb // self._cluster_size, MIN_SLOT_MB)

    # -- fleet bookkeeping -------------------------------------------------
    def _status_change(self, instance: InvokerInstanceId, status: str) -> None:
        idx = instance.instance
        new_rows = []
        while idx >= len(self._registry):
            new_rows.append(len(self._registry))
            self._registry.append(instance)
            self._healthy.append(False)
        self._registry[idx] = instance
        self._healthy[idx] = status == HEALTHY
        if new_rows:
            if len(self._registry) > self._n_pad:
                self._grow_padding(_next_pow2(len(self._registry)))
            # initialize ONLY the new rows (full capacity, health set below);
            # existing rows keep their in-flight holds
            slot_vals = jnp.asarray(
                [self._slot_mb(self._registry[i].user_memory.to_mb)
                 for i in new_rows], jnp.int32)
            self.state = self.state._replace(
                free_mb=self.state.free_mb.at[jnp.asarray(new_rows)].set(slot_vals))
            # occupancy's cached books must learn the fresh rows' capacity
            # (registration is rare; the sync transfer is n_pad int32s)
            self._set_books_now(np.asarray(self.state.free_mb))
            if self._journal_live():
                self._journal_append({
                    "t": "reg",
                    "reg": [self._registry[i].to_json() for i in new_rows],
                    "healthy": [bool(self._healthy[i]) for i in new_rows]})
        self._health_updates[idx] = self._healthy[idx]
        self._recompute_partitions()

    def _next_books_seq(self) -> int:
        """Claim the next books-cache sequence number (event-loop only:
        dispatches and state installs are loop-serialized)."""
        self._books_seq += 1
        return self._books_seq

    def _install_books(self, books_np, seq: int) -> None:
        """Install host books into occupancy()'s cache unless a NEWER
        step's books already landed. Called on the event loop."""
        if seq >= self._books_cache_seq:
            self._books_cache_seq = seq
            self._books_cache = books_np

    def _set_books_now(self, books_np) -> None:
        """Synchronous cache install for authoritative state changes
        (init/registration/growth/restore) — supersedes any in-flight
        readback's books."""
        self._install_books(books_np, self._next_books_seq())

    def _recover_consumed_state(self) -> bool:
        """After a failed donated device call: if the failure happened
        past the point where XLA consumed the donated buffers, the books
        (and possibly the token-bucket carry, donated in the same tuple by
        the admit variant) are unrecoverable deleted arrays — every later
        call on them would die on 'Array has been deleted'. Rebuild
        fresh-capacity state; leaked in-flight holds self-heal via forced
        timeouts, exactly as after a restart. Returns True when a rebuild
        happened (the failure consumed the donation), False when the
        buffers are intact (failure before consumption, or donation off).
        Every donated call site — request dispatch, the idle release
        fold, the readback-compensation release — routes its failure
        handler through here."""
        if not self._donate:
            return False
        bucket_gone = (self._bucket_state is not None
                       and self._bucket_state.tokens.is_deleted())
        # check conc_free AND free_mb: on the CPU twin np.asarray is a
        # zero-copy view, so the books cache PINS free_mb from donation
        # (it survives undeleted) while the unreferenced conc_free/health
        # buffers are consumed — free_mb alone would miss the outage
        if not (self.state.free_mb.is_deleted()
                or self.state.conc_free.is_deleted() or bucket_gone):
            return False
        if self.logger:
            self.logger.error(
                None, "device call failure consumed the donated state;"
                " rebuilding device books", "TpuBalancer")
        if bucket_gone:
            self._bucket_state = None
        self._init_device_state()
        if self._journal_live():
            # books were rebuilt at full capacity: replay must do the same
            self._journal_append({"t": "reinit"})
        return True

    def _books_ref(self):
        """Donation-safe reference to the post-step books vector, taken on
        the event loop BEFORE any later dispatch can consume the live
        buffers: under donation the next dispatched step invalidates
        self.state, so holders crossing an await/thread boundary get their
        own device-side copy (n_pad int32s — never the [N, A] matrix)."""
        return (jnp.copy(self.state.free_mb) if self._donate
                else self.state.free_mb)

    def _set_inflight(self, delta: int) -> None:
        """Single writer for the in-flight step counter and its gauge —
        the two must never drift, so every pipeline transition (dispatch,
        readback, both failure paths) goes through here."""
        self._inflight_steps += delta
        self.metrics.gauge("loadbalancer_pipeline_inflight",
                           self._inflight_steps)

    def _materialize_state(self) -> PlacementState:
        """Copy-out boundary for holders of the device state. With buffer
        donation ON, the NEXT dispatched step CONSUMES self.state's buffers
        (XLA aliases them into its output), so any reader that keeps the
        state across an await/thread boundary — the snapshot worker, a
        growth re-pad racing the pipeline, occupancy's cold fallback — must
        hold its own copy. Without donation the arrays are immutable and
        the live reference is safe to hold forever."""
        st = self.state
        if not getattr(self, "_donate", False):
            return st
        return PlacementState(jnp.copy(st.free_mb), jnp.copy(st.conc_free),
                              jnp.copy(st.health))

    def _grow_padding(self, new_pad: int) -> None:
        """Re-pad the device arrays, PRESERVING the live books (in-flight
        memory holds and concurrency permits survive fleet growth; only
        update_cluster resets them, which is reference behavior)."""
        st = self._materialize_state()
        old_free = np.asarray(st.free_mb)
        old_conc = np.asarray(st.conc_free)
        old_health = np.asarray(st.health)
        self.profiler.expect("reshard" if self.mesh is not None
                             else "fleet_growth")
        n_old = old_free.shape[0]
        free = np.zeros((new_pad,), np.int32)
        free[:n_old] = old_free
        conc = np.zeros((new_pad, self.action_slots), np.int32)
        conc[:n_old] = old_conc
        health = np.zeros((new_pad,), bool)
        health[:n_old] = old_health
        self._n_pad = new_pad
        self._install_state(PlacementState(jnp.asarray(free),
                                           jnp.asarray(conc),
                                           jnp.asarray(health)))
        if self._journal_live():
            self._journal_append({"t": "grow", "n_pad": new_pad})

    def _ensure_slot_capacity(self, slot_key: str) -> None:
        """Grow the concurrency-slot axis before the allocator runs dry, the
        same way _grow_padding grows the invoker axis. Past the hard cap the
        allocator's stable-hash overflow takes over — counted and warned, so
        conflated concurrency pools are never silent."""
        if not (self._slots.saturated and self._slots.needs_slot(slot_key)):
            return
        if self.action_slots < self.max_action_slots:
            self._grow_slots(min(self.action_slots * 2, self.max_action_slots))
        else:
            # counted on EVERY overflowed acquire, so sustained conflation
            # shows up as a climbing rate, not a one-off blip
            self.metrics.counter("loadbalancer_action_slot_overflow")
            if self.logger and slot_key not in self._slots.overflow:
                self.logger.warn(
                    None, f"action concurrency slots saturated at the hard "
                    f"cap ({self.action_slots}); '{slot_key}' shares a "
                    "hashed slot (conflated concurrency pool)")

    def _install_state(self, state: PlacementState) -> None:
        """Adopt new-shape device arrays: shard onto the mesh (if any) and
        drop pallas if the shapes outgrew its VMEM budget. On a mesh this
        IS a reshard event — the new-shape shard_map programs compile
        under an expect window (the caller's growth/restore window, plus
        this explicit reshard stamp) so the recompile watchdog stays
        quiet through cluster grow/resize."""
        if self.mesh is not None:
            from ...parallel.sharded_state import shard_state
            self.profiler.expect("reshard")
            state = shard_state(state, self.mesh, axis=self.fleet_axis)
        self.state = state
        self._set_books_now(np.asarray(state.free_mb))
        if getattr(self, "kernel_resolved", self.kernel) == "pallas":
            plan = self._pallas_plan()
            if plan is None:
                self._use_xla_kernels()
            elif (plan == "scan"
                  and getattr(self, "placement_kernel_resolved",
                              "scan") == "repair"):
                # growth kept the resident state inside the budget but
                # evicted the repair kernel's residue scratch: downgrade
                # to the VMEM scan in place
                self.profiler.expect("kernel_swap")
                GLOBAL_EVENT_LOG.record("kernel_swap",
                                        instance=self.controller.instance,
                                        to="pallas_scan",
                                        why="scratch_evicted")
                (self._sched_fn, self._release_fn,
                 self.placement_kernel_resolved) = _pallas_pair("scan")
                self._build_packed_fns()
                self._export_kernel_gauge()

    def _grow_slots(self, new_slots: int) -> None:
        """Widen conc_free's action axis, preserving every live permit."""
        self.profiler.expect("slot_growth")
        st = self._materialize_state()
        old_conc = np.asarray(st.conc_free)
        conc = np.zeros((old_conc.shape[0], new_slots), np.int32)
        conc[:, : old_conc.shape[1]] = old_conc
        self.action_slots = new_slots
        self._slots.grow(new_slots)
        self._install_state(PlacementState(st.free_mb,
                                           jnp.asarray(conc),
                                           st.health))
        self.metrics.counter("loadbalancer_action_slot_growth")
        if self._journal_live():
            self._journal_append({"t": "slots", "action_slots": new_slots})
        if self.logger:
            self.logger.info(
                None, f"grew action concurrency slots to {new_slots}")

    def _recompute_partitions(self) -> None:
        n = len(self._registry)
        self.managed_count = max(int(self.managed_fraction * n), 1) if n else 0
        self.blackbox_count = max(int(self.blackbox_fraction * n), 1) if n else 0
        self._steps_managed = pairwise_coprimes(max(1, self.managed_count))
        self._steps_blackbox = pairwise_coprimes(max(1, self.blackbox_count))
        # host-side per-invoker capacity vector (this controller's memory
        # share), kept in sync with the registry so the flight recorder's
        # occupancy digest never needs a per-step rebuild
        self._caps_mb = np.asarray(
            [self._slot_mb(i.user_memory.to_mb) for i in self._registry],
            np.int64)

    def update_cluster(self, cluster_size: int) -> None:
        """Controller joined/left: re-shard every invoker's memory
        (ref updateCluster :561-584)."""
        if cluster_size != self._cluster_size:
            self._cluster_size = cluster_size
            self.profiler.expect("reshard" if self.mesh is not None
                                 else "cluster_resize")
            self._init_device_state()
            self._recompute_partitions()  # capacity shares changed
            if self._journal_live():
                self._journal_append({"t": "cluster", "size": cluster_size})

    @property
    def cluster_size(self) -> int:
        return self._cluster_size

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        self.start_ack_feed()
        self.supervision.start()
        # warm the first-traffic bucket signature while the fleet is still
        # registering, so the opening micro-batches skip the cold compile
        if self.prewarm and \
                (8, self.HEALTH_BATCH, 8) not in self._warm_sigs:
            self._spawn_warm([(8, self.HEALTH_BATCH, 8)])

    async def close(self) -> None:
        self._closing = True  # no new flush tasks from here on
        await self.supervision.stop()
        if self._flush_task:
            self._flush_task.cancel()
        # let in-flight readbacks resolve their publishers first
        if self._readbacks:
            await asyncio.gather(*list(self._readbacks),
                                 return_exceptions=True)
        # fail queued publishers instead of leaving them awaiting forever
        pending, self._pending = self._pending, []
        self._req_ring.clear()
        for req, fut, slot_key, _t, aid, *_ in pending:
            self._slots.release(slot_key, req[self.R_CONC_SLOT])
            self.waterfall.discard(aid)
            if not fut.done():
                fut.set_exception(LoadBalancerException("load balancer shut down"))
        # batched-publish finishers drain AFTER the queued rows fail (every
        # placement future they await is resolved by now — dispatched rows
        # by the readback gather above, queued rows by the loop above) and
        # BEFORE the producer closes, so every caller-facing future maps
        # its outcome while sends still work
        if self._publish_finishers:
            await asyncio.gather(*list(self._publish_finishers),
                                 return_exceptions=True)
        # releases queued during the readback drain (abandoned publishers)
        # will never reach a device step now — free their host slots
        for r in self._releases:
            self._slots.release(r[4], r[1])
        self._releases.clear()
        self._rel_ring.clear()
        await super().close()

    # -- publish -----------------------------------------------------------
    def _standby_error(self) -> Optional[LoadBalancerException]:
        """The pre-placement refusals shared by publish/publish_many."""
        if self.ha_standby:
            # HA failover mode: placement is fenced to the active leader —
            # refusing BEFORE any state change makes the 503 safe for the
            # edge to retry on the active upstream
            return LoadBalancerException(
                "standby controller: placement is fenced to the active "
                "leader")
        if len(self._registry) == 0 or not any(self._healthy):
            return LoadBalancerException(
                "No invokers available to schedule the activation.")
        return None

    def _build_row(self, action: ExecutableWhiskAction,
                   msg: ActivationMessage) -> tuple:
        """One request row in packed-matrix order — the per-activation
        half of publish, shared verbatim by the serial and batched paths
        (parity by construction). The home hash and the modular inverse
        are pure functions of their inputs, so both ride bounded memo
        dicts; everything stateful (_rand_counter, the slot allocator,
        slot-axis growth) mutates in exactly the serial order."""
        n = len(self._registry)
        blackbox = action.exec_metadata().is_blackbox
        size = self.blackbox_count if blackbox else self.managed_count
        offset = (n - self.blackbox_count) if blackbox else 0
        fqn_str = str(action.fully_qualified_name)
        hkey = (str(msg.user.namespace.name), fqn_str)
        h = self._hash_cache.get(hkey)
        if h is None:
            if len(self._hash_cache) >= 65536:
                self._hash_cache.clear()
            h = self._hash_cache[hkey] = generate_hash(*hkey)
        steps = self._steps_blackbox if blackbox else self._steps_managed
        step = steps[h % len(steps)]
        ikey = (step, size)
        step_inv = self._modinv_cache.get(ikey)
        if step_inv is None:
            if len(self._modinv_cache) >= 65536:
                self._modinv_cache.clear()
            step_inv = self._modinv_cache[ikey] = _mod_inverse(step, size)
        self._rand_counter += 1
        mem = action.limits.memory.megabytes
        maxc = action.limits.concurrency.max_concurrent
        slot_key = f"{fqn_str}:{mem}"
        self._ensure_slot_capacity(slot_key)
        # request row in packed-matrix order (see _dispatch_batch): a plain
        # tuple converts to the int32 batch matrix in one C-speed np.array
        # call instead of a per-field Python fill loop
        ns_slot = (self._ns_slot(msg.user.namespace.uuid.asString)
                   if self.rate_limit_per_minute is not None else 0)
        req = (offset, size, h % size, step_inv, mem,
               self._slots.acquire(slot_key), maxc,
               (h ^ (self._rand_counter * 2654435761)) % max(size, 1), 1,
               ns_slot)
        return req, slot_key, fqn_str

    async def publish(self, action: ExecutableWhiskAction, msg: ActivationMessage
                      ) -> asyncio.Future:
        err = self._standby_error()
        if err is not None:
            raise err
        pid = None
        if self.partition_ring is not None:
            pid = self.partition_of_msg(msg)
            err = self._partition_refusal(msg, pid)
            if err is not None:
                raise err
        req, slot_key, fqn_str = self._build_row(action, msg)
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        # trailing fields feed the flight recorder: enqueue time (queue-age
        # digest), the activation/action ids for the decision row, and the
        # trace id (exemplar plumbing on OpenMetrics scrapes)
        aid_str = msg.activation_id.asString
        t_now = time.monotonic()
        self._note_arrival(t_now)
        entry = (req, fut, slot_key, t_now,
                 aid_str, fqn_str,
                 trace_id_of(msg.trace_context))
        if pid is not None:
            # active/active: the row's (partition, epoch) rides the entry
            # so the dispatch-time journal record carries per-partition
            # ids + the epoch each row was admitted under (a spilled row
            # keeps its origin's stamp when that is ahead of ours)
            entry = entry + ((pid, self._row_epoch(msg, pid)),)
        # waterfall: the activation is now IN the balancer's queue — the
        # delta from here to batch_assemble is pure queueing/window wait
        self.waterfall.stamp(aid_str, STAGE_PUBLISH_ENQUEUE)
        if self.ring_assembly:
            # the packed-matrix column lands in the preallocated ring NOW
            # (one C-speed write) — flush-time assembly is two slice
            # copies. The entry is built FIRST: an exception between a
            # ring push and its queue append would desync the two FIFOs
            # and shift every later request's geometry.
            self._req_ring.push(req)
        self._pending.append(entry)
        # inline fast path: with free pipeline capacity, dispatch NOW
        # (synchronously — the assembly+enqueue body has no awaits) when the
        # batch is full, or on an idle FAST device (sub-window round trips:
        # overlap is real, so eager dispatch just cuts latency). On a
        # slow/tunneled device round trips serialize, so splitting an
        # arrival wave into eager sub-batches multiplies wire time —
        # measured RTT (EWMA of the readback histogram) picks the policy.
        # Under arrival PRESSURE (_coalesce_window_s > 0) eager dispatch is
        # the tax, not the cure: per-arrival steps ship batches of 1-3 and
        # the fixed dispatch cost dominates the loop — hold the window and
        # let the batch fill instead.
        if not ((len(self._pending) >= self.max_batch
                 or (self._inflight_steps == 0
                     and self._rtt_ewma_ms < self.RTT_FAST_MS
                     and self._coalesce_window_s() == 0.0))
                and self._try_flush_now()):
            self._arm_flush(urgent=len(self._pending) >= self.max_batch)
        try:
            inv_idx, forced = await fut
        except asyncio.CancelledError:
            # Cancelled between set_result and resumption: the placement is
            # lost to this caller but its capacity is not — give it back the
            # same way the readback loop does for futures cancelled earlier.
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                self._abandon_placement(int(fut.result()[0]), req, slot_key)
            # abandoned = never acked = never finished: drop the stage
            # vector too, like every other abandonment path (a cancelled
            # future's vector would otherwise sit in the active map until
            # the eviction cap pushed out a LIVE activation's instead)
            self.waterfall.discard(aid_str)
            raise
        invoker, promise = self._map_placement(inv_idx, forced, req,
                                               slot_key, aid_str, msg, action)
        await self.send_activation_to_invoker(msg, invoker)
        return promise

    def _map_placement(self, inv_idx: int, forced, req: tuple,
                       slot_key: str, aid: str, msg, action):
        """The post-placement outcome mapping, shared by the serial
        `publish` and the batched `_row_placed` continuation so the two
        paths cannot drift: failure codes release the held capacity,
        discard the stage vector and raise the serial exception texts;
        success books the forced counter, sets up the activation entry
        and returns (invoker, completion promise)."""
        if inv_idx == -2:
            # device token bucket rejected it: no capacity was consumed
            self._slots.release(slot_key, req[self.R_CONC_SLOT])
            self.waterfall.discard(aid)
            self.metrics.counter("loadbalancer_device_throttled")
            raise LoadBalancerThrottleException(
                "Too many requests in the last minute (device rate "
                "admission).")
        if inv_idx < 0:
            self._slots.release(slot_key, req[self.R_CONC_SLOT])
            self.waterfall.discard(aid)
            raise LoadBalancerException(
                "No invokers available to schedule the activation.")
        if forced:
            self.metrics.counter("loadbalancer_forced_placements")
        invoker = self._registry[inv_idx]
        promise = self.setup_activation(msg, action, invoker)
        entry = self.activation_slots.get(aid)
        if entry is not None:
            entry.conc_slot = req[self.R_CONC_SLOT]
        return invoker, promise

    def publish_many(self, pairs) -> List[asyncio.Future]:
        """The batch-shaped publish SPI (ISSUE 14): one call schedules a
        whole admission batch. Against N serial publishes this pays ONE
        clock read + arrival-EWMA pass (`_note_arrivals`), ONE
        `stamp_many(PUBLISH_ENQUEUE)`, ONE NumPy column pass into the
        request ring (`push_block`), ONE shared flush decision — the
        whole batch lands in one device micro-batch instead of an eager
        head-of-batch dispatch of 1 — with per-row continuations as
        done-callbacks (`_row_placed`: zero tasks, sends handed to the
        bus coalescer task-free). Each returned future
        resolves to the completion promise (what `publish` returns) or
        raises `publish`'s exact exceptions; per-row decisions, waterfall
        stamps, 429 texts and abandonment capacity-returns are the serial
        path's, row for row (parity-fuzzed). Off switch
        (CONFIG_whisk_loadBalancer_batchPublish=false): the serial
        per-pair default."""
        if not self.batch_publish:
            return super().publish_many(pairs)
        loop = asyncio.get_event_loop()
        outs: List[asyncio.Future] = [loop.create_future() for _ in pairs]
        err = self._standby_error()
        if err is not None:
            # fresh exception instance per row (serial parity: each
            # publish call raises its own) — N waiters re-raising one
            # shared object interleave their __traceback__ frames
            for out in outs:
                out.set_exception(type(err)(*err.args))
            return outs
        built: List[tuple] = []
        ring = self.partition_ring
        # cross-partition spillover (active/active): with a peered sink
        # and the pending queue past the depth gate, this batch's
        # NON-BLOCKING tail forwards to the least-loaded peer instead of
        # deepening the local queue (the forwarded rows are fenced with
        # this owner's partition epoch, so the peer's journal replays
        # them exactly; blocking rows stay local — their client waits on
        # THIS controller's completion promise)
        overflow = 0
        if (ring is not None and self.spillover_sink is not None
                and self.spillover_sink.has_peer()):
            overflow = max(0, len(self._pending) + len(pairs)
                           - self.spillover_depth)
        spill_rows: List[tuple] = []
        for (action, msg), out in zip(pairs, outs):
            pid = None
            if ring is not None:
                pid = self.partition_of_msg(msg)
                err = self._partition_refusal(msg, pid)
                if err is not None:
                    out.set_exception(err)
                    continue
                if overflow > 0 and not msg.blocking \
                        and pid in self.owned_partitions:
                    spill_rows.append((action, msg, out, pid))
                    overflow -= 1
                    continue
            try:
                req, slot_key, fqn_str = self._build_row(action, msg)
            except Exception as e:  # noqa: BLE001 — per-row isolation,
                # like N independent publish calls: one bad row must not
                # strand its batch-mates
                out.set_exception(e)
                continue
            built.append((req, loop.create_future(), slot_key,
                          msg.activation_id.asString, msg, action, out,
                          fqn_str, pid))
        if spill_rows:
            self._spill_forward(spill_rows)
        if not built:
            return outs
        # the serial path notes an arrival only AFTER a successful row
        # build (a raising _build_row never reaches _note_arrival), so
        # the shared clock read counts built rows, not offered pairs —
        # else a burst of failing rows would decay the arrival EWMA and
        # flip _coalesce_window_s where serial stays eager
        t_now = time.monotonic()
        self._note_arrivals(t_now, len(built))
        if self.ring_assembly:
            # the NumPy column pass: every built row's packed column lands
            # in the preallocated ring in one [rows, k] block write (two
            # slice copies), replacing k per-row ring assignments. The
            # pending entries append in the SAME synchronous block, so the
            # two FIFOs cannot desync.
            self._req_ring.push_block(
                np.asarray([b[0] for b in built], np.int32).T)
        for req, fut, slot_key, aid, msg, _action, _out, fqn_str, pid \
                in built:
            entry = (req, fut, slot_key, t_now, aid, fqn_str,
                     trace_id_of(msg.trace_context))
            self._pending.append(
                entry if pid is None
                else entry + ((pid, self._row_epoch(msg, pid)),))
        self.waterfall.stamp_many([b[3] for b in built],
                                  STAGE_PUBLISH_ENQUEUE)
        self.metrics.histogram("loadbalancer_publish_batch_size",
                               len(built))
        # ONE shared flush decision for the whole admission batch (the
        # serial path decides per row, which at idle eagerly dispatches a
        # 1-deep device step for the batch's FIRST row): drain full
        # buckets inline, then apply the serial eager/window rule once.
        while (len(self._pending) >= self.max_batch
               and self._try_flush_now()):
            pass
        if self._pending and not (
                self._inflight_steps == 0
                and self._rtt_ewma_ms < self.RTT_FAST_MS
                and self._coalesce_window_s() == 0.0
                and self._try_flush_now()):
            self._arm_flush(urgent=len(self._pending) >= self.max_batch)
        # per-row continuations are DONE-CALLBACKS, not a task: at sweep
        # depth (a few rows per event-loop sweep at moderate rates) a
        # per-batch finisher task costs more than the per-row work it
        # amortizes — measured as a ~0.7 tasks/activation regression.
        # The callback chain mints zero loop objects beyond the two
        # futures the SPI contract needs, and the caller-cancellation
        # bridge makes the readback fan-out read a gone caller as an
        # abandoned publisher (capacity returned per row).
        for b in built:
            req, fut, slot_key, aid, msg, action, out, _fqn, _pid = b
            out.add_done_callback(
                lambda o, f=fut: (f.cancel() if (o.cancelled()
                                                 and not f.done())
                                  else None))
            fut.add_done_callback(
                lambda f, r=req, sk=slot_key, a=aid, m=msg, ac=action,
                o=out: self._row_placed(f, r, sk, a, m, ac, o))
        return outs

    def _row_placed(self, fut: asyncio.Future, req: tuple, slot_key: str,
                    aid: str, msg, action, out: asyncio.Future) -> None:
        """One batched-publish row's continuation (a done-callback on its
        placement future): the serial publish's post-placement body —
        error mapping, activation setup, fencing — then the dispatch send
        handed to the bus coalescer WITHOUT awaiting (its flush future
        resolves `out`, so send failures still surface exactly like the
        serial path's raised send errors). All rows of a readback wave run
        their callbacks in one sweep, so their sends coalesce into the
        same bus frames the serial path's fan-out produced."""
        wf = self.waterfall
        try:
            if fut.cancelled():
                # abandoned row: the readback fan-out (or the bridge
                # racing an unplaced row) already returned the capacity
                # and dropped the stage vector
                return
            exc = fut.exception()
            if exc is not None:
                # dispatch failure: the failing device step already
                # released this row's slot and discarded its vector
                if not out.done():
                    out.set_exception(exc)
                return
            inv_idx, forced = fut.result()
            if out.cancelled():
                # caller went away between the fan-out resolving the row
                # and this callback — the serial CancelledError branch
                self._abandon_placement(int(inv_idx), req, slot_key)
                wf.discard(aid)
                return
            # outcome mapping shared verbatim with the serial publish
            # (_map_placement): failure codes release capacity, discard
            # the vector and raise the serial texts — the enclosing
            # except hands them to `out` exactly like a serial raise
            invoker, promise = self._map_placement(inv_idx, forced, req,
                                                   slot_key, aid, msg,
                                                   action)
            send_nowait = getattr(self.producer, "send_nowait", None)
            if send_nowait is not None:
                # fence stamping + published counter shared with the
                # serial send (prepare_dispatch), so the two paths
                # cannot drift. Note this task-free submit is the one
                # dispatch that does NOT flow through the
                # send_activation_to_invoker hook — minting a coroutine
                # per row to honor it would be the exact per-activation
                # floor this path removes.
                topic = self.prepare_dispatch(msg, invoker)
                sendf = send_nowait(topic, msg)

                def _sent(sf: asyncio.Future) -> None:
                    # retrieve the flush outcome UNCONDITIONALLY (before
                    # any early-return): a caller gone by cancellation
                    # must not leave an unretrieved flush exception
                    # spamming the loop's GC-time logger
                    send_exc = (None if sf.cancelled()
                                else sf.exception())
                    if out.done():
                        return
                    if sf.cancelled():
                        # the coalescer's drainer was cancelled with the
                        # dispatch still queued (loop teardown): serial
                        # parity is the awaited send RAISING
                        # CancelledError to the caller — never success
                        # for an unsent dispatch
                        out.cancel()
                        return
                    if send_exc is not None:
                        # serial parity: the entry stays; the forced
                        # timeout self-heals the held capacity
                        out.set_exception(send_exc)
                    else:
                        out.set_result(promise)

                sendf.add_done_callback(_sent)
            else:
                # raw (non-coalescing) producer: no task-free submit —
                # one send task per row, the serial cost (this is the
                # coalescing-off configuration, not the hot path). The
                # task awaits send_activation_to_invoker (which runs
                # prepare_dispatch itself), so the documented dispatch
                # hook keeps covering this path for subclasses/tests.
                task = asyncio.get_event_loop().create_task(
                    self._send_then_resolve(invoker, msg, out, promise))
                self._publish_finishers.add(task)
                task.add_done_callback(self._publish_finishers.discard)
        except Exception as e:  # noqa: BLE001 — a raising done-callback
            # would land in the loop's exception handler and strand the
            # caller: fail the row instead
            if not out.done():
                out.set_exception(e)

    def _row_epoch(self, msg, pid: int) -> int:
        """The fence epoch a row is admitted under: our view of its
        partition's epoch, or the origin's stamp when that is ahead (a
        spilled row whose claim announcement we have not folded yet)."""
        ep = self.partition_epochs.get(pid, 0)
        if msg.fence_part == pid and msg.fence_epoch is not None:
            ep = max(ep, int(msg.fence_epoch))
        return ep

    def _spill_forward(self, rows: List[tuple]) -> None:
        """Forward an overflow sub-batch to the spillover sink
        (spillover.py). Each row is fence-stamped with ITS partition's
        current epoch BEFORE it leaves — the stamp is both the invoker
        fence and the peer-side admission credential — and the waterfall
        stamps the extra hop, then folds the origin-side partial vector
        (the peer's books own the rest of the row's life). The caller's
        future resolves to a completed placeholder promise: spillover
        only takes non-blocking rows, whose promise is never awaited."""
        wf = self.waterfall
        loop = asyncio.get_event_loop()
        pairs = []
        for action, msg, _out, pid in rows:
            msg.fence_part = pid
            msg.fence_epoch = self.partition_epochs.get(pid, 0)
            pairs.append((action, msg))
        try:
            sent = self.spillover_sink.forward(pairs)
        except Exception as e:  # noqa: BLE001 — a failing forward fails
            # its rows like a refused publish, never the whole batch —
            # and is never counted as a forward (no stamp, no counter)
            for _action, _msg, out, _pid in rows:
                if not out.done():
                    out.set_exception(LoadBalancerException(
                        f"spillover forward failed: {e}"))
            return
        # handed to the sink: NOW count the forwards and fold the
        # origin-side waterfall (an async send that later fails shows up
        # in loadbalancer_spillover_send_failed, like a lost produce)
        for _action, msg, _out, _pid in rows:
            aid = msg.activation_id.asString
            wf.stamp(aid, STAGE_SPILL_FORWARD)
            row = wf.finish(aid)
            # ISSUE 18: the origin half's tail verdict runs HERE — the
            # spill hop is this process's terminal stage (no completion
            # ack ever comes back to these books), so waiting for one
            # would leak the pending spans forever. The kept half (the
            # driver + hop spans joined to the partial stage vector) is
            # what /admin/trace/{id} merges with the peer's half.
            if self.trace_store.enabled:
                from ...utils.tracing import trace_id_of
                tid = (row or {}).get("trace_id") or trace_id_of(
                    getattr(msg, "trace_context", None))
                self.trace_store.complete(aid, tid, row=row)
        self.spilled_rows += len(rows)
        self.metrics.counter("loadbalancer_spillover_forwarded", len(rows))
        for (_action, _msg, out, _pid), row_sent in zip(rows, sent):
            placeholder: asyncio.Future = loop.create_future()
            placeholder.set_result(None)

            def _resolve(sf: asyncio.Future, o=out, p=placeholder) -> None:
                exc = None if sf.cancelled() else sf.exception()
                if exc is not None:
                    self.metrics.counter(
                        "loadbalancer_spillover_send_failed")
                if o.done():
                    return
                if sf.cancelled():
                    o.cancel()
                elif exc is not None:
                    o.set_exception(exc)
                else:
                    o.set_result(p)

            row_sent.add_done_callback(_resolve)

    async def _send_then_resolve(self, invoker, msg, out: asyncio.Future,
                                 promise) -> None:
        try:
            await self.send_activation_to_invoker(msg, invoker)
        except Exception as e:  # noqa: BLE001
            if not out.done():
                out.set_exception(e)
            return
        if not out.done():
            out.set_result(promise)

    def _abandon_placement(self, inv_idx: int, req: tuple, slot_key: str) -> None:
        """A publisher went away (client disconnect) after its request was
        (or will never be) placed. Route the reserved capacity through the
        normal release queue — which also frees the host conc slot at drain
        time, keeping the slot index pinned to this action until the
        device-side decrement lands."""
        if inv_idx >= 0:
            self._queue_release(inv_idx, req[self.R_CONC_SLOT],
                                req[self.R_NEED_MB], req[self.R_MAX_CONC],
                                slot_key)
            self._arm_flush()
        else:
            self._slots.release(slot_key, req[self.R_CONC_SLOT])

    def _queue_release(self, inv: int, slot: int, mem: int, maxc: int,
                       key: str) -> None:
        """Buffer one capacity release for the next device step (the slot
        KEY rides host-side for drain-time slot bookkeeping; the int column
        mirrors into the release ring for flush assembly)."""
        if self.ring_assembly:
            self._rel_ring.push((inv, slot, mem, maxc))
        self._releases.append((inv, slot, mem, maxc, key))

    # -- completion hooks --------------------------------------------------
    def release_invoker(self, invoker: InvokerInstanceId, entry) -> None:
        action_name = entry.action_key.rsplit("@", 1)[0]
        key = f"{action_name}:{entry.memory_mb}"
        slot = (entry.conc_slot if entry.conc_slot is not None
                else self._slots.lookup(key))
        self._queue_release(invoker.instance, slot, entry.memory_mb,
                            entry.max_concurrent, key)
        self._arm_flush()

    def on_invocation_finished(self, invoker, is_system_error, forced) -> None:
        self.supervision.on_invocation_finished(invoker, is_system_error, forced)

    async def invoker_health(self) -> List[InvokerHealth]:
        return self.supervision.health()

    #: occupancy() now serves from the last readback's CACHED books — no
    #: device sync, so the admin endpoint runs inline on the event loop and
    #: can never stall (or race a donated buffer under) the dispatch loop
    OCCUPANCY_SYNCS_DEVICE = False

    def occupancy(self) -> dict:
        """Per-invoker slots-in-use/capacity from the last device-step
        readback's cached free_mb copy (refreshed on every readback and
        every state install, so it exists from construction onward). Under
        a full pipeline the cache lags the dispatched state by up to
        `pipeline_depth` unread steps — and never costs a device->host
        transfer on the API path, which under buffer donation would
        additionally race the dispatch loop consuming the live buffer.
        Host books are snapshotted up front (list() is atomic under the
        GIL) and every index is length-guarded against concurrent fleet
        growth."""
        free = self._books_cache
        if free is None:  # pre-init construction window: empty fleet
            free = np.zeros((0,), np.int32)
        registry = list(self._registry)
        healthy = list(self._healthy)
        caps = self._caps_mb

        def rows():
            for i, inv in enumerate(registry):
                cap = (int(caps[i]) if i < len(caps)
                       else self._slot_mb(inv.user_memory.to_mb))
                f = int(free[i]) if i < len(free) else cap
                yield (inv.as_string,
                       healthy[i] if i < len(healthy) else False,
                       cap, f, cap - f)

        out = occupancy_json(self.kernel_resolved, rows())
        if self.mesh is not None:
            # per-shard books aggregated from the SAME cached vector —
            # still zero device syncs on the API path
            out["mesh"] = {"n_shards": self.n_shards,
                           "axis": self.fleet_axis}
            out["shards"] = self._shard_occupancy(free, caps)
        return out

    def _shard_occupancy(self, free, caps) -> List[dict]:
        """Per-shard occupancy rows from host-cached books. Shard s owns
        invoker rows [s*k, (s+1)*k) with k = n_pad / n_shards (the
        NamedSharding block layout); padding rows carry zero capacity and
        zero free, so they drop out of the sums."""
        rows_per = max(1, self._n_pad // max(1, self.n_shards))
        n_reg = len(caps)
        out = []
        for s in range(self.n_shards):
            lo, hi = s * rows_per, (s + 1) * rows_per
            reg_hi = min(hi, n_reg)
            cap = int(caps[lo:reg_hi].sum()) if lo < n_reg else 0
            f = int(free[lo:min(hi, len(free))].sum()) \
                if lo < len(free) else cap
            used = cap - f
            out.append({"shard": s,
                        "invokers": max(0, reg_hi - lo),
                        "capacity_mb": cap, "used_mb": used,
                        "occupancy": (round(used / cap, 4) if cap
                                      else 0.0)})
        return out

    def _export_shard_gauges(self) -> None:
        """`loadbalancer_fleet_shards` + per-shard occupancy ratios from
        the cached books — host numpy only, never a device sync (rides
        the 1 Hz supervision tick)."""
        self.metrics.gauge("loadbalancer_fleet_shards", self.n_shards)
        free = self._books_cache
        if free is None:
            return
        for row in self._shard_occupancy(free, self._caps_mb):
            self.metrics.gauge("loadbalancer_shard_occupancy_ratio",
                               row["occupancy"],
                               tags={"shard": str(row["shard"])})

    def kernel_profile(self) -> dict:
        """The profiling-plane payload, labeled with the kernel actually
        running (xla / pallas / sharded) — host-side reads only, no device
        sync (memory_stats is a runtime counter read, not an array pull)."""
        out = self.profiler.profile_json(kernel=self.kernel_resolved)
        out["placement_kernel"] = getattr(self, "placement_kernel_resolved",
                                          self.placement_kernel)
        out["kernel_chosen_by"] = getattr(self, "_kernel_chosen_by", "static")
        if self.mesh is not None:
            out["mesh"] = {"n_shards": self.n_shards,
                           "axis": self.fleet_axis}
        if self._calibration is not None:
            out["calibration"] = self._calibration
        return out

    # -- placement journal (HA plane; loadbalancer/journal.py) -------------
    def attach_journal(self, journal) -> None:
        """Adopt a PlacementJournal. Appends start from the max of the
        balancer's own seq and what the log already holds, so a restarted
        active never reuses a sequence number. Also registers the
        journal's durability lag as an alert signal: the built-in
        `journal_stall` rule (anomaly.py) fires when the lag stays above
        its threshold for its window — an fsync device stall — and
        /admin/ready surfaces the firing state."""
        self.journal = journal
        if journal is not None:
            self._journal_seq = max(self._journal_seq, journal.last_seq())
            self.anomaly.extra_signals["journal_lag_batches"] = (
                lambda: float(self.journal.lag_batches)
                if self.journal is not None else None)

    def _journal_live(self) -> bool:
        return (self.journal is not None and not self._journal_mute
                and not self.ha_standby)

    def _journal_append(self, rec: dict) -> int:
        """Stamp the next seq (and fencing epoch) onto `rec` and append.
        Returns the seq (0 when journaling is off). Called on the event
        loop in the SAME synchronous block as the state mutation it
        records, so journal order == device-state mutation order and a
        snapshot's `journal_seq` is exactly consistent with its books."""
        if not self._journal_live():
            return 0
        if (self.mesh is not None and not self._journal_mesh_stamped
                and rec.get("t") != "mesh"):
            # topology header: ONE `mesh` record ahead of this writer's
            # first append (rides alongside `reg`/`cluster`), so replay
            # can refuse a different device count with a logged reason
            self._journal_mesh_stamped = True
            from ...parallel.fleet_mesh import mesh_topology
            self._journal_append({"t": "mesh", **mesh_topology(self.mesh)})
        self._journal_seq += 1
        rec["seq"] = self._journal_seq
        if self.fence_epoch is not None:
            rec["epoch"] = self.fence_epoch
        try:
            self.journal.append(rec)
        except Exception as e:  # noqa: BLE001 — journaling degrades, the
            # placement path never dies for the flight data recorder
            if self.logger:
                self.logger.warn(None, f"journal append failed: {e!r}; "
                                       "detaching journal", "TpuBalancer")
            self.journal = None
        return rec.get("seq", 0)

    def replay_journal(self, records, logger=None,
                       from_seq: Optional[int] = None,
                       parts_filter=None, foreign: bool = False) -> dict:
        """Deterministically re-execute a journal tail on top of the
        current (snapshot-restored) state. Batch records re-run the SAME
        schedule/release kernels the active used (non-donated replay
        programs) over the recorded packed input buffers — placement is
        bit-deterministic (ops/placement parity suite), so the re-derived
        books equal the dead active's and the re-derived decisions equal
        the journaled readback (`parity_mismatches` counts divergence,
        e.g. a kernel-knob change across the restart). Structural records
        (registration/growth/cluster) re-apply their host-side mutation.

        Batches journaled at dispatch but crashed before readback replay
        with their full request set (conservative over-hold: those
        placements were computed on the dead device; self-heal via forced
        timeouts reclaims them, exactly the checkpoint posture).

        Mesh topology: a fleet-mesh writer stamps `mesh` records and a
        shard count (`S`) on every batch record. Replay proceeds only on
        a MATCHING topology (a promoted standby with the same device
        count reshards at restore and replays the tail bit-exactly);
        any mismatch — journal written at a different shard count, or a
        single-device journal replayed on a mesh (and vice versa) —
        COLD-STARTS with a logged reason instead of silently
        mis-sharding (`skipped: "mesh_topology"`).

        Active/active (ISSUE 15): `parts_filter` restricts the replay to
        records whose `parts` intersect the given partition set — the
        HANDOFF path, where the new owner of a partition set absorbs the
        previous owner's tail and nothing else (structural records —
        registration/growth/cluster — are the previous owner's OWN
        topology and are skipped under a filter). `foreign=True` marks
        the tail as another controller's journal: its seqs live in that
        journal's numbering, so this balancer's own `_journal_seq` never
        moves, and a topology mismatch SKIPS the absorb (logged) instead
        of cold-starting the survivor's live books. Records carrying a
        `pe` (per-partition epoch) map are additionally dropped PER
        PARTITION: a record whose every overlapping partition was
        superseded at-or-before its seq is a zombie owner's late flush."""
        stats: dict = {}
        for _ in self.replay_stepper(records, logger=logger,
                                     from_seq=from_seq,
                                     parts_filter=parts_filter,
                                     foreign=foreign, stats=stats):
            pass
        return stats

    def replay_stepper(self, records, logger=None,
                       from_seq: Optional[int] = None,
                       parts_filter=None, foreign: bool = False,
                       stats: Optional[dict] = None):
        """The replay engine behind `replay_journal`, exposed as a
        generator for the time-travel debugger (timetravel.py): yields one
        step dict `{seq, t, rec, detail}` per APPLIED record (acks and
        stale/filtered records are handled internally, exactly as before),
        so a consumer can stop at seq K, break on an activation id, or
        inspect the re-derived books between any two steps. `stats` is a
        caller-supplied dict mutated in place (replayed/batches/
        parity_mismatches/last_seq...) — shared state with the driver, and
        still correct when the consumer abandons the generator early:
        finalization (journal un-mute, host-books refresh, last_seq) runs
        in the generator's `finally`, i.e. also on `close()`."""
        log = logger or self.logger
        if stats is None:
            stats = {}
        if from_seq is not None and not foreign:
            self._journal_seq = int(from_seq)
        stats.update({"replayed": 0, "batches": 0, "parity_mismatches": 0,
                      "from_seq": (int(from_seq) if from_seq is not None
                                   else self._journal_seq)})
        self.profiler.expect("snapshot_restore")
        recs = [r for r in records]
        # stale-epoch filter: a demoted active's already-popped write batch
        # can still land in its own old segment AFTER the new epoch began —
        # any record whose epoch is superseded at-or-before its seq was
        # never part of the promoted active's state and must not replay
        first_seq: Dict[int, int] = {}
        #: per-partition variant of the same bound: (pid, epoch) -> first
        #: seq observed carrying it (records with a `pe` map)
        pfirst_seq: Dict[tuple, int] = {}
        for r in recs:
            e, s = int(r.get("epoch", 0)), int(r.get("seq", 0))
            first_seq[e] = min(first_seq.get(e, s), s)
            for pid_s, pe in (r.get("pe") or {}).items():
                key = (int(pid_s), int(pe))
                pfirst_seq[key] = min(pfirst_seq.get(key, s), s)
        bounds = sorted(first_seq.items())
        pbounds: Dict[int, list] = {}
        for (pid, e), s in sorted(pfirst_seq.items()):
            pbounds.setdefault(pid, []).append((e, s))

        def _fresh_for(pid: int, e: int, s: int) -> bool:
            return not any(e2 > e and s2 <= s
                           for e2, s2 in pbounds.get(pid, ()))

        def _fresh(r: dict) -> bool:
            e, s = int(r.get("epoch", 0)), int(r.get("seq", 0))
            if any(e2 > e and s2 <= s for e2, s2 in bounds):
                return False
            pe = r.get("pe")
            if not pe:
                return True
            # fresh while ANY overlapping partition is fresh — a batch
            # mixing a stale and a live partition still owes the live
            # partition its holds (the stale rows are epsilon over-hold,
            # self-healed by forced timeouts like every replay over-hold)
            pids = ((int(p) for p in pe)
                    if parts_filter is None
                    else (int(p) for p in pe if int(p) in parts_filter))
            return any(_fresh_for(p, int(pe[str(p)]), s) for p in pids)

        if parts_filter is not None:
            parts_filter = set(int(p) for p in parts_filter)
            kept = []
            kept_seqs = set()
            for r in recs:
                t = r.get("t")
                if t == "batch":
                    if parts_filter & set(int(p) for p in
                                          r.get("parts") or ()):
                        kept.append(r)
                        kept_seqs.add(int(r.get("seq", 0)))
                elif t == "ack":
                    # an ack applies through its dispatch-time batch
                    # record: absorbed iff that batch was
                    if int(r.get("for", 0)) in kept_seqs:
                        kept.append(r)
                # everything else (reg/grow/slots/cluster/reinit/fold/
                # mesh): the previous owner's own topology and idle
                # bookkeeping — never part of a partition handoff
            stats["filtered_out"] = len(recs) - len(kept)
            recs = kept
        n_all = len(recs)
        recs = [r for r in recs if _fresh(r)]
        stats["stale_epoch_dropped"] = n_all - len(recs)
        # acks key their dispatch-time batch record by `for` (the ack's own
        # seq only orders it in the log)
        acks = {int(r["for"]): r for r in recs
                if r.get("t") == "ack" and "for" in r}
        replay_step = make_fused_step_packed(self._release_fn, self._sched_fn)
        replay_release = make_release_packed(self._release_fn)
        # foreign tails run on a LOCAL cursor in the dead owner's seq
        # space; our own journal numbering is untouched
        cursor = (int(from_seq or 0) if foreign else self._journal_seq)
        self._journal_mute = True
        cold = False
        try:
            for rec in recs:
                t = rec.get("t")
                seq = int(rec.get("seq", 0))
                if t == "ack":
                    # already applied through its batch record; still claim
                    # the seq so the promoted active never reuses it
                    cursor = max(cursor, seq)
                    if not foreign:
                        self._journal_seq = cursor
                    continue
                if seq <= cursor:
                    continue
                if t in ("batch", "mesh"):
                    got = int(rec.get("S" if t == "batch" else "n_shards",
                                      1))
                    if got != self.n_shards:
                        if foreign:
                            # NEVER cold-start a live survivor's books
                            # over an absorbed tail: skip the absorb, say
                            # so — the epoch bump (which already
                            # happened) is the correctness guarantee;
                            # the un-replayed holds self-heal
                            if log:
                                log.warn(None, "absorbed journal tail was "
                                               f"written at {got} fleet "
                                               f"shard(s), this balancer "
                                               f"runs {self.n_shards}; "
                                               "skipping the absorb "
                                               "replay", "TpuBalancer")
                            stats["skipped"] = "mesh_topology"
                            break
                        cold = True
                        self._topology_coldstart(stats, recs, got, log)
                        return
                detail = None
                if t == "mesh":
                    pass  # topology verified above; nothing to re-apply
                elif t == "batch":
                    detail = self._replay_batch(rec, acks.get(seq),
                                                replay_step, stats)
                elif t == "fold":
                    self._replay_fold(rec, replay_release)
                elif t == "reg":
                    self._replay_reg(rec)
                elif t == "grow":
                    if int(rec["n_pad"]) > self._n_pad:
                        self._grow_padding(int(rec["n_pad"]))
                elif t == "slots":
                    if int(rec["action_slots"]) > self.action_slots:
                        self._grow_slots(int(rec["action_slots"]))
                elif t == "cluster":
                    self.update_cluster(int(rec["size"]))
                elif t == "reinit":
                    self._init_device_state()
                elif log:
                    log.warn(None, f"journal record type {t!r} unknown "
                                   "(newer writer?); skipped", "TpuBalancer")
                stats["replayed"] += 1
                cursor = max(cursor, seq)
                if not foreign:
                    self._journal_seq = cursor
                yield {"seq": seq, "t": t, "rec": rec, "detail": detail}
        finally:
            self._journal_mute = False
            if not cold:
                self._set_books_now(np.asarray(self.state.free_mb))
                stats["last_seq"] = cursor
                if stats["parity_mismatches"] and log:
                    log.warn(None, f"journal replay re-derived "
                                   f"{stats['parity_mismatches']} decisions "
                                   "differently than the recorded readback "
                                   "(kernel knobs changed across the "
                                   "restart?)", "TpuBalancer")

    def absorb_partitions(self, pids, journal, snap_doc=None,
                          logger=None) -> dict:
        """Partition handoff, absorb side (ISSUE 15): replay the PREVIOUS
        owner's journal tail — filtered to exactly the partitions this
        controller just claimed — through the same kernels, on top of the
        live books. This is PR 8's promote-and-replay scoped per
        partition: the dead (or rebalanced-away) owner's post-snapshot
        in-flight holds for these partitions land on the new owner's
        books conservatively (un-acked rows self-heal via forced
        timeouts), per-partition stale epochs drop, and the previous
        owner's structural records never touch our topology. The
        absorbed tail's seqs stay in the previous owner's numbering
        (`foreign`), so our own journal order is untouched. The epoch
        bump that fences the previous owner happened at claim time
        (set_partition_leadership) — this replay is books-accuracy, the
        fence is the zero-double-execution guarantee.

        Every failure path degrades to skipped-absorb with the fence
        still in place; never an abort."""
        log = logger or self.logger
        pids = set(int(p) for p in pids)
        for pid in pids:
            self.partition_replay[pid] = "replaying"
        from_seq = int((snap_doc or {}).get("journal_seq", 0))
        GLOBAL_EVENT_LOG.record("absorb_start",
                                instance=self.controller.instance,
                                parts=sorted(pids), from_seq=from_seq)
        stats = {"absorbed_partitions": sorted(pids), "replayed": 0}
        try:
            stats = self.replay_journal(journal.records(from_seq),
                                        logger=log, from_seq=from_seq,
                                        parts_filter=pids, foreign=True)
            stats["absorbed_partitions"] = sorted(pids)
        except Exception as e:  # noqa: BLE001 — degrade, never abort: the
            # claim's epoch bump already fences the previous owner
            stats["skipped"] = f"absorb_error: {e!r}"
            if log:
                log.warn(None, f"partition absorb replay failed ({e!r}); "
                               "continuing with the fence only",
                         "TpuBalancer")
        finally:
            for pid in pids:
                self.partition_replay[pid] = "ready"
        GLOBAL_EVENT_LOG.record("absorb_end",
                                instance=self.controller.instance,
                                parts=sorted(pids),
                                replayed=int(stats.get("replayed", 0)),
                                skipped=stats.get("skipped"))
        self.metrics.counter("loadbalancer_partitions_absorbed", len(pids))
        return stats

    def _topology_coldstart(self, stats: dict, recs: list, got: int,
                            log) -> dict:
        """A journal tail written at a different mesh topology cannot be
        replayed here (the packed records are deterministic only through
        the SAME sharded kernels): cold-start — fresh full-capacity books
        over the restored registry; leaked in-flight holds self-heal via
        forced timeouts, exactly the pruned-tail posture — with a logged
        reason. Every seq in the tail is still claimed so a promoted
        active never reuses one."""
        if log:
            log.warn(None, f"placement journal tail was written at {got} "
                           f"fleet shard(s) but this balancer runs "
                           f"{self.n_shards}; cold-starting instead of "
                           f"mis-sharding the replay", "TpuBalancer")
        stats["skipped"] = "mesh_topology"
        stats["journal_shards"] = got
        stats["balancer_shards"] = self.n_shards
        self._journal_seq = max(
            [self._journal_seq] + [int(r.get("seq", 0)) for r in recs])
        self._init_device_state()
        stats["last_seq"] = self._journal_seq
        return stats

    def _replay_batch(self, rec: dict, ack: Optional[dict], replay_step,
                      stats: dict) -> dict:
        R, H, B = int(rec["R"]), int(rec["H"]), int(rec["B"])
        rows, b = int(rec["rows"]), int(rec["b"])
        buf = decode_array(rec["buf"])
        rel = buf[:5 * R]
        health = buf[5 * R:5 * R + 3 * H]
        req = buf[5 * R + 3 * H:].reshape(rows, B)[:9].copy()
        if ack is not None:
            out_rec = np.asarray(ack["out"], np.int64)
            throttled = ((out_rec >> 1) & 1).astype(bool)
            # device rate admission already rejected these at commit time:
            # replay with their valid bit cleared so the re-derived books
            # hold exactly what the committed step held
            req[8, :len(throttled)] &= ~throttled
        buf9 = np.concatenate([rel, health, req.ravel()]).astype(np.int32)
        self.state, out = replay_step(self.state, buf9, R, H, B)
        stats["batches"] += 1
        #: per-batch evidence for the time-travel debugger (timetravel.py):
        #: the driver (replay_journal) ignores it
        detail: dict = {"b": b, "aids": rec.get("aids") or [],
                        "acked": ack is not None, "mismatches": 0}
        if ack is not None:
            derived = np.asarray(out)[:b].astype(np.int64)
            recorded = np.asarray(ack["out"], np.int64)[:b]
            thr = ((recorded >> 1) & 1).astype(bool)
            mism = int(np.count_nonzero(derived[~thr] != recorded[~thr]))
            stats["parity_mismatches"] += mism
            detail.update({"derived": derived, "recorded": recorded,
                           "throttled": thr, "mismatches": mism})
        return detail

    def _replay_fold(self, rec: dict, replay_release) -> None:
        if "rel" in rec:
            rel = decode_array(rec["rel"]).reshape(5, -1)
            self.state = replay_release(self.state, rel)
        health = rec.get("health")
        if health:
            self.state = set_health(self.state,
                                    [int(i) for i, _ in health],
                                    [bool(v) for _, v in health])

    def _replay_reg(self, rec: dict) -> None:
        new_rows = []
        for j, healthy in zip(rec["reg"], rec["healthy"]):
            inv = InvokerInstanceId.from_json(j)
            idx = inv.instance
            while idx >= len(self._registry):
                new_rows.append(len(self._registry))
                self._registry.append(inv)
                self._healthy.append(False)
            self._registry[idx] = inv
            self._healthy[idx] = bool(healthy)
        if new_rows:
            if len(self._registry) > self._n_pad:
                self._grow_padding(_next_pow2(len(self._registry)))
            slot_vals = jnp.asarray(
                [self._slot_mb(self._registry[i].user_memory.to_mb)
                 for i in new_rows], jnp.int32)
            self.state = self.state._replace(
                free_mb=self.state.free_mb.at[jnp.asarray(new_rows)].set(
                    slot_vals))
        self._recompute_partitions()

    # -- checkpoint / resume (SURVEY §5.4) ---------------------------------
    def snapshot_parts(self) -> dict:
        """Event-loop-side capture for a snapshot: ONE consistent reference
        to the (immutable) device state plus copies of the host books. The
        heavy device->host transfer can then run on a worker thread
        (checkpoint.BalancerSnapshotter) without racing loop mutations or
        mixing books from different device steps. With buffer donation ON
        the captured state is an explicit device-side COPY: the live
        reference would be consumed (invalidated) by the next pipelined
        dispatch before the worker thread gets to read it."""
        return {
            "state": self._materialize_state(),
            "journal_seq": self._journal_seq,
            "n_pad": self._n_pad,
            "fleet_shards": self.n_shards,
            "cluster_size": self._cluster_size,
            "action_slots": self.action_slots,
            "registry": [inv.to_json() for inv in self._registry],
            "healthy": list(self._healthy),
            "slots": dict(self._slots.slots),
            "slot_refcount": dict(self._slots.refcount),
            "slot_overflow": {k: list(v)
                              for k, v in self._slots.overflow.items()},
        }

    def snapshot(self, parts: Optional[dict] = None) -> dict:
        """Host-side snapshot of the device capacity matrix + registry. The
        balancer state is soft (reconstructible from pings/acks), so this is
        the whole checkpoint story: dump it periodically, restore on boot to
        skip the warm-up window. Thread-safe given `parts` from
        snapshot_parts()."""
        parts = dict(parts) if parts is not None else self.snapshot_parts()
        state = parts.pop("state")
        conc = np.asarray(state.conc_free)
        nz = np.nonzero(conc)
        parts["free_mb"] = np.asarray(state.free_mb).tolist()
        parts["conc_nonzero"] = [[int(i), int(j), int(conc[i, j])]
                                 for i, j in zip(*nz)]
        return parts

    def restore(self, snap: dict) -> None:
        self.profiler.expect("snapshot_restore")
        # the snapshot's books are GLOBAL (topology-independent): restoring
        # them onto a different shard count is a deterministic reshard —
        # _install_state re-places every row on this balancer's own mesh.
        # Said out loud because the JOURNAL tail is not topology-portable
        # (replay_journal cold-starts on a mismatch).
        snap_shards = int(snap.get("fleet_shards", 1))
        if snap_shards != self.n_shards and self.logger:
            self.logger.info(
                None, f"snapshot was taken at {snap_shards} fleet "
                f"shard(s); resharding deterministically onto "
                f"{self.n_shards}", "TpuBalancer")
        # the snapshot's books already hold every journaled mutation up to
        # this seq: replay_journal resumes from here (older snapshots carry
        # no seq — a full-history journal replays from 0)
        self._journal_seq = int(snap.get("journal_seq", 0))
        self._n_pad = int(snap["n_pad"])
        if self.mesh is not None and self._n_pad % self.n_shards:
            # a single-device snapshot may carry a pad the mesh cannot
            # divide: round up (extra rows are unhealthy zero-capacity
            # padding, exactly like growth padding)
            self._n_pad = _next_pow2(max(self._n_pad, self.n_shards))
        self._cluster_size = int(snap["cluster_size"])
        # older snapshots predate the growable slot axis
        self.action_slots = int(snap.get("action_slots", self.action_slots))
        self._registry = [InvokerInstanceId.from_json(j)
                          for j in snap["registry"]]
        self._healthy = [bool(h) for h in snap["healthy"]]
        free = np.asarray(snap["free_mb"], np.int32)
        if len(free) < self._n_pad:  # pad rounded up above
            free = np.concatenate(
                [free, np.zeros((self._n_pad - len(free),), np.int32)])
        conc = np.zeros((self._n_pad, self.action_slots), np.int32)
        for i, j, v in snap.get("conc_nonzero", []):
            conc[i, j] = v
        health = np.zeros((self._n_pad,), bool)
        health[: len(self._healthy)] = self._healthy
        self._install_state(PlacementState(jnp.asarray(free),
                                           jnp.asarray(conc),
                                           jnp.asarray(health)))
        self._slots.n_slots = self.action_slots
        self._slots.slots = dict(snap.get("slots", {}))
        self._slots.refcount = dict(snap.get("slot_refcount", {}))
        self._slots.overflow = {k: [int(v[0]), int(v[1])]
                                for k, v in snap.get("slot_overflow", {}).items()}
        used = set(self._slots.slots.values())
        self._slots.free = [s for s in range(self.action_slots - 1, -1, -1)
                            if s not in used]
        self._recompute_partitions()

    # -- the device step ---------------------------------------------------

    #: adaptive dispatch window (see PlacementPathConfig.adaptive_window):
    #: the bounded accumulation delay a loaded balancer trades for batch
    #: size, and the minimum batch a window must be expected to gather to
    #: be worth holding (below that, eager dispatch wins on latency with
    #: nothing to amortize)
    ADAPTIVE_WINDOW_MS = 8.0
    ADAPTIVE_MIN_BATCH = 4

    def _note_arrival(self, now: float) -> None:
        """Track the publish inter-arrival EWMA — the pressure signal the
        adaptive window switches on. One subtract + one blend per publish."""
        gap_ms = (now - self._last_pub_t) * 1e3
        self._last_pub_t = now
        self._last_gap_ms = gap_ms
        self._gap_ewma_ms = min(0.9 * self._gap_ewma_ms + 0.1 * gap_ms,
                                1000.0)

    def _note_arrivals(self, now: float, n: int) -> None:
        """Arrival accounting for a whole admission batch at ONE shared
        clock read (the ISSUE 14 small fix: the serial path paid a
        time.monotonic() + blend per activation). Equivalent to n serial
        `_note_arrival(now)` calls: the first blends the real gap, the
        remaining n-1 blend zero gaps — a pure 0.9^(n-1) decay, applied
        in closed form (the 1000 ms clamp only ever binds on the first
        blend, since decay shrinks). At n=1 this IS `_note_arrival`,
        bit-exact."""
        self._note_arrival(now)
        if n > 1:
            self._gap_ewma_ms *= 0.9 ** (n - 1)
            self._last_gap_ms = 0.0

    def _coalesce_window_s(self) -> float:
        """> 0 when arrival pressure says windowed batching beats eager
        dispatch: the EWMA predicts at least ADAPTIVE_MIN_BATCH arrivals
        inside one window, and the instantaneous gap confirms traffic is
        still flowing (a lone request after a burst must not inherit the
        burst's window)."""
        if (self.adaptive_window
                and self._gap_ewma_ms * self.ADAPTIVE_MIN_BATCH
                <= self.ADAPTIVE_WINDOW_MS
                and self._last_gap_ms <= self.ADAPTIVE_WINDOW_MS):
            return self.ADAPTIVE_WINDOW_MS / 1e3
        return 0.0

    def _arm_flush(self, urgent: bool = False) -> None:
        if getattr(self, "_closing", False):
            return  # close() drains queued releases host-side itself
        window = self._coalesce_window_s()
        # idle fast path: with no step in flight there is nothing to batch
        # WITH — waiting out the window would only add latency (the window
        # exists to amortize a round trip that is already being paid).
        # Under arrival pressure the adaptive window overrides: the batch
        # forming over the next few ms IS the thing to batch with.
        if self._inflight_steps == 0 and self._pending and window == 0.0:
            urgent = True
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_event_loop().create_task(
                self._flush_later(0 if urgent
                                  else (window or self.batch_window)))

    async def _flush_later(self, delay: float) -> None:
        # loop INSIDE the task until drained: a tail call to _arm_flush would
        # be a no-op (this task is not done() yet) and strand leftover work
        while True:
            if delay:
                await asyncio.sleep(delay)
            async with self._step_lock:
                await self._device_step()
            if not (self._pending or self._releases or self._health_updates):
                return
            delay = self._coalesce_window_s() or self.batch_window

    #: request-tuple field indices (row order of the packed matrix)
    R_NEED_MB, R_CONC_SLOT, R_MAX_CONC = 4, 5, 6

    #: namespace-bucket axis for device rate admission
    RATE_NS_BUCKETS = 1024

    #: tail sub-range of the bucket axis reserved for overflow namespaces
    #: (beyond RATE_NS_BUCKETS - RATE_NS_SHARED_BUCKETS dedicated tenants):
    #: they CRC32-hash into these shared buckets, so conflation stays among
    #: overflow namespaces instead of draining dedicated tenants' tokens
    RATE_NS_SHARED_BUCKETS = 64

    #: health updates drained per device step — a FIXED batch shape, so the
    #: fused program's compile-cache keys vary only in (release, batch)
    #: buckets; leftovers roll to the next step (fleet churn is slow vs the
    #: step rate)
    HEALTH_BATCH = 64

    #: below this measured round trip the device counts as "fast": eager
    #: idle dispatch wins; above it, wave batching wins (round trips on a
    #: tunneled device serialize rather than pipeline)
    RTT_FAST_MS = 5.0

    #: don't pay a telemetry-fold dispatch on the hot path for fewer than
    #: this many buffered completion events; the supervision tick and the
    #: scrape-time drain pick up the tail within a second
    TELEMETRY_FOLD_MIN = 64

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        """Pad batch sizes to power-of-two buckets so the jitted kernels see
        at most log2(max_batch) distinct shapes (no per-size recompiles)."""
        b = 8
        while b < n and b < cap:
            b *= 2
        return min(b, cap) if n <= cap else cap

    def _release_packed(self, pad_to: Optional[int] = None) -> np.ndarray:
        """Drain buffered releases into ONE packed int32[5,R] host array
        (+ host-side slot bookkeeping) — same padding as _release_arrays.
        With ring_assembly the int columns were written at enqueue time, so
        assembly is two contiguous slice copies instead of a list-of-tuples
        np.array transpose.

        The per-step drain cap equals max_batch (not a multiple): the
        batch-shaped ack path (ISSUE 12) lands a whole completion
        frame's releases in one sweep, and larger caps reached R buckets
        the steady state never compiles. The backlog still drains at >=
        the ack arrival rate (releases match placements one-to-one), so
        the leftover queue is bounded by one burst.

        `pad_to`: the fused-step caller passes its shared (R, B) bucket
        — see _dispatch_batch's shared-bucket rule — so the release axis
        pads to the SAME power of two as the request axis instead of
        minting an independent static dim."""
        cap = self.max_batch
        rel, self._releases = self._releases[:cap], self._releases[cap:]
        b = self._bucket(len(rel), cap) if rel else 8
        if pad_to is not None:
            b = max(b, pad_to)
        out = np.zeros((5, b), np.int32)
        out[3, len(rel):] = 1  # padded rows: maxc=1
        if rel:
            if self.ring_assembly:
                self._rel_ring.pop_into(out[:4], len(rel))
            else:
                out[:4, :len(rel)] = np.array([r[:4] for r in rel],
                                              np.int32).T
            out[4, :len(rel)] = 1
        for r in rel:
            self._slots.release(r[4], r[1])
        return out

    def _health_packed(self) -> np.ndarray:
        """Drain up to HEALTH_BATCH flips into ONE packed int32[3,H] array —
        same repeat-last padding rule as _health_arrays."""
        b = self.HEALTH_BATCH
        take = list(self._health_updates.items())[:b]
        for k, _ in take:
            del self._health_updates[k]
        out = np.zeros((3, b), np.int32)
        if take:
            pad = b - len(take)
            idxs = [k for k, _ in take] + [take[-1][0]] * pad
            vals = [int(v) for _, v in take] + [int(take[-1][1])] * pad
            out[0] = idxs
            out[1] = vals
            out[2] = 1
        return out

    def _try_flush_now(self) -> bool:
        """Synchronous dispatch fast path: runs the batch dispatch inline
        when the pipeline has capacity and no flush task is mid-step. The
        dispatch body has no awaits, so it is atomic on the event loop."""
        if (self._pending and not self._step_lock.locked()
                and self._inflight_steps < self.pipeline_depth
                and not getattr(self, "_closing", False)):
            self._set_inflight(1)
            self._dispatch_batch()
            return True
        return False

    async def _device_step(self) -> None:
        if not self._pending:
            # nothing to schedule: fold releases (padded+masked like the
            # fused path) and health (exact-size; dict keys are unique)
            folded = bool(self._releases)
            try:
                rel_np = ups = None
                if self._releases:
                    rel_np = self._release_packed()
                    self.state = self._release_packed_fn(self.state, rel_np)
                if self._health_updates:
                    ups, self._health_updates = self._health_updates, {}
                    self.state = set_health(self.state, list(ups.keys()),
                                            list(ups.values()))
                if (rel_np is not None or ups) and self._journal_live():
                    fold = {"t": "fold"}
                    if rel_np is not None:
                        fold["rel"] = encode_array(rel_np)
                    if ups:
                        fold["health"] = [[int(k), bool(v)]
                                          for k, v in ups.items()]
                    self._journal_append(fold)
            except Exception as e:  # noqa: BLE001 — a failed donated fold
                # may have CONSUMED self.state: without a rebuild every
                # later idle fold dies on the deleted buffer and a
                # drain-only balancer stays wedged indefinitely. (The
                # popped releases are moot either way: rebuilt books start
                # at full capacity.)
                if not self._recover_consumed_state():
                    raise
                if self.logger:
                    self.logger.error(None, f"idle fold failed: {e!r}",
                                      "TpuBalancer")
            if folded:
                # no schedule means no readback to piggyback the occupancy
                # cache on — refresh it off-loop so idle fleets converge
                self._refresh_books_async()
            try:
                self.telemetry.device_fold()
            except Exception as e:  # noqa: BLE001 — a telemetry failure
                # must not kill the flush task (stranding queued releases)
                if self.logger:
                    self.logger.warn(None, f"telemetry fold failed: {e!r}",
                                     "TpuBalancer")
            return

        # bound dispatched-but-unread steps (capacity freed by the readback
        # task) BEFORE popping the batch: a cancellation while waiting here
        # (close() cancels the flush task) must leave the queue intact so
        # close() can fail those publishers instead of stranding them
        while self._inflight_steps >= self.pipeline_depth:
            self._capacity_free.clear()
            await self._capacity_free.wait()
        self._set_inflight(1)
        self._dispatch_batch()

    def _dispatch_batch(self) -> None:
        batch, self._pending = self._pending[: self.max_batch], \
            self._pending[self.max_batch:]
        t0 = time.monotonic()
        b = len(batch)
        # ONE shared power-of-two bucket for the release AND request axes:
        # R and B are independent static dims of the fused program, so
        # their cross product is the jit cache-key space — log2 x log2
        # combos, most compiled mid-run the first time an arrival pattern
        # surfaces them (the batch-shaped ack path made this chronic:
        # measured as repeated ~400 ms first-sight compile stalls).
        # Padding both axes to max(R_bucket, B_bucket) collapses the key
        # space to log2(max_batch) shapes, which one warmup pass covers;
        # the cost is a few masked zero rows in a kernel that is already
        # shape-padded.
        n_rel = min(len(self._releases), self.max_batch)
        bp = max(self._bucket(b, self.max_batch),
                 self._bucket(n_rel, self.max_batch) if n_rel else 8)
        # ONE packed request matrix: row layout must match
        # make_fused_step_packed (offset..rand, valid); request tuples are
        # already in row order, so one C-speed np.array call fills it.
        # Padded request columns keep size=1/max_conc=1 like the old
        # pad_req dict
        rate_on = self.rate_limit_per_minute is not None
        rows = 10 if rate_on else 9
        req_np = np.zeros((rows, bp), np.int32)
        req_np[1, b:] = 1  # size
        req_np[6, b:] = 1  # max_conc
        if self.ring_assembly:
            # columns were written at publish() time: drain the b oldest
            # (rate off drops the ring's ns_slot row — pop_into copies only
            # the rows req_np carries)
            self._req_ring.pop_into(req_np, b)
        else:
            req_np[:, :b] = np.array(
                [entry[0][:rows] for entry in batch], np.int32).T
        # flight-recorder input digest, captured host-side before the step
        # (batch is FIFO: batch[0] carries the oldest enqueue time)
        rec = None
        if self.flight_recorder.enabled:
            rec = BatchRecord(digest={
                "kernel": self.kernel_resolved,
                "healthy_invokers": sum(self._healthy),
                "queue_depth": b + len(self._pending),
                "oldest_age_ms": round((t0 - batch[0][3]) * 1e3, 3),
            })
            if self.mesh is not None:
                rec.digest["shards"] = self.n_shards
            tid = next((e[6] for e in batch if e[6]), None)
            if tid is not None:
                # the record carries a trace: the phase histogram's bucket
                # line gets an exemplar pointing at it (OpenMetrics only)
                rec.digest["trace_id"] = tid
        # waterfall: assemble/dispatch/readback are BATCH events — one
        # shared timestamp per edge for every activation in the batch (the
        # aid list is built once, only when the plane is live)
        wf = self.waterfall
        wf_aids = [e[4] for e in batch] if wf.enabled else None
        rel_np = self._release_packed(pad_to=bp)
        health_np = self._health_packed()
        # releases + health flips + schedule: ONE device program over ONE
        # host->device transfer and ONE packed result vector back (the old
        # column-wise path did 16 in + 2 out — on a tunneled chip the
        # transfer round-trips dominate the step, not the kernel). No
        # await between the pop above and the task creation below, so no
        # cancellation window can orphan the popped batch.
        buf = np.concatenate([rel_np.ravel(), health_np.ravel(),
                              req_np.ravel()])
        t_assembled = time.monotonic()
        # shadow counterfactual (quality plane, every K batches): a
        # decision-only pass over the SAME packed buffer, enqueued BEFORE
        # the (possibly donating) production step so it reads the
        # pre-step buffers off the device stream. It writes nothing back;
        # `now` is hoisted and shared so the rate-admission fold (a pure
        # function of buckets/now) reproduces the production admitted set
        # exactly.
        now32 = (np.float32(time.monotonic() - self._t0_mono)
                 if rate_on else None)
        shadow_out = None
        if self._shadow_fn is not None:
            self._quality_batches += 1
            k = self.quality.shadow_every_n
            if k > 0 and self._quality_batches % k == 0:
                try:
                    if rate_on:
                        shadow_out = self._shadow_fn(
                            (self.state, self._bucket_state), buf,
                            self._shadow_penalty, now32,
                            rel_np.shape[1], health_np.shape[1], bp)
                    else:
                        shadow_out = self._shadow_fn(
                            self.state, buf, self._shadow_penalty,
                            rel_np.shape[1], health_np.shape[1], bp)
                except Exception as e:  # noqa: BLE001 — the shadow is
                    # observability: it must never take placement down
                    shadow_out = None
                    if self.logger:
                        self.logger.warn(None, f"shadow step failed: {e!r}",
                                         "TpuBalancer")
        # host-observatory bracket: a GC pause landing inside this window
        # stalls the device dispatch — counting it here turns a mysterious
        # dispatch-stage outlier in the waterfall into an attributed cause
        GLOBAL_HOST_OBSERVATORY.begin_dispatch()
        try:
            if rate_on:
                (self.state, self._bucket_state), out = self._packed_fn(
                    (self.state, self._bucket_state), buf, now32,
                    rel_np.shape[1], health_np.shape[1], bp)
            else:
                self.state, out = self._packed_fn(
                    self.state, buf, rel_np.shape[1], health_np.shape[1], bp)
        except Exception as e:  # noqa: BLE001 — a failed dispatch must not
            # leak the permit, the host-side conc slots, or strand the
            # publishers (device capacity from the drained releases is
            # recovered by forced-timeout self-heal)
            self._set_inflight(-1)
            self._capacity_free.set()
            self._recover_consumed_state()
            for req, fut, slot_key, _t, aid, *_ in batch:
                self._slots.release(slot_key, req[self.R_CONC_SLOT])
                wf.discard(aid)
                if not fut.done():
                    fut.set_exception(
                        LoadBalancerException(f"device dispatch failed: {e}"))
            if self.logger:
                self.logger.error(None, f"device dispatch failed: {e!r}",
                                  "TpuBalancer")
            return
        finally:
            GLOBAL_HOST_OBSERVATORY.end_dispatch()

        # quality scoring (every batch when the plane is armed): one tiny
        # read-only program over the POST-commit books, the decision
        # vector and the anomaly EWMAs — enqueued async on the same
        # stream; the summary row resolves on the readback worker
        q_summary = None
        if self.quality.enabled:
            try:
                q_summary = self.quality.device_step(
                    self.state.free_mb, self.state.conc_free,
                    self.state.health, self._quality_ewma,
                    self._quality_caps, req_np[:9], out, shadow_out)
            except Exception as e:  # noqa: BLE001 — scoring must never
                # take the placement path down with it
                if self.logger:
                    self.logger.warn(None, f"quality step failed: {e!r}",
                                     "TpuBalancer")

        # write-ahead journal: the state mutation above is committed on
        # the loop, so the record lands at exactly this point in mutation
        # order (readback appends a matching `ack` with the decisions)
        jseq = 0
        if self._journal_live():
            jrec = {
                "t": "batch", "R": int(rel_np.shape[1]),
                "H": int(health_np.shape[1]), "B": bp,
                "rows": rows, "b": b, "buf": encode_array(buf),
                "aids": [e[4] for e in batch]}
            if self.partition_ring is not None:
                # active/active: the record carries its rows' ring
                # partitions plus the epoch each was admitted under, so
                # a handoff replays EXACTLY the partitions the new owner
                # absorbed and drops per-partition stale epochs
                # (replay_journal parts_filter). Off-mode records carry
                # neither key — the wire format is unchanged.
                pe: Dict[str, int] = {}
                for e in batch:
                    if len(e) > 7:
                        p, ep = e[7]
                        pe[str(p)] = max(pe.get(str(p), 0), int(ep))
                jrec["parts"] = sorted(int(p) for p in pe)
                jrec["pe"] = pe
            if self.mesh is not None:
                # shard count travels on EVERY batch record (the one-shot
                # `mesh` header can be pruned away with its snapshot):
                # replay refuses a topology mismatch per batch
                jrec["S"] = self.n_shards
            jseq = self._journal_append(jrec)
        # compile-ahead: warm the successor bucket shapes off-loop before
        # queue growth needs them in a live dispatch
        self._prewarm_buckets(rel_np.shape[1], health_np.shape[1], bp)
        # completion telemetry rides the SAME dispatch cycle: at most one
        # extra scatter-add program per batch over event rows already packed
        # host-side — asynchronous like the step itself, no readback (counts
        # stay on device until a scrape). Small tails are left for the 1 Hz
        # supervision tick / scrape-time drain instead of paying a dispatch
        # for a near-empty fold on every micro-batch.
        try:
            if self.telemetry.pending >= self.TELEMETRY_FOLD_MIN:
                self.telemetry.device_fold()
        except Exception as e:  # noqa: BLE001 — telemetry must never take
            # the placement path down with it
            if self.logger:
                self.logger.warn(None, f"telemetry fold failed: {e!r}",
                                 "TpuBalancer")
        # phase breakdown (bench + ops visibility): assembly is host numpy
        # packing, dispatch is the jit enqueue (transfers + program launch)
        t_dispatched = time.monotonic()
        if wf_aids is not None:
            wf.stamp_many(wf_aids, STAGE_BATCH_ASSEMBLE,
                          int(t_assembled * 1e9))
            wf.stamp_many(wf_aids, STAGE_DEVICE_DISPATCH,
                          int(t_dispatched * 1e9))
        self.metrics.histogram("loadbalancer_tpu_assembly_ms",
                               (t_assembled - t0) * 1e3)
        self.metrics.histogram("loadbalancer_tpu_dispatch_ms",
                               (t_dispatched - t_assembled) * 1e3)
        self.metrics.histogram("loadbalancer_tpu_batch_size", b)
        self.profiler.observe_phase("assembly", (t_assembled - t0) * 1e3)
        self.profiler.observe_phase("dispatch",
                                    (t_dispatched - t_assembled) * 1e3)
        if rec is not None:
            rec.timings["assembly_ms"] = round((t_assembled - t0) * 1e3, 3)
            rec.timings["dispatch_ms"] = round(
                (t_dispatched - t_assembled) * 1e3, 3)
        # pipelined readback: dispatch returns future arrays immediately, so
        # the NEXT batch can dispatch (chained on device) while this batch's
        # results cross the wire on a worker thread — on a tunneled chip the
        # round-trip dwarfs the compute, and serializing them caps
        # throughput at batch/RTT. Dispatch stays event-loop-serialized
        # under the step lock; only readbacks overlap.
        # under donation the NEXT dispatched step consumes self.state's
        # buffers while this step's readback is still crossing the wire —
        # _books_ref hands the worker thread its own device-side copy
        books = self._books_ref()
        task = asyncio.get_event_loop().create_task(
            self._readback_step(batch, b, out, t0, req_np, rec, books,
                                self._next_books_seq(), jseq, q_summary))
        self._readbacks.add(task)
        task.add_done_callback(self._readbacks.discard)

    def _refresh_books_async(self) -> None:
        """Refresh occupancy()'s cached books off a device step that has no
        readback of its own (the idle release/health fold): take a
        donation-safe reference to the books vector NOW, convert it on a
        worker thread. Tracked in _readbacks so close() drains it."""
        books = self._books_ref()
        seq = self._next_books_seq()

        async def _pull():
            self._install_books(await asyncio.to_thread(np.asarray, books),
                                seq)

        task = asyncio.get_event_loop().create_task(_pull())
        self._readbacks.add(task)
        task.add_done_callback(self._readbacks.discard)

    def _read_back(self, out):
        """Device->host conversion seam (runs on the worker thread);
        a separate method so tests can inject readback failures. The packed
        step returns B+1 elements: B decisions + the trailing repair-round
        count (0 for scan/pallas/sharded kernels)."""
        return unpack_step_output(np.asarray(out))

    async def _readback_step(self, batch, b, out, t0, req_np, rec=None,
                             books_free=None, books_seq=0,
                             journal_seq=0, q_summary=None) -> None:
        # the step-duration stamp is taken ON the worker thread so the
        # metric measures device step + readback, not loop re-scheduling
        def _read():
            t_r0 = time.monotonic()
            arrs = self._read_back(out)
            t_r1 = time.monotonic()
            rb_ms = (t_r1 - t_r0) * 1e3
            self.metrics.histogram("loadbalancer_tpu_readback_ms", rb_ms)
            self.profiler.observe_phase("readback", rb_ms)
            # benign cross-thread write: a float EWMA steering a heuristic
            self._rtt_ewma_ms = 0.8 * self._rtt_ewma_ms + 0.2 * rb_ms
            # the EWMA silently flips the eager-vs-batched dispatch policy
            # at RTT_FAST_MS — exported so operators can SEE which regime
            # the balancer is in (not just infer it from latency shifts)
            self.metrics.gauge("loadbalancer_readback_rtt_ms",
                               self._rtt_ewma_ms)
            # POST-step books captured at dispatch: the transfer happens
            # here on the worker thread (tiny — n_pad int32s — and off the
            # event loop); the copy also refreshes occupancy()'s cache so
            # the admin endpoint never needs its own device sync — the
            # install itself happens back on the loop, sequence-guarded
            # (worker threads finish out of order under the pipeline)
            free_np = np.asarray(books_free)
            if rec is not None:
                caps = self._caps_mb
                n_reg = min(len(caps), len(free_np))
                cap_total = int(caps[:n_reg].sum())
                used = cap_total - int(free_np[:n_reg].sum())
                rec.digest["free_slot_hist"] = free_slot_histogram(
                    free_np[:n_reg], MIN_SLOT_MB)
                rec.digest["occupancy"] = (
                    round(used / cap_total, 4) if cap_total else 0.0)
                rec.timings["readback_ms"] = round(rb_ms, 3)
            # quality summary: resolved here on the worker alongside the
            # books it was computed from (the scorer program has had the
            # whole readback round trip to complete)
            if q_summary is not None:
                try:
                    s = np.asarray(q_summary)
                    self.quality.note_summary(s)
                    if rec is not None:
                        rec.digest["quality"] = {
                            "regret_ms": round(float(s[S_REGRET_SUM_MS]), 3),
                            "imbalance_cov": round(
                                float(s[S_IMBALANCE_COV]), 4),
                            "divergent": int(s[S_DIVERGENT]),
                        }
                except Exception as e:  # noqa: BLE001 — a failed score
                    # readout must not fail the batch readback
                    if self.logger:
                        self.logger.warn(
                            None, f"quality summary failed: {e!r}",
                            "TpuBalancer")
            return arrs, t_r1, free_np

        try:
            (chosen_np, forced_np, throttled_np, rounds), t_done, books_np = \
                await asyncio.to_thread(_read)
            self._install_books(books_np, books_seq)
            if journal_seq and self._journal_live():
                # the committed decision vector, keyed to the dispatch-time
                # batch record: replay asserts parity against it, and the
                # throttled bits tell replay which requests the device rate
                # admission rejected (they consumed no capacity)
                enc = (((chosen_np[:b].astype(np.int64) + 1) << 2)
                       | (throttled_np[:b].astype(np.int64) << 1)
                       | forced_np[:b].astype(np.int64))
                self._journal_append({"t": "ack", "for": journal_seq,
                                      "out": [int(v) for v in enc]})
        except Exception as e:  # noqa: BLE001 — publishers must not hang,
            # and their host-side conc slots must not leak. The DISPATCH
            # succeeded (only the host conversion failed), so the device
            # state holds this batch's placements with no publisher left to
            # ever release them. Reverse them ON DEVICE — `out` is still
            # a device array, so no readback is needed to undo exactly what
            # the schedule fold acquired (release_batch is its inverse).
            compensated = True
            try:
                chosen, _, _ = unpack_chosen(out[:-1])
                rel = jnp.stack([
                    jnp.maximum(chosen, 0).astype(jnp.int32),
                    jnp.asarray(req_np[5]), jnp.asarray(req_np[4]),
                    jnp.asarray(req_np[6]),
                    jnp.asarray(req_np[8]) * (chosen >= 0).astype(jnp.int32)])
                self.state = self._release_packed_fn(self.state, rel)
                if journal_seq and self._journal_live():
                    # the dispatch-time batch record stands; journal its
                    # on-device reversal so replay undoes it identically
                    # (np.asarray syncs, but this is already an error path)
                    self._journal_append({"t": "fold",
                                          "rel": encode_array(
                                              np.asarray(rel))})
            except Exception:  # noqa: BLE001 — device genuinely dead: keep
                # the host refcounts PINNED so the slot indices cannot be
                # reassigned to a different action and inherit the phantom
                # concurrency; restart/self-heal owns recovery from here.
                # If the failed release consumed the donated state, rebuild
                # it so the dispatch loop itself survives the outage.
                compensated = False
                self._recover_consumed_state()
            for req, fut, slot_key, _t, aid, *_ in batch:
                if compensated:
                    self._slots.release(slot_key, req[self.R_CONC_SLOT])
                self.waterfall.discard(aid)
                if not fut.done():
                    fut.set_exception(
                        LoadBalancerException(f"device step failed: {e}"))
            self._set_inflight(-1)
            self._capacity_free.set()
            # already surfaced through the futures — re-raising would only
            # produce unretrieved-task noise on the loop
            if self.logger:
                self.logger.error(None, f"device readback failed: {e!r} "
                                  f"(compensated={compensated})",
                                  "TpuBalancer")
            return
        self._set_inflight(-1)
        self._capacity_free.set()
        wf = self.waterfall
        if wf.enabled:
            wf.stamp_many([e[4] for e in batch], STAGE_DEVICE_READBACK,
                          int(t_done * 1e9))
        dt_ms = (t_done - t0) * 1e3
        self.metrics.histogram("loadbalancer_tpu_schedule_batch_ms", dt_ms)
        self.metrics.counter("loadbalancer_tpu_scheduled", b)
        if self.placement_kernel_resolved == "repair" and rounds > 0:
            # how many speculate-commit rounds the batch actually cost —
            # the knob's health signal (repair pays off iff this stays near
            # 1; a fleet-sized spike means pathological intra-batch
            # contention and the scan kernel would serve better). Batches
            # the "auto" hybrid routed to the scan program report 0 and
            # stay out of the histogram.
            self.metrics.histogram("loadbalancer_repair_rounds", rounds)
            if rec is not None:
                rec.digest["repair_rounds"] = rounds
        t_f0 = time.monotonic()
        for (req, fut, slot_key, _t, aid, *_), inv_idx, f, thr in zip(
                batch, chosen_np, forced_np, throttled_np):
            if fut.cancelled():
                # abandoned publisher (client disconnected while awaiting
                # placement): nobody will ever ack this activation, so give
                # back what the schedule fold reserved for it (throttled
                # requests carry chosen == -1: nothing was reserved) —
                # and drop its waterfall vector, which will never finish
                self._abandon_placement(int(inv_idx), req, slot_key)
                wf.discard(aid)
            elif not fut.done():
                fut.set_result((-2 if thr else int(inv_idx), bool(f)))
        t_f1 = time.monotonic()
        fanout_ms = (t_f1 - t_f0) * 1e3
        self.metrics.histogram("loadbalancer_tpu_fanout_ms", fanout_ms)
        prof = self.profiler
        prof.observe_phase("fanout", fanout_ms)
        prof.observe_phase("total", dt_ms,
                           trace_id=(rec.digest.get("trace_id")
                                     if rec is not None else None))
        if rec is not None:
            # tail sampling: with a threshold armed, full per-decision rows
            # are filed only for slow batches (a live capture window takes
            # everything); skipped batches still refresh the gauges
            self._record_batch(rec, batch, chosen_np, forced_np, throttled_np,
                               fanout_ms, file=prof.admit_batch(dt_ms))
            # after the record files: the device span's batch_seq tag is
            # the assigned ring seq (the join key /admin/trace ships)
            self._trace_batch_hooks(rec, batch, forced_np, dt_ms, b)
            if prof.capture_armed:
                row = rec.to_json()
                row["total_ms"] = round(dt_ms, 3)
                prof.capture_step(row)
        elif prof.capture_armed:
            # flight recorder off: the capture window still gets timings
            prof.capture_step({"ts": time.time(), "batch_size": b,
                               "total_ms": round(dt_ms, 3)})

    def _trace_batch_hooks(self, rec, batch, forced_np, dt_ms: float,
                           b: int) -> None:
        """ISSUE 18 trace-observatory riders for one placed micro-batch,
        all from stamps already taken (rec.ts, dt_ms — no new clock
        reads): the per-batch `device_dispatch` span under the digest's
        trace id (the flight-recorder link the assembled tree joins on),
        the `divergent` mark when the shadow counterfactual disagreed,
        the `exemplar` force-keep (the phase histogram just pinned this
        trace id onto a bucket line — every rendered exemplar must
        resolve), and the `forced` mark per force-placed row."""
        from ...utils.tracestore import GLOBAL_TRACE_STORE, synthetic_span
        store = GLOBAL_TRACE_STORE
        if not store.active:
            return
        tid = rec.digest.get("trace_id")
        if tid:
            store.emit(synthetic_span(
                tid, "device_dispatch", rec.ts, rec.ts + dt_ms / 1e3,
                tags={"proc": f"controller{self.controller.name}",
                      "batch_seq": str(rec.seq),
                      "kernel": str(rec.digest.get("kernel")),
                      "batch_size": str(b)}))
            if self.profiler.enabled:
                store.force(tid, "exemplar")
            q = rec.digest.get("quality")
            if q and q.get("divergent"):
                store.mark(tid, "divergent")
        for e, f in zip(batch, forced_np):
            if f and e[6]:
                store.mark(e[6], "forced")

    def _record_batch(self, rec, batch, chosen_np, forced_np, throttled_np,
                      fanout_ms: float, file: bool = True) -> None:
        """Finish and file the flight-recorder record for one micro-batch,
        and refresh the introspection gauges. `file=False` (tail-sampled
        fast batch) refreshes the gauges without ringing the record."""
        rec.timings["fanout_ms"] = round(fanout_ms, 3)
        fr = self.flight_recorder
        if file:
            n_reg = len(self._registry)
            decisions = rec.decisions
            for (req, fut, slot_key, t_enq, aid, act, _tid, *_), ci, f, thr \
                    in zip(batch, chosen_np, forced_np, throttled_np):
                ci = int(ci)
                name = (self._registry[ci].as_string
                        if 0 <= ci < n_reg else None)
                decisions.append((aid, act, ci, name, bool(f), bool(thr),
                                  req[self.R_NEED_MB]))
            fr.record(rec)
        m = self.metrics
        d = rec.digest
        m.gauge("loadbalancer_placement_queue_depth", d["queue_depth"])
        m.gauge("loadbalancer_placement_batch_age_ms", d["oldest_age_ms"])
        m.gauge("loadbalancer_healthy_invokers", d["healthy_invokers"])
        m.gauge("loadbalancer_fleet_occupancy_ratio", d.get("occupancy", 0.0))
        m.gauge("loadbalancer_flight_recorder_dropped", fr.dropped)


class TpuBalancerProvider:
    @staticmethod
    def instance(**kwargs) -> TpuBalancer:
        return TpuBalancer(**kwargs)

"""ArtifactStore: the persistence abstraction.

Rebuild of common/scala/.../core/database/ArtifactStore.scala:41-150 — an
async document CRUD + view-query + attachment interface. Concrete stores:
memory (tests/standalone, ref MemoryArtifactStore) and sqlite (durable
single-node, the CouchDB-equivalent here); the SPI seam
(`ArtifactStoreProvider`) admits remote/document-DB impls unchanged.

View queries reproduce the reference design-doc views the controller needs
(`whisks.v2.1.0/<collection>`, `activations/byDate`): list entities of a
collection in a namespace, newest first, with skip/limit/since/upto.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class ArtifactStoreException(Exception):
    pass


class NoDocumentException(ArtifactStoreException):
    pass


class DocumentConflict(ArtifactStoreException):
    pass


class StaleParameter(ArtifactStoreException):
    pass


class ArtifactStore:
    """Async document store. Documents are JSON dicts with `_id` and `_rev`
    managed by the store; callers hand in entity JSON + doc id."""

    # optional delegation of attachment bytes to a separate AttachmentStore
    # (ref: CouchDbRestStore takes an attachmentStore; reference.conf wires
    # S3AttachmentStoreProvider in that slot)
    attachment_store = None

    def with_attachment_store(self, attachment_store) -> "ArtifactStore":
        self.attachment_store = attachment_store
        return self

    # -- CRUD --------------------------------------------------------------
    async def put(self, doc_id: str, doc: Dict[str, Any],
                  rev: Optional[str] = None) -> str:
        """Insert or update. `rev` must match the stored revision for
        updates (None means insert-new). Returns the new revision.
        Raises DocumentConflict on mismatch (ref ArtifactStore.put)."""
        raise NotImplementedError

    async def get(self, doc_id: str) -> Dict[str, Any]:
        """Fetch a document (with _id/_rev); NoDocumentException if absent."""
        raise NotImplementedError

    async def delete(self, doc_id: str, rev: Optional[str] = None) -> bool:
        """Delete; DocumentConflict if rev given and stale; NoDocumentException
        if absent."""
        raise NotImplementedError

    # -- views -------------------------------------------------------------
    async def query(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None,
                    skip: int = 0, limit: int = 0,
                    descending: bool = True) -> List[Dict[str, Any]]:
        """List documents of `collection` (actions/triggers/rules/packages/
        activations/subjects), filtered by namespace (exact root match) and
        optional entity name, ordered by `updated`."""
        raise NotImplementedError

    async def count(self, collection: str, namespace: Optional[str] = None,
                    name: Optional[str] = None,
                    since: Optional[float] = None, upto: Optional[float] = None
                    ) -> int:
        raise NotImplementedError

    # -- attachments (ref AttachmentStore SPI) -----------------------------
    async def attach(self, doc_id: str, name: str, content_type: str,
                     data: bytes) -> None:
        raise NotImplementedError

    async def read_attachment(self, doc_id: str, name: str) -> Tuple[str, bytes]:
        raise NotImplementedError

    async def delete_attachments(self, doc_id: str,
                                 except_name: Optional[str] = None) -> None:
        """Delete a document's attachments; `except_name` keeps the current
        one (update-time GC of superseded per-put attachment names)."""
        raise NotImplementedError

    async def close(self) -> None:
        if self.attachment_store is not None:
            await self.attachment_store.close()


def match_query(doc: Dict[str, Any], collection: str, namespace: Optional[str],
                name: Optional[str], since: Optional[float],
                upto: Optional[float]) -> bool:
    """Shared view predicate for stores that filter in process."""
    if doc.get("entityType") != collection:
        return False
    if namespace is not None:
        ns = str(doc.get("namespace", ""))
        if ns != namespace and not ns.startswith(namespace + "/"):
            return False
    if name is not None and doc.get("name") != name:
        return False
    ts = doc.get("start", doc.get("updated", 0))
    if since is not None and ts < since:
        return False
    if upto is not None and ts > upto:
        return False
    return True


def sort_key(doc: Dict[str, Any]) -> float:
    return doc.get("start", doc.get("updated", 0)) or 0

"""Distributed tracing: spans correlated by transaction id.

Rebuild of common/scala/.../common/tracing/OpenTracingProvider.scala:43-160 —
a per-transid stack of spans; the active span's context serializes into
`ActivationMessage.trace_context` (W3C traceparent style) and is restored on
the invoker side, so traces survive the bus hop (Message.scala:61,
InvokerReactive.scala:224). Finished spans go to a pluggable reporter:
in-memory buffer by default, `ZipkinReporter` (Zipkin v2 JSON over HTTP,
the reference's reporting backend, OpenTracingProvider.scala:43-160 +
application.conf:461-476) when CONFIG_whisk_tracing_zipkinUrl is set —
see `maybe_enable_zipkin`. Span caches expire so abandoned transactions
don't leak.
"""
from __future__ import annotations

import asyncio
import json
import secrets
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float
    end: Optional[float] = None
    tags: Dict[str, str] = field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return ((self.end or time.time()) - self.start) * 1e3

    def to_json(self) -> dict:
        return {"traceId": self.trace_id, "id": self.span_id,
                "parentId": self.parent_id, "name": self.name,
                "timestamp": int(self.start * 1e6),
                "duration": int(self.duration_ms * 1e3), "tags": self.tags}


class Reporter:
    def report(self, span: Span) -> None:
        raise NotImplementedError


class BufferReporter(Reporter):
    """In-memory span sink, ring-shaped: a full buffer evicts the OLDEST
    span so the NEWEST always survive — on a long soak the buffer tracks
    live traffic instead of fossilizing at startup spans. Evictions count
    as `dropped_spans` (like ZipkinReporter), so a saturated buffer stays
    visible to tests/operators instead of silently lossy."""

    def __init__(self, max_spans: int = 10_000):
        from collections import deque
        self.spans = deque(maxlen=max(1, max_spans))
        self.max_spans = max_spans
        self.sent_spans = 0
        self.dropped_spans = 0

    def report(self, span: Span) -> None:
        if len(self.spans) >= self.max_spans:
            self.dropped_spans += 1
        self.spans.append(span)
        self.sent_spans += 1


class ZipkinReporter(Reporter):
    """Zipkin v2 JSON-over-HTTP reporter (POST {url}/api/v2/spans).

    Spans buffer host-side and flush asynchronously — at `batch_size`, on
    the `flush_interval` tick, or at close(). A dead collector costs one
    failed POST per flush window and drops those spans; tracing must never
    take the data plane down with it.
    """

    def __init__(self, url: str, service_name: str = "openwhisk-tpu",
                 batch_size: int = 100, flush_interval: float = 1.0,
                 logger=None):
        self.url = url.rstrip("/") + "/api/v2/spans"
        self.service_name = service_name
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.logger = logger
        self._pending: List[Span] = []
        self._flush_task: Optional[asyncio.Task] = None
        self._flushing = False  # True only while a POST is in flight
        self._session = None  # lazily-created, kept for connection reuse
        self.sent_spans = 0
        self.dropped_spans = 0

    def report(self, span: Span) -> None:
        self._pending.append(span)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (sync tooling): spans flush on explicit close()
        full = len(self._pending) >= self.batch_size
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = loop.create_task(
                self._flush_later(0.0 if full else self.flush_interval))
        elif full and not self._flushing:
            # a flush is scheduled but still sleeping out its interval —
            # the batch is full NOW, so replace it with an immediate one.
            # A flush that is already mid-POST is never preempted: its
            # backlog drains on the next flush once it completes.
            self._flush_task.cancel()
            self._flush_task = loop.create_task(self._flush_later(0.0))

    async def _flush_later(self, delay: float) -> None:
        if delay:
            await asyncio.sleep(delay)
        while True:
            self._flushing = True
            try:
                await self.flush()
            finally:
                self._flushing = False
            # a full batch accumulated during the POST: drain it now rather
            # than waiting for the next report() to schedule a task
            if len(self._pending) < self.batch_size:
                return

    def _encode(self, spans: List[Span]) -> bytes:
        out = []
        for s in spans:
            doc = s.to_json()
            doc["localEndpoint"] = {"serviceName": self.service_name}
            doc["tags"] = {k: str(v) for k, v in doc["tags"].items()}
            if doc["parentId"] is None:
                del doc["parentId"]
            out.append(doc)
        return json.dumps(out).encode()

    async def flush(self) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        try:
            import aiohttp

            if self._session is None or self._session.closed:
                self._session = aiohttp.ClientSession()
            async with self._session.post(
                    self.url, data=self._encode(batch),
                    headers={"Content-Type": "application/json"},
                    timeout=aiohttp.ClientTimeout(total=5)) as resp:
                if resp.status >= 400:
                    raise RuntimeError(f"collector returned {resp.status}")
            self.sent_spans += len(batch)
        except asyncio.CancelledError:
            # cancelled mid-POST (full-batch preemption or close()): the
            # popped batch goes back so the next flush re-sends it instead
            # of losing it uncounted
            self._pending = batch + self._pending
            raise
        except Exception as e:  # noqa: BLE001 — tracing is best-effort
            self.dropped_spans += len(batch)
            if self.logger:
                self.logger.warn(None, f"zipkin flush failed, dropped "
                                       f"{len(batch)} spans: {e}")

    async def close(self) -> None:
        if self._flush_task and not self._flush_task.done():
            self._flush_task.cancel()
            try:
                await self._flush_task
            except asyncio.CancelledError:
                pass
        await self.flush()
        if self._session is not None and not self._session.closed:
            await self._session.close()


@dataclass
class TracingSettings:
    zipkin_url: Optional[str] = None
    batch_size: int = 100
    flush_interval: float = 1.0


def maybe_enable_zipkin(service_name: str,
                        tracer: Optional["Tracer"] = None) -> Optional[ZipkinReporter]:
    """Swap the Zipkin reporter in when CONFIG_whisk_tracing_zipkinUrl is
    exported (the reference gates identically on a configured zipkin url,
    application.conf:461-476). Returns the reporter, or None when unset."""
    from .config import load_config

    cfg = load_config(TracingSettings, env_path="tracing")
    if not cfg.zipkin_url:
        return None
    reporter = ZipkinReporter(cfg.zipkin_url, service_name=service_name,
                              batch_size=cfg.batch_size,
                              flush_interval=cfg.flush_interval)
    t = tracer or GLOBAL_TRACER
    current = t.reporter
    if hasattr(current, "swap_inner"):
        # a trace-store tee (utils/tracestore.py) wraps the real sink:
        # swap the sink INSIDE it so the tail-sampling tee survives
        current.swap_inner(reporter)
    else:
        t.reporter = reporter
    return reporter


class Tracer:
    """Span lifecycle keyed by transid (ref OpenTracer)."""

    def __init__(self, reporter: Optional[Reporter] = None,
                 expiry_seconds: float = 3600.0):
        self.reporter = reporter or BufferReporter()
        self.expiry = expiry_seconds
        #: opportunistic-sweep cadence: a fraction of the expiry so small
        #: populations of abandoned stacks (below the size trigger) still
        #: age out within ~1.25x the expiry window
        self._sweep_interval = max(0.05, expiry_seconds / 4.0)
        self._last_sweep = time.monotonic()
        self._stacks: Dict[str, List[Span]] = {}
        self._touched: Dict[str, float] = {}
        #: finish_span calls that found nothing to finish (no stack for the
        #: transid, or a span that was already finished/expired): each one
        #: is a span silently lost to the trace — counted so a miswired
        #: caller shows up in the tracing gauges instead of as a mystery
        #: hole in the waterfall
        self.orphan_finishes = 0

    def start_span(self, name: str, transid) -> Span:
        stack = self._stacks.setdefault(transid.id, [])
        parent = stack[-1] if stack else None
        span = Span(
            trace_id=parent.trace_id if parent else secrets.token_hex(16),
            span_id=secrets.token_hex(8),
            parent_id=parent.span_id if parent else None,
            name=name, start=time.time())
        stack.append(span)
        now = time.monotonic()
        self._touched[transid.id] = now
        self._expire(now)
        return span

    def finish_span(self, transid, tags: Optional[Dict[str, str]] = None,
                    span: Optional[Span] = None) -> Optional[Span]:
        """Finish `span` (or the top of the stack when omitted). Passing the
        span start_span returned makes concurrent invokes sharing one transid
        safe: each finishes its OWN span even when interleaving reordered the
        stack."""
        stack = self._stacks.get(transid.id)
        if not stack:
            self.orphan_finishes += 1
            return None
        if span is not None:
            if span not in stack:
                self.orphan_finishes += 1
                return None
            stack.remove(span)
        else:
            span = stack.pop()
        span.end = time.time()
        if tags:
            span.tags.update(tags)
        if not stack:
            self._stacks.pop(transid.id, None)
            self._touched.pop(transid.id, None)
        self.reporter.report(span)
        return span

    # -- stack-free spans (invoker side) -----------------------------------
    def start_remote_child(self, name: str,
                           context: Optional[Dict[str, str]]) -> Span:
        """A span parented directly from a serialized traceparent, touching
        no per-transid stack — safe when many activations share one transid
        (e.g. all rules of one trigger fire) and finish out of order."""
        parts = (context or {}).get("traceparent", "").split("-")
        if len(parts) == 4:
            trace_id, parent_id = parts[1], parts[2]
        else:
            trace_id, parent_id = secrets.token_hex(16), None
        return Span(trace_id=trace_id, span_id=secrets.token_hex(8),
                    parent_id=parent_id, name=name, start=time.time())

    def finish(self, span: Span, tags: Optional[Dict[str, str]] = None) -> None:
        """Finish and report a stack-free span."""
        span.end = time.time()
        if tags:
            span.tags.update(tags)
        self.reporter.report(span)

    def error(self, transid, message: str) -> None:
        stack = self._stacks.get(transid.id)
        if stack:
            stack[-1].tags["error"] = message

    # -- context propagation (traceparent style) ---------------------------
    def get_trace_context(self, transid) -> Optional[Dict[str, str]]:
        stack = self._stacks.get(transid.id)
        if not stack:
            return None
        s = stack[-1]
        return {"traceparent": f"00-{s.trace_id}-{s.span_id}-01"}

    def set_trace_context(self, transid, context: Optional[Dict[str, str]]) -> None:
        """Restore a remote parent so child spans link across the bus."""
        if not context:
            return
        tp = context.get("traceparent", "")
        parts = tp.split("-")
        if len(parts) != 4:
            return
        remote = Span(trace_id=parts[1], span_id=parts[2], parent_id=None,
                      name="remote-parent", start=time.time())
        self._stacks.setdefault(transid.id, []).append(remote)
        self._touched[transid.id] = time.monotonic()

    def clear(self, transid) -> None:
        """Drop any remaining spans for a transaction WITHOUT reporting them
        (e.g. the invoker's restored remote parent after the work is done)."""
        self._stacks.pop(transid.id, None)
        self._touched.pop(transid.id, None)

    def _expire(self, now: Optional[float] = None) -> None:
        """Drop abandoned transaction stacks. Two triggers: the size
        threshold (a burst of live transactions) and an opportunistic
        time-based sweep — without it, fewer than 1000 abandoned stacks
        would linger FOREVER. Amortized: the sweep reuses the caller's
        monotonic read and runs at most once per `_sweep_interval`, so
        the per-span cost below both triggers is two comparisons."""
        if now is None:
            now = time.monotonic()
        if (len(self._touched) < 1000
                and now - self._last_sweep < self._sweep_interval):
            return
        self._last_sweep = now
        cutoff = now - self.expiry
        for tid in [t for t, at in self._touched.items() if at < cutoff]:
            self._stacks.pop(tid, None)
            self._touched.pop(tid, None)


def trace_id_of(context: Optional[Dict[str, str]]) -> Optional[str]:
    """The trace id carried by a serialized W3C traceparent context, or
    None when the context is absent or malformed (exemplar plumbing:
    histogram bucket lines link back to traces by this id)."""
    if not context:
        return None
    parts = context.get("traceparent", "").split("-")
    return parts[1] if len(parts) == 4 and parts[1] else None


def export_tracing_gauges(metrics, tracer: Optional["Tracer"] = None) -> None:
    """Refresh the tracing health gauges on a MetricEmitter (ridden by the
    balancers' supervision tick): span send/drop counts from the live
    reporter, open transaction stacks, and orphan finish_span calls —
    the silent-return path that used to be invisible."""
    t = tracer if tracer is not None else GLOBAL_TRACER
    metrics.gauge("tracing_orphan_finishes", t.orphan_finishes)
    metrics.gauge("tracing_active_transactions", len(t._stacks))
    rep = t.reporter
    metrics.gauge("tracing_spans_sent", getattr(rep, "sent_spans", 0))
    metrics.gauge("tracing_spans_dropped", getattr(rep, "dropped_spans", 0))


# process-wide default tracer (ref WhiskTracerProvider)
GLOBAL_TRACER = Tracer()

"""Pallas placement kernel: exact parity with the XLA kernel.

Runs in interpret mode on the CPU backend (the kernel itself is TPU-shaped;
interpret mode executes the same program semantics). On-device parity and
the timing comparison are exercised by tests/performance/placement_sweep.py
--pallas on real hardware.
"""
import numpy as np
import pytest

import jax.numpy as jnp

pytestmark = pytest.mark.pallas

from __graft_entry__ import _example_batch
from openwhisk_tpu.ops.placement import init_state, schedule_batch, set_health
from openwhisk_tpu.ops.placement_pallas import (fits_vmem,
                                                schedule_batch_pallas,
                                                to_transposed)


@pytest.mark.parametrize("n,batch,seed", [(64, 32, 1), (256, 96, 2),
                                          (128, 64, 3)])
def test_pallas_matches_xla(n, batch, seed):
    state = init_state(n, [1024] * n, action_slots=64)
    req = _example_batch(n, batch, seed=seed)
    s1, c1, f1 = schedule_batch(state, req)
    s2, c2, f2 = schedule_batch_pallas(to_transposed(state), req,
                                       interpret=True)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))
    np.testing.assert_array_equal(np.asarray(s1.free_mb),
                                  np.asarray(s2.free_mb))
    np.testing.assert_array_equal(np.asarray(s1.conc_free),
                                  np.asarray(s2.conc_free).T)


def test_pallas_respects_health_mask_and_overload():
    n = 16
    state = init_state(n, [256] * n, action_slots=8)
    state = set_health(state, list(range(8)), [False] * 8)
    req = _example_batch(n, 48, seed=9)  # demand far exceeds capacity
    s1, c1, f1 = schedule_batch(state, req)
    s2, c2, f2 = schedule_batch_pallas(to_transposed(state), req,
                                       interpret=True)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))
    # unhealthy invokers never chosen, even forced
    assert not set(np.asarray(c2)[np.asarray(c2) >= 0]) & set(range(8))
    assert np.asarray(f2).any()  # overload forced placements happened


def test_pallas_out_of_range_slots_match_xla_scatter_semantics():
    """OOB slot ids: reads clamp to the last column, writes are dropped —
    exactly XLA's dynamic_index_in_dim + scatter behavior. The adversarial
    case is max_conc>1 with an OOB slot (a clamping write would mint phantom
    concurrency permits in column A-1 that a later request could consume)."""
    from openwhisk_tpu.ops.placement import RequestBatch
    n, a = 32, 4
    state = init_state(n, [512] * n, action_slots=a)

    def mk(slots, max_concs):
        b = len(slots)
        z = jnp.zeros((b,), jnp.int32)
        return RequestBatch(
            offset=z, size=jnp.full((b,), n, jnp.int32), home=z,
            step_inv=jnp.ones((b,), jnp.int32),
            need_mb=jnp.full((b,), 128, jnp.int32),
            conc_slot=jnp.asarray(slots, jnp.int32),
            max_conc=jnp.asarray(max_concs, jnp.int32),
            rand=z, valid=jnp.ones((b,), bool))

    # OOB slot 9 with max_conc=4, then a legit request on slot 3 (the
    # clamped column) with max_conc=4
    req = mk([9, 3, 3], [4, 4, 4])
    s1, c1, f1 = schedule_batch(state, req)
    s2, c2, f2 = schedule_batch_pallas(to_transposed(state), req,
                                       interpret=True)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(s1.free_mb),
                                  np.asarray(s2.free_mb))
    np.testing.assert_array_equal(np.asarray(s1.conc_free),
                                  np.asarray(s2.conc_free).T)


def test_fits_vmem_budget():
    assert fits_vmem(1024, 256)
    assert fits_vmem(4096, 256)
    assert not fits_vmem(65536, 256)

"""Host hot-loop observatory: where the CONTROLLER'S OWN wall-time goes.

Every profiling plane so far watches the device (PR 3's KernelProfiler) or
the stage boundaries (PR 6's waterfall). ROADMAP item 1 says the next order
of magnitude is blocked by per-activation *Python* — dict-shaped message
construction, JSON serde per hop, asyncio task churn, single-threaded
fan-in — none of which those planes can see. This module is the host-side
equivalent: a per-process `HostObservatory` with four always-on planes plus
a bounded capture plane, all within a <5% overhead budget (the
`host_profiling_overhead` bench rider gates it):

  1. EVENT-LOOP LAG — a self-rescheduling `loop.call_at` probe measures
     each tick against its SCHEDULED deadline (Tene's coordinated-omission
     rule, PAPERS.md: lag from schedule, never from the previous tick; a
     stall backfills one sample per missed tick) into log2-us histograms,
     plus a slow-callback interposer: a task-factory wrapper times every
     coroutine resumption and files steps over `stallThresholdMs` into a
     SeqRingBuffer of worst offenders, named by coroutine + task.
  2. GC PAUSES — `gc.callbacks` accounting: per-generation pause
     histograms, collected/uncollectable counters, and a
     pause-overlapping-a-dispatch counter (the balancer brackets its
     device dispatch with begin_dispatch/end_dispatch) so a GC pause that
     lands inside `device_dispatch` is attributed, not mysterious.
  3. TASK CHURN + SERDE COST — tasks created/finished/active gauges from
     the same task factory, and byte+wall-time counters per
     serialize/deserialize hop (messaging/connector.py's
     encode_message/decode_message helpers feed them, labeled
     {hop,direction}) so "JSON is X% of the loop at 1k/s" is a measured
     number.
  4. SAMPLING PROFILER — a background daemon thread over
     `sys._current_frames()` (no setitimer: it must coexist with the
     journal writer and prewarm drainer threads, so it samples ONLY the
     registered event-loop thread) folding stacks into a self-time census
     (ranked top-N) and a collapsed-stack (flamegraph-format) dump;
     `capture(seconds)` arms a bounded full-rate window.

Exposition (register_renderer on the installing process's MetricEmitter):
`openwhisk_host_event_loop_lag_seconds`,
`openwhisk_host_gc_pause_seconds{generation}`, `openwhisk_host_tasks_*`,
`openwhisk_host_serde_{seconds,bytes}_total{hop,direction}`. Read side:
auth-gated `GET /admin/profile/host` (snapshot) and
`POST /admin/profile/host/capture` (bounded capture window), following the
PR 3 capture-plane pattern.

Off switch: `CONFIG_whisk_hostProfiling_enabled=false` is a TRUE no-op —
install() refuses (no task factory swap, no gc callbacks, no sampler
thread) and the serde helpers fall straight through without touching a
clock (tracemalloc-asserted in tests/test_hostprof.py, like PR 2/3).

Design notes: one process-global instance (GLOBAL_HOST_OBSERVATORY, the
GLOBAL_WATERFALL pattern) because the planes span layers that never share
a balancer reference; hot-path folds are single GIL-atomic increments
under one uncontended lock; the probe/factory/sampler only exist after an
explicit install() (Controller.start, the invoker main, or a bench
harness), so library use of this package never grows background machinery.
"""
from __future__ import annotations

import asyncio
import gc
import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .config import load_config
from .eventlog import identity
from .ring_buffer import SeqRingBuffer
from .waterfall import bucket_bounds_ms, bucket_of_us

#: full-rate sampling during an armed capture window (the always-on rate
#: is `sampleHz`); bounded by captureLimitS so a capture can never become
#: a standing tax
CAPTURE_HZ = 241.0
#: distinct leaf frames / collapsed stacks kept before folding into the
#: overflow key (bounds sampler memory on pathological stack diversity)
MAX_CENSUS_KEYS = 1024
MAX_COLLAPSED_KEYS = 4096
MAX_STACK_DEPTH = 48
_OVERFLOW_KEY = "<overflow>"


@dataclass(frozen=True)
class HostProfilingConfig:
    """`CONFIG_whisk_hostProfiling_*` env overrides."""
    enabled: bool = True
    #: always-on sampler rate (Hz); 0 disables the sampler plane only.
    #: Deliberately an off-round prime so it cannot phase-lock with 1 Hz
    #: supervision ticks or 10 ms batching windows.
    sample_hz: float = 23.0
    #: event-loop lag probe tick (ms)
    lag_probe_ms: float = 20.0
    #: a coroutine resumption at least this long is filed as a stall
    stall_threshold_ms: float = 50.0
    #: hard cap on one capture window's length (seconds)
    capture_limit_s: float = 10.0
    #: worst-offender stall ring size
    stall_ring: int = 64
    #: log2-us histogram buckets (shared bounds with the waterfall)
    buckets: int = 30


@dataclass(frozen=True)
class GcTuningConfig:
    """`CONFIG_whisk_host_gc_*` env overrides for `tune_gc()`.

    Rationale (measured by this module's GC plane, ISSUE 12): CPython's
    default thresholds (700, 10, 10) run a FULL-heap gen-2 collection
    every ~70k surviving allocations. A loaded controller allocates
    hundreds of objects per activation over a permanent heap of ~1M
    objects (jax's module graph alone), so gen-2 fires mid-burst and
    stalls the event loop for 100-250 ms — the observatory measured GC at
    ~12% of wall with 262 ms p99 gen-2 pauses at 2k activations/s.
    `tune_gc()` freezes the post-boot permanent heap out of the collector
    (gc.freeze) and raises the thresholds so cycles still collect but
    full scans amortize over far more allocations. Default OFF for the
    product (`enabled=false`): operators opt in per deployment; the
    open-loop harness (tools/loadgen.py) opts in for its own process and
    says so in the generator block."""
    enabled: bool = False
    gen0: int = 50000
    gen1: int = 50
    gen2: int = 100
    freeze: bool = True

    @classmethod
    def from_env(cls) -> "GcTuningConfig":
        return load_config(cls, env_path="host.gc")


def tune_gc(config: Optional[GcTuningConfig] = None,
            force: bool = False) -> Optional[dict]:
    """Apply the GC tuning above (see GcTuningConfig). Returns what was
    done ({frozen, thresholds}) or None when disabled. `force=True`
    applies regardless of the enabled flag (the harness's explicit
    opt-in). One full collection runs first so freeze() pins a clean
    heap."""
    cfg = config if config is not None else GcTuningConfig.from_env()
    if not (cfg.enabled or force):
        return None
    gc.collect()
    frozen = 0
    if cfg.freeze:
        gc.freeze()
        frozen = gc.get_freeze_count()
    gc.set_threshold(int(cfg.gen0), int(cfg.gen1), int(cfg.gen2))
    return {"frozen": frozen,
            "thresholds": [int(cfg.gen0), int(cfg.gen1), int(cfg.gen2)]}


class _TimedCoro:
    """Coroutine-protocol wrapper timing every resumption (one event-loop
    callback turn). The fast path is two perf_counter_ns calls around the
    inner send/throw; only a step over the stall threshold takes the slow
    path into the observatory. Registered as a Coroutine ABC subclass (see
    module bottom) so asyncio.Task accepts it."""

    __slots__ = ("_coro", "_obs", "_name", "__name__", "__qualname__")

    def __init__(self, coro, obs: "HostObservatory", name: str):
        self._coro = coro
        self._obs = obs
        self._name = name
        # asyncio's task repr reads these off the coroutine object
        self.__name__ = getattr(coro, "__name__", name)
        self.__qualname__ = name

    def send(self, value):
        t0 = time.perf_counter_ns()
        try:
            return self._coro.send(value)
        finally:
            dt = time.perf_counter_ns() - t0
            if dt >= self._obs._stall_ns:
                self._obs._note_stall(self._name, dt)

    def throw(self, *args):
        t0 = time.perf_counter_ns()
        try:
            return self._coro.throw(*args)
        finally:
            dt = time.perf_counter_ns() - t0
            if dt >= self._obs._stall_ns:
                self._obs._note_stall(self._name, dt)

    def close(self):
        return self._coro.close()

    def __await__(self):
        return self

    def __iter__(self):
        return self

    def __next__(self):
        return self.send(None)


# Task.__init__ requires collections.abc.Coroutine membership; registering
# (instead of inheriting) keeps _TimedCoro a __slots__ class with no ABC
# machinery on the per-step hot path.
import collections.abc as _abc  # noqa: E402

_abc.Coroutine.register(_TimedCoro)


class HostObservatory:
    """The per-process host hot-loop observatory (see module doc)."""

    def __init__(self, config: Optional[HostProfilingConfig] = None):
        self.config = config or HostProfilingConfig()
        self.enabled = self.config.enabled
        self.n_buckets = max(4, int(self.config.buckets))
        self._stall_ns = int(max(0.0, self.config.stall_threshold_ms) * 1e6)
        self._lock = threading.Lock()
        self._installed = False
        #: wall-time epoch behind the gc/serde share percentages —
        #: stamped at construction (serde accounting runs enabled-only,
        #: no install needed), re-stamped by install() and reset()
        self._epoch_mono = time.monotonic()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._metrics = None
        self._prev_factory = None
        self._factory_ref = None
        self._probe_handle = None
        self._probe_next = 0.0
        self._target_tid: Optional[int] = None
        self._sampler: Optional[threading.Thread] = None
        self._sampler_stop: Optional[threading.Event] = None
        self._capture: Optional[dict] = None
        self._gc_t0_ns = 0
        self._dispatch_depth = 0
        self._reset_aggregates()

    @classmethod
    def from_config(cls) -> "HostObservatory":
        return cls(load_config(HostProfilingConfig, env_path="host_profiling"))

    def _reset_aggregates(self) -> None:
        b = self.n_buckets
        # event-loop lag (log2-us, like the waterfall's stage histograms —
        # plain int lists: finish-side folds are single slot increments)
        self._lag_hist = [0] * b
        self._lag_sum_us = 0
        self._lag_max_us = 0
        self._lag_ticks = 0
        # stalls (slow coroutine resumptions)
        self._stalls: SeqRingBuffer[dict] = SeqRingBuffer(
            max(8, int(self.config.stall_ring)))
        self._stall_count = 0
        self._stall_sum_us = 0
        # gc pauses per generation
        self._gc_hist = [[0] * b for _ in range(3)]
        self._gc_sum_us = [0, 0, 0]
        self._gc_count = [0, 0, 0]
        self._gc_collected = 0
        self._gc_uncollectable = 0
        self._gc_in_dispatch = 0
        # task churn
        self._tasks_created = 0
        self._tasks_finished = 0
        # serde: (hop, direction) -> [count, bytes, wall_ns]
        self._serde: Dict[Tuple[str, str], list] = {}
        # sampler census
        self._census: Dict[str, int] = {}
        self._collapsed: Dict[str, int] = {}
        self._samples = 0

    def reset(self) -> None:
        """Drop all accumulated state (bench riders isolate windows). The
        wall-time epoch behind the gc/serde share percentages re-stamps
        too, so a post-warmup reset yields shares over the measured window
        rather than over boot-to-now."""
        with self._lock:
            # tasks created before the reset still deliver their done-
            # callbacks afterwards: carry the in-flight count forward so
            # active (= created - finished) can never go negative
            inflight = self._tasks_created - self._tasks_finished
            self._reset_aggregates()
            self._tasks_created = max(0, inflight)
        self._epoch_mono = time.monotonic()

    # -- install / uninstall ----------------------------------------------
    def install(self, loop: Optional[asyncio.AbstractEventLoop] = None,
                metrics=None) -> bool:
        """Arm all four planes on the CURRENT event-loop thread. Returns
        True when this call did the install (the caller then owns the
        matching uninstall); False when disabled or already installed.
        With `metrics`, also registers the exposition renderer there."""
        if not self.enabled or self._installed:
            return False
        loop = loop if loop is not None else asyncio.get_event_loop()
        self._loop = loop
        self._installed = True
        self._epoch_mono = time.monotonic()
        self._target_tid = threading.get_ident()
        # slow-callback interposer + task churn: one factory serves both.
        # The bound method is pinned once — uninstall's identity check
        # must see the SAME object set_task_factory stored.
        self._prev_factory = loop.get_task_factory()
        self._factory_ref = self._task_factory
        loop.set_task_factory(self._factory_ref)
        # lag probe: the first deadline is fixed NOW; every later deadline
        # derives from it (schedule, not previous tick)
        interval = max(1.0, float(self.config.lag_probe_ms)) / 1e3
        self._probe_next = loop.time() + interval
        self._probe_handle = loop.call_at(self._probe_next, self._probe_tick)
        gc.callbacks.append(self._gc_cb)
        if self.config.sample_hz > 0 and hasattr(sys, "_current_frames"):
            self._sampler_stop = threading.Event()
            self._sampler = threading.Thread(
                target=self._sample_loop, name="hostprof-sampler",
                daemon=True)
            self._sampler.start()
        if metrics is not None:
            metrics.register_renderer(self.prometheus_text)
            self._metrics = metrics
        return True

    def uninstall(self) -> None:
        """Tear every plane back down (idempotent). Restores the previous
        task factory only if ours is still the live one."""
        if not self._installed:
            return
        self._installed = False
        if self._probe_handle is not None:
            self._probe_handle.cancel()
            self._probe_handle = None
        loop = self._loop
        if loop is not None and \
                loop.get_task_factory() is getattr(self, "_factory_ref",
                                                   None):
            loop.set_task_factory(self._prev_factory)
        self._prev_factory = None
        self._factory_ref = None
        try:
            gc.callbacks.remove(self._gc_cb)
        except ValueError:
            pass
        if self._sampler_stop is not None:
            self._sampler_stop.set()
        if self._sampler is not None:
            self._sampler.join(timeout=2.0)
        self._sampler = None
        self._sampler_stop = None
        self._capture = None
        if self._metrics is not None:
            self._metrics.unregister_renderer(self.prometheus_text)
            self._metrics = None
        self._loop = None

    @property
    def installed(self) -> bool:
        return self._installed

    @property
    def serde_active(self) -> bool:
        """Whether the serde helpers should pay for a clock read. Enabled
        is enough (no install needed): serde accounting is pure counters,
        useful even when no loop-side plane is armed."""
        return self.enabled

    @property
    def sampler_running(self) -> bool:
        return self._sampler is not None and self._sampler.is_alive()

    # -- plane 1: event-loop lag -------------------------------------------
    def _probe_tick(self) -> None:
        if not self._installed or self._loop is None:
            return
        loop = self._loop
        now = loop.time()
        interval = max(1.0, float(self.config.lag_probe_ms)) / 1e3
        sched = self._probe_next
        nb = self.n_buckets
        with self._lock:
            # coordinated omission: when a stall swallowed k ticks, each
            # missed tick records its own lag from its own deadline —
            # one probe firing late must not collapse k samples into one
            while True:
                lag_us = max(0, int((now - sched) * 1e6))
                self._lag_hist[bucket_of_us(lag_us, nb)] += 1
                self._lag_sum_us += lag_us
                self._lag_ticks += 1
                if lag_us > self._lag_max_us:
                    self._lag_max_us = lag_us
                sched += interval
                if sched > now:
                    break
        self._probe_next = sched
        self._probe_handle = loop.call_at(sched, self._probe_tick)

    def _note_stall(self, coro_name: str, dt_ns: int) -> None:
        """Slow path only: a coroutine resumption over the threshold."""
        task_name = None
        try:
            t = asyncio.current_task()
            if t is not None:
                task_name = t.get_name()
        except RuntimeError:
            pass
        with self._lock:
            self._stall_count += 1
            self._stall_sum_us += dt_ns // 1000
            self._stalls.append({
                "coro": coro_name,
                "task": task_name,
                "ms": round(dt_ns / 1e6, 3),
                "ts": time.time(),
            })

    # -- plane 2: gc pauses ------------------------------------------------
    def _gc_cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_t0_ns = time.perf_counter_ns()
            return
        t0 = self._gc_t0_ns
        if t0 == 0:
            return
        self._gc_t0_ns = 0
        dt_us = (time.perf_counter_ns() - t0) // 1000
        gen = info.get("generation", 2)
        gen = 2 if gen is None or gen > 2 else (0 if gen < 0 else int(gen))
        # DELIBERATELY LOCK-FREE: an automatic collection can fire on an
        # allocation made while THIS thread already holds self._lock
        # (snapshot copies, serde first-insert, the stall ring append) —
        # taking the non-reentrant lock here would self-deadlock the
        # process. Every fold below is a single GIL-held slot increment;
        # a reader may see a momentarily torn histogram copy, which is
        # acceptable telemetry slack, unlike a frozen event loop.
        self._gc_hist[gen][bucket_of_us(dt_us, self.n_buckets)] += 1
        self._gc_sum_us[gen] += dt_us
        self._gc_count[gen] += 1
        self._gc_collected += int(info.get("collected", 0) or 0)
        self._gc_uncollectable += int(info.get("uncollectable", 0) or 0)
        if self._dispatch_depth > 0:
            # the waterfall cross-link: this pause landed inside a
            # device_dispatch bracket — the batch it stalled will show
            # the time in its dispatch stage, and this counter names
            # the cause
            self._gc_in_dispatch += 1

    def begin_dispatch(self) -> None:
        """Bracket entry for the balancer's device-dispatch section (loop
        thread only; plain increments so the disabled path costs two
        attribute ops)."""
        self._dispatch_depth += 1

    def end_dispatch(self) -> None:
        self._dispatch_depth -= 1

    # -- plane 3: task churn + serde ---------------------------------------
    def _task_factory(self, loop, coro, **kwargs):
        self._tasks_created += 1
        if hasattr(coro, "send") and hasattr(coro, "throw"):
            name = getattr(coro, "__qualname__", None) or repr(coro)
            coro = _TimedCoro(coro, self, name)
        if self._prev_factory is not None:
            task = self._prev_factory(loop, coro, **kwargs)
        else:
            task = asyncio.Task(coro, loop=loop, **kwargs)
        task.add_done_callback(self._task_done)
        return task

    def _task_done(self, task) -> None:
        # deliberately does NOT call task.exception(): retrieving it here
        # would suppress asyncio's "exception was never retrieved" warning
        # for genuinely dropped failures
        self._tasks_finished += 1

    def serde_observe(self, hop: str, direction: str, nbytes: int,
                      dt_ns: int) -> None:
        """One serialize/deserialize hop (messaging/connector.py's
        encode_message/decode_message are the callers)."""
        with self._lock:
            row = self._serde.get((hop, direction))
            if row is None:
                row = self._serde[(hop, direction)] = [0, 0, 0]
            row[0] += 1
            row[1] += nbytes
            row[2] += dt_ns

    # -- plane 4: sampling profiler ----------------------------------------
    def _fold_frame(self, frame) -> Tuple[str, str]:
        """(leaf self-time key, collapsed root;..;leaf stack) for one
        sampled frame."""
        parts: List[str] = []
        g = frame
        depth = 0
        while g is not None and depth < MAX_STACK_DEPTH:
            code = g.f_code
            parts.append(f"{os.path.basename(code.co_filename)}:"
                         f"{code.co_name}")
            g = g.f_back
            depth += 1
        parts.reverse()
        code = frame.f_code
        leaf = (f"{code.co_name} ({os.path.basename(code.co_filename)}:"
                f"{code.co_firstlineno})")
        return leaf, ";".join(parts)

    @staticmethod
    def _bump(d: dict, key: str, cap: int) -> None:
        if key in d or len(d) < cap:
            d[key] = d.get(key, 0) + 1
        else:
            d[_OVERFLOW_KEY] = d.get(_OVERFLOW_KEY, 0) + 1

    def _sample_loop(self) -> None:
        stop = self._sampler_stop
        base_period = 1.0 / max(0.5, float(self.config.sample_hz))
        while True:
            cap = self._capture
            period = (1.0 / CAPTURE_HZ) if cap is not None else base_period
            if stop.wait(period):
                return
            try:
                frame = sys._current_frames().get(self._target_tid)
            except Exception:  # noqa: BLE001 — a failed sample is a skip
                continue
            if frame is None:
                continue
            leaf, collapsed = self._fold_frame(frame)
            now = time.monotonic()
            with self._lock:
                self._samples += 1
                self._bump(self._census, leaf, MAX_CENSUS_KEYS)
                self._bump(self._collapsed, collapsed, MAX_COLLAPSED_KEYS)
                cap = self._capture
                if cap is not None:
                    if now >= cap["until"]:
                        self._capture = None
                    else:
                        cap["samples"] += 1
                        self._bump(cap["census"], leaf, MAX_CENSUS_KEYS)
                        self._bump(cap["collapsed"], collapsed,
                                   MAX_COLLAPSED_KEYS)

    async def capture(self, seconds: float) -> dict:
        """Arm a bounded full-rate (CAPTURE_HZ) sampling window, wait it
        out, and return the window's collapsed stacks + census — the PR 3
        capture-plane pattern. One window at a time."""
        if not self.enabled or not self.sampler_running:
            raise RuntimeError("host sampler is not running")
        seconds = min(max(0.05, float(seconds)),
                      float(self.config.capture_limit_s))
        with self._lock:
            if self._capture is not None:
                raise RuntimeError("a capture window is already armed")
            cap = {"until": time.monotonic() + seconds, "samples": 0,
                   "census": {}, "collapsed": {}}
            self._capture = cap
        await asyncio.sleep(seconds + 2.0 / CAPTURE_HZ)
        with self._lock:
            if self._capture is cap:
                self._capture = None
            census = dict(cap["census"])
            collapsed = dict(cap["collapsed"])
        ranked = sorted(census.items(), key=lambda kv: -kv[1])
        total = max(1, cap["samples"])
        lines = [f"{stack} {n}" for stack, n in
                 sorted(collapsed.items(), key=lambda kv: -kv[1])]
        return {
            "seconds": seconds,
            "hz": CAPTURE_HZ,
            "samples": cap["samples"],
            "top": [{"frame": k, "samples": n,
                     "pct": round(100.0 * n / total, 1)}
                    for k, n in ranked[:20]],
            #: flamegraph.pl / speedscope "collapsed" format, one
            #: semicolon-joined stack + count per line
            "collapsed": "\n".join(lines),
        }

    # -- read side ---------------------------------------------------------
    def _pctl_ms(self, counts: List[int], q: float) -> Optional[float]:
        """Upper bound (ms) of the bucket holding the q-quantile (shared
        log2 bounds with the waterfall); None when empty or overflowed."""
        import math
        total = sum(counts)
        if not total:
            return None
        target = max(1, math.ceil(q * total))
        cum = 0
        bounds = bucket_bounds_ms(self.n_buckets)
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                return bounds[i] if i < len(bounds) else None
        return None

    def snapshot(self) -> dict:
        """The `GET /admin/profile/host` payload: host-side reads only."""
        if not self.enabled:
            # disabled payload stays byte-identical to pre-federation
            # builds — the fleet mergers drop disabled members anyway
            return {"enabled": False}
        with self._lock:
            lag_hist = list(self._lag_hist)
            lag_sum_us, lag_max_us = self._lag_sum_us, self._lag_max_us
            lag_ticks = self._lag_ticks
            stalls = [s for s in self._stalls.last(self._stalls.size)
                      if s is not None]
            stall_count, stall_sum_us = self._stall_count, self._stall_sum_us
            gc_hist = [list(h) for h in self._gc_hist]
            gc_sum_us = list(self._gc_sum_us)
            gc_count = list(self._gc_count)
            gc_collected = self._gc_collected
            gc_uncollectable = self._gc_uncollectable
            gc_in_dispatch = self._gc_in_dispatch
            created, finished = self._tasks_created, self._tasks_finished
            serde = {k: list(v) for k, v in self._serde.items()}
            census = dict(self._census)
            samples = self._samples
        uptime_s = max(0.0, time.monotonic() - self._epoch_mono)
        wall_us = max(1.0, uptime_s * 1e6)
        gc_total_us = sum(gc_sum_us)
        ranked = sorted(census.items(), key=lambda kv: -kv[1])
        return {
            "enabled": True,
            # the federation's merge key (ISSUE 16) — disambiguates
            # multi-process loadgen's per-worker host snapshots too
            "identity": identity(),
            "installed": self._installed,
            "uptime_s": round(uptime_s, 3),
            "loop_lag": {
                "ticks": lag_ticks,
                "probe_interval_ms": self.config.lag_probe_ms,
                "p50_ms": self._pctl_ms(lag_hist, 0.50),
                "p99_ms": self._pctl_ms(lag_hist, 0.99),
                "max_ms": round(lag_max_us / 1000.0, 3),
                "mean_ms": (round(lag_sum_us / lag_ticks / 1000.0, 3)
                            if lag_ticks else None),
            },
            "stalls": {
                "threshold_ms": self.config.stall_threshold_ms,
                "count": stall_count,
                "total_ms": round(stall_sum_us / 1000.0, 3),
                #: worst offenders first (the ring keeps the most recent
                #: `stall_ring`; ranking inside it answers "who stalls")
                "worst": sorted(stalls, key=lambda s: -s["ms"])[:16],
            },
            "gc": {
                "pauses": {str(g): gc_count[g] for g in range(3)},
                "pause_ms": {str(g): round(gc_sum_us[g] / 1000.0, 3)
                             for g in range(3)},
                "p99_ms": {str(g): self._pctl_ms(gc_hist[g], 0.99)
                           for g in range(3) if gc_count[g]},
                "collected": gc_collected,
                "uncollectable": gc_uncollectable,
                "overlapping_dispatch": gc_in_dispatch,
                #: share of host wall-time spent paused in GC since
                #: install — the "GC is X% of the loop" number
                "pause_share_pct": round(100.0 * gc_total_us / wall_us, 3),
            },
            "tasks": {
                "created": created,
                "finished": finished,
                "active": created - finished,
            },
            "serde": [
                {"hop": hop, "direction": direction, "count": row[0],
                 "bytes": row[1], "ms": round(row[2] / 1e6, 3),
                 #: serde wall-time over host wall-time — the "JSON is
                 #: X% of the loop" number, per hop and direction
                 "share_pct": round(100.0 * (row[2] / 1e3) / wall_us, 3)}
                for (hop, direction), row in sorted(serde.items())
            ],
            "sampler": {
                "running": self.sampler_running,
                "hz": self.config.sample_hz,
                "samples": samples,
                "distinct_frames": len(census),
                "top": [{"frame": k, "samples": n,
                         "pct": round(100.0 * n / max(1, samples), 1)}
                        for k, n in ranked[:10]],
            },
        }

    def raw_counts(self) -> dict:
        """The exact-merge export behind `?raw=1` (ISSUE 16): integer
        bucket counts / sums only — percentiles do not compose across
        processes, bucket counts merge bucket-wise bit-exactly."""
        with self._lock:
            out = {
                "identity": identity(),
                "enabled": self.enabled,
                "buckets": self.n_buckets,
                "uptime_s": round(max(0.0, time.monotonic()
                                      - self._epoch_mono), 3),
                "lag": {"hist": list(self._lag_hist),
                        "sum_us": int(self._lag_sum_us),
                        "max_us": int(self._lag_max_us),
                        "ticks": int(self._lag_ticks)},
                "stalls": {"count": int(self._stall_count),
                           "sum_us": int(self._stall_sum_us)},
                "gc": {"hist": [list(h) for h in self._gc_hist],
                       "sum_us": [int(v) for v in self._gc_sum_us],
                       "count": [int(v) for v in self._gc_count],
                       "collected": int(self._gc_collected),
                       "uncollectable": int(self._gc_uncollectable),
                       "overlapping_dispatch": int(self._gc_in_dispatch)},
                "tasks": {"created": int(self._tasks_created),
                          "finished": int(self._tasks_finished)},
                "serde": [[hop, direction, int(row[0]), int(row[1]),
                           int(row[2])]
                          for (hop, direction), row
                          in sorted(self._serde.items())],
            }
        return out

    def collapsed_text(self) -> str:
        """The always-on census as flamegraph collapsed-stack lines."""
        with self._lock:
            items = sorted(self._collapsed.items(), key=lambda kv: -kv[1])
        return "\n".join(f"{stack} {n}" for stack, n in items)

    # -- exposition --------------------------------------------------------
    @staticmethod
    def _plain_counter(name: str, value, openmetrics: bool) -> List[str]:
        """Unlabeled counter with the OpenMetrics `_total` naming rule
        (see controller/monitoring.py counter_family_text)."""
        base = name[:-len("_total")] if name.endswith("_total") else name
        fam = base if openmetrics else name
        sample = (base + "_total") if openmetrics else name
        return [f"# TYPE {fam} counter", f"{sample} {value}"]

    def prometheus_text(self, openmetrics: bool = False) -> str:
        if not self.enabled:
            return ""
        from ..controller.monitoring import (counter_family_text,
                                             histogram_family_text)
        with self._lock:
            lag_hist = list(self._lag_hist)
            lag_sum_us = self._lag_sum_us
            gc_hist = [list(h) for h in self._gc_hist]
            gc_sum_us = list(self._gc_sum_us)
            stall_count = self._stall_count
            gc_in_dispatch = self._gc_in_dispatch
            gc_collected = self._gc_collected
            gc_uncollectable = self._gc_uncollectable
            created, finished = self._tasks_created, self._tasks_finished
            serde = {k: list(v) for k, v in self._serde.items()}
        bounds = bucket_bounds_ms(self.n_buckets)
        out: List[str] = []
        if sum(lag_hist):
            out += histogram_family_text(
                "openwhisk_host_event_loop_lag_seconds", "thread",
                [("event_loop", lag_hist, lag_sum_us / 1000.0)], bounds)
        gc_rows = [(str(g), gc_hist[g], gc_sum_us[g] / 1000.0)
                   for g in range(3) if sum(gc_hist[g])]
        out += histogram_family_text(
            "openwhisk_host_gc_pause_seconds", "generation", gc_rows, bounds)
        out += self._plain_counter("openwhisk_host_tasks_created_total",
                                   created, openmetrics)
        out += self._plain_counter("openwhisk_host_tasks_finished_total",
                                   finished, openmetrics)
        out += ["# TYPE openwhisk_host_tasks_active gauge",
                f"openwhisk_host_tasks_active {created - finished}"]
        out += self._plain_counter("openwhisk_host_loop_stalls_total",
                                   stall_count, openmetrics)
        out += self._plain_counter(
            "openwhisk_host_gc_pauses_in_dispatch_total", gc_in_dispatch,
            openmetrics)
        out += self._plain_counter("openwhisk_host_gc_collected_total",
                                   gc_collected, openmetrics)
        out += self._plain_counter("openwhisk_host_gc_uncollectable_total",
                                   gc_uncollectable, openmetrics)
        serde_rows = sorted(serde.items())
        out += counter_family_text(
            "openwhisk_host_serde_seconds_total",
            [({"hop": hop, "direction": d}, round(row[2] / 1e9, 6))
             for (hop, d), row in serde_rows], openmetrics=openmetrics)
        out += counter_family_text(
            "openwhisk_host_serde_bytes_total",
            [({"hop": hop, "direction": d}, row[1])
             for (hop, d), row in serde_rows], openmetrics=openmetrics)
        return "\n".join(out)


#: the process-wide observatory (GLOBAL_WATERFALL pattern): the messaging
#: serde helpers, the balancer's dispatch bracket and the admin endpoints
#: all reach it without a shared reference; Controller.start / the invoker
#: main own install()/uninstall()
GLOBAL_HOST_OBSERVATORY = HostObservatory.from_config()

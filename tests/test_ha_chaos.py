"""Chaos / HA tests: component kill + recovery under a live deployment.

Parity with the reference's ha/ShootComponentsTests (docker-restart
controller mid-traffic, assert availability via the hot standby),
invokerShoot/ShootInvokerTests (invoker kill/recovery) and
limits/ThrottleTests (throttle enforcement over HTTP) — here against real
OS processes wired over the TCP bus, traffic through the edge proxy.
"""
import asyncio
import base64
import os
import signal
import socket
import subprocess
import sys
import time

import aiohttp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from openwhisk_tpu.standalone import GUEST_KEY, GUEST_UUID  # noqa: E402

AUTH = "Basic " + base64.b64encode(f"{GUEST_UUID}:{GUEST_KEY}".encode()).decode()
HDRS = {"Authorization": AUTH, "Content-Type": "application/json"}
CODE = "def main(a):\n    return {'alive': True, 'n': a.get('n')}\n"


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Cluster:
    """Popen-based mini-deployment with per-service kill/restart."""

    def __init__(self, tmp_path, n_controllers=1, edge=False, ctrl_env=None,
                 balancer="sharding", docstore=False):
        self.balancer = balancer
        self.db_file = str(tmp_path / "whisks.db")
        self.docstore_port = _free_port() if docstore else None
        # with a docstore, services dial it; without, they share the file
        self.db = (f"docstore://127.0.0.1:{self.docstore_port}"
                   if docstore else self.db_file)
        self.bus_port = _free_port()
        self.ctrl_ports = [_free_port() for _ in range(n_controllers)]
        self.edge_port = _free_port() if edge else None
        # Pin spawned services to the CPU backend regardless of what the
        # caller's environment says (the driver exports JAX_PLATFORMS=axon,
        # under which multiple TPU controllers would contend for one chip).
        self.env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        self.env.update(ctrl_env or {})
        self.ctrl_extra_argv: list = []
        self._ctrl_argvs: dict = {}
        self.procs = {}

    def spawn(self, name, argv):
        self.procs[name] = subprocess.Popen(
            argv, env=self.env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def start(self):
        self.spawn("bus", [sys.executable, "-m", "openwhisk_tpu.messaging",
                           "--port", str(self.bus_port)])
        if self.docstore_port:
            self.start_docstore()
        time.sleep(1.5)
        self.start_invoker()
        for i, port in enumerate(self.ctrl_ports):
            argv = [sys.executable, "-m", "openwhisk_tpu.controller",
                    "--bus", f"127.0.0.1:{self.bus_port}", "--db", self.db,
                    "--port", str(port), "--instance", str(i),
                    "--cluster-size", str(len(self.ctrl_ports)),
                    "--balancer", self.balancer]
            if i == 0:
                argv.append("--seed-guest")
            argv += self.ctrl_extra_argv
            self._ctrl_argvs[i] = argv
            self.spawn(f"controller{i}", argv)
        if self.edge_port:
            self.spawn("edge", [sys.executable, "-m", "openwhisk_tpu.edge",
                                "--port", str(self.edge_port), "--controllers",
                                *[f"http://127.0.0.1:{p}"
                                  for p in self.ctrl_ports]])

    def start_docstore(self):
        self.spawn("docstore", [sys.executable, "-m",
                                "openwhisk_tpu.database.remote_store",
                                "--db", self.db_file,
                                "--port", str(self.docstore_port)])

    def start_invoker(self, name="chaos-a"):
        self.spawn("invoker", [sys.executable, "-m", "openwhisk_tpu.invoker",
                               "--bus", f"127.0.0.1:{self.bus_port}",
                               "--db", self.db, "--unique-name", name,
                               "--memory", "1024"])

    def kill(self, name, sig=signal.SIGKILL):
        proc = self.procs[name]
        proc.send_signal(sig)
        proc.wait(timeout=10)

    def restart_controller(self, i: int):
        """Re-spawn controller i with the exact argv it was born with."""
        self.spawn(f"controller{i}", self._ctrl_argvs[i])

    def stop(self):
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def api(self, port=None):
        port = port or (self.edge_port or self.ctrl_ports[0])
        return f"http://127.0.0.1:{port}/api/v1"

    async def wait_healthy(self, session, port=None, want="up", timeout=60):
        url = f"http://127.0.0.1:{port or self.ctrl_ports[0]}/invokers"
        for _ in range(timeout * 2):
            try:
                async with session.get(url, headers=HDRS) as r:
                    if r.status == 200 and want in (await r.text()):
                        return True
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.5)
        return False


@pytest.mark.slow
class TestControllerFailover:
    def test_kill_controller0_traffic_survives_via_edge(self, tmp_path):
        """ref ha/ShootComponentsTests:47-160 — one controller dies, the
        edge fails over and requests keep succeeding."""
        cluster = Cluster(tmp_path, n_controllers=2, edge=True)
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s)
                    # both controllers must see the fleet (per-controller
                    # health groups) before traffic starts
                    assert await cluster.wait_healthy(
                        s, port=cluster.ctrl_ports[1])
                    base = cluster.api()  # through the edge
                    async with s.put(f"{base}/namespaces/_/actions/ha",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200, await r.text()

                    async def invoke(n):
                        # transient errors COUNT AS FAILED ATTEMPTS — the
                        # test's ok-threshold absorbs them; raising here
                        # would fail the test on one connection hiccup
                        try:
                            async with s.post(
                                    f"{base}/namespaces/_/actions/ha?blocking=true&result=true",
                                    headers=HDRS, json={"n": n}) as r:
                                return r.status, await r.json(
                                    content_type=None)
                        except (aiohttp.ClientError, asyncio.TimeoutError,
                                ValueError):
                            return 0, {}

                    assert (await invoke(1))[0] == 200
                    cluster.kill("controller0")
                    # edge marks the dead upstream failed and retries the
                    # standby; allow the window where in-flight errors once
                    ok = 0
                    for n in range(12):
                        status, body = await invoke(100 + n)
                        if status == 200 and body == {"alive": True,
                                                      "n": 100 + n}:
                            ok += 1
                        await asyncio.sleep(0.25)
                    return ok

            ok = asyncio.run(drive())
            assert ok >= 8, f"only {ok}/12 invokes survived controller kill"
        finally:
            cluster.stop()


@pytest.mark.slow
class TestClusterMembership:
    def test_controller_kill_reshards_capacity_on_survivor(self, tmp_path):
        """VERDICT r1 #3 acceptance: kill controller1 mid-traffic; within a
        bounded window controller0's TPU balancer re-shards from 1/2 to the
        whole fleet (cluster/size 2 -> 1) while invokes keep succeeding
        (ref updateCluster, ShardingContainerPoolBalancer.scala:561-584)."""
        cluster = Cluster(tmp_path, n_controllers=2, edge=True, balancer="tpu")
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    # two TPU balancers compile kernels serially on this
                    # 1-core box: allow a long boot window
                    assert await cluster.wait_healthy(s, timeout=120)
                    assert await cluster.wait_healthy(
                        s, port=cluster.ctrl_ports[1], timeout=120)

                    async def cluster_size(port):
                        url = f"http://127.0.0.1:{port}/invokers"
                        async with s.get(url, headers=HDRS) as r:
                            return (await r.json()).get("cluster/size")

                    # membership converged: both see 2
                    for _ in range(120):
                        if (await cluster_size(cluster.ctrl_ports[0]) == 2 and
                                await cluster_size(cluster.ctrl_ports[1]) == 2):
                            break
                        await asyncio.sleep(0.25)
                    else:
                        raise AssertionError("membership never reached 2")

                    base = cluster.api()
                    async with s.put(f"{base}/namespaces/_/actions/mem",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200, await r.text()

                    async def invoke(n):
                        # transient errors count as failed attempts (the
                        # loop polls 40x and the final invoke re-asserts)
                        try:
                            async with s.post(
                                    f"{base}/namespaces/_/actions/mem?blocking=true&result=true",
                                    headers=HDRS, json={"n": n}) as r:
                                return r.status, await r.json(
                                    content_type=None)
                        except (aiohttp.ClientError, asyncio.TimeoutError,
                                ValueError):
                            return 0, {}

                    assert (await invoke(1))[0] == 200
                    cluster.kill("controller1")  # SIGKILL: no graceful leave
                    # survivor folds to 1 within the heartbeat timeout window
                    resharded = False
                    ok = 0
                    for n in range(40):
                        size = await cluster_size(cluster.ctrl_ports[0])
                        status, body = await invoke(200 + n)
                        if status == 200:
                            ok += 1
                        if size == 1:
                            resharded = True
                            break
                        await asyncio.sleep(0.25)
                    assert resharded, "survivor never folded to cluster size 1"
                    status, body = await invoke(999)
                    return ok, status, body

            ok, status, body = asyncio.run(drive())
            assert status == 200 and body == {"alive": True, "n": 999}
            assert ok >= 1
        finally:
            cluster.stop()


@pytest.mark.slow
class TestDocstoreFailover:
    def test_docstore_restart_traffic_resumes_entities_survive(self, tmp_path):
        """ref ha/ShootComponentsTests:314-315 (CouchDB restart): kill the
        shared doc-store mid-traffic; after a restart on the same backing
        file, clients reconnect, entities survive, invokes succeed again."""
        cluster = Cluster(tmp_path, n_controllers=1, docstore=True)
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s)
                    base = cluster.api()
                    async with s.put(f"{base}/namespaces/_/actions/ds",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200, await r.text()

                    async def invoke(n):
                        async with s.post(
                                f"{base}/namespaces/_/actions/ds?blocking=true&result=true",
                                headers=HDRS, json={"n": n}) as r:
                            return r.status, await r.json(content_type=None)

                    status, body = await invoke(1)
                    assert status == 200 and body == {"alive": True, "n": 1}

                    cluster.kill("docstore")
                    cluster.start_docstore()
                    # wait for the restarted docstore to LISTEN before the
                    # measured window: its boot time is load-dependent (a
                    # fresh interpreter on a busy 1-core box can take
                    # seconds), and what this test asserts is that CLIENTS
                    # RECONNECT once it's back — not how fast it boots
                    for _ in range(240):
                        try:
                            socket.create_connection(
                                ("127.0.0.1", cluster.docstore_port),
                                timeout=0.25).close()
                            break
                        except OSError:
                            await asyncio.sleep(0.25)
                    else:
                        pytest.fail("docstore never listened after restart")
                    # clients reconnect lazily on the next request; then
                    # require sustained success
                    ok = 0
                    for n in range(16):
                        try:
                            status, body = await invoke(100 + n)
                            if status == 200 and body == {"alive": True,
                                                          "n": 100 + n}:
                                ok += 1
                        except aiohttp.ClientError:
                            pass
                        await asyncio.sleep(0.25)
                    # the entity itself must have survived the restart
                    async with s.get(f"{base}/namespaces/_/actions/ds",
                                     headers=HDRS) as r:
                        return ok, r.status

            ok, get_status = asyncio.run(drive())
            assert ok >= 10, f"only {ok}/16 invokes after docstore restart"
            assert get_status == 200
        finally:
            cluster.stop()


@pytest.mark.slow
class TestInvokerRecovery:
    def test_invoker_kill_marks_down_then_recovers(self, tmp_path):
        """ref invokerShoot/ShootInvokerTests — ping silence flips the
        invoker Offline (10 s); a restart under the same unique name reuses
        the id and serves traffic again."""
        cluster = Cluster(tmp_path, n_controllers=1)
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s)
                    base = cluster.api()
                    async with s.put(f"{base}/namespaces/_/actions/rec",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200

                    cluster.kill("invoker")
                    # offline after 10 s of silence
                    assert await cluster.wait_healthy(s, want="down",
                                                      timeout=30), \
                        "invoker never marked down"
                    # invoking now is rejected (no usable invokers)
                    async with s.post(
                            f"{base}/namespaces/_/actions/rec?blocking=true",
                            headers=HDRS, json={}) as r:
                        rejected = r.status

                    cluster.start_invoker(name="chaos-a")  # same unique name
                    assert await cluster.wait_healthy(s, want="up",
                                                      timeout=60)
                    async with s.post(
                            f"{base}/namespaces/_/actions/rec?blocking=true&result=true",
                            headers=HDRS, json={"n": 7}) as r:
                        return rejected, r.status, await r.json()

            rejected, status, body = asyncio.run(drive())
            assert rejected >= 500  # unavailable while fleet is down
            assert (status, body) == (200, {"alive": True, "n": 7})
        finally:
            cluster.stop()


@pytest.mark.slow
class TestThrottlesOverHttp:
    def test_rate_throttle_returns_429(self, tmp_path):
        """ref limits/ThrottleTests — invocations past the per-minute rate
        limit are rejected with 429 over the REST surface."""
        cluster = Cluster(tmp_path, n_controllers=1,
                          ctrl_env={"CONFIG_whisk_limits_invocationsPerMinute": "2"})
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s)
                    base = cluster.api()
                    async with s.put(f"{base}/namespaces/_/actions/th",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200
                    statuses = []
                    for _ in range(4):
                        async with s.post(
                                f"{base}/namespaces/_/actions/th?blocking=true",
                                headers=HDRS, json={}) as r:
                            statuses.append(r.status)
                            body = await r.json()
                    return statuses, body

            statuses, last_body = asyncio.run(drive())
            assert statuses[:2] == [200, 200]
            assert 429 in statuses[2:], statuses
            assert "error" in last_body
        finally:
            cluster.stop()


@pytest.mark.slow
class TestTpuBalancerDistributed:
    def test_tpu_balancer_multi_process(self, tmp_path):
        """The TPU placement path in true distributed mode: TWO controller
        processes, each with its own device-kernel balancer and a cluster-
        sharded half of the fleet's capacity, publishing interleaved onto
        the SAME shared invoker (bus + invoker beside them as their own OS
        processes). (Subprocesses pin JAX to the CPU backend so tests never
        contend for the tunneled chip.)"""
        env = {"JAX_PLATFORMS": "cpu"}
        cluster = Cluster(tmp_path, n_controllers=2, balancer="tpu",
                          ctrl_env=env)
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s, timeout=240)
                    assert await cluster.wait_healthy(
                        s, port=cluster.ctrl_ports[1], timeout=240)
                    base0 = cluster.api(cluster.ctrl_ports[0])
                    base1 = cluster.api(cluster.ctrl_ports[1])
                    async with s.put(f"{base0}/namespaces/_/actions/tdist",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200, await r.text()

                    # interleave: both controllers place concurrently on the
                    # one shared invoker (each owns half its capacity).
                    # A transient non-200/connection error under full-suite
                    # load retries — the claim under test is that BOTH
                    # controllers' placements execute, not that a loaded
                    # one-core box never hiccups.
                    async def one(i):
                        base = base0 if i % 2 == 0 else base1
                        last = (0, {})
                        for _ in range(3):
                            try:
                                async with s.post(
                                        f"{base}/namespaces/_/actions/tdist"
                                        "?blocking=true&result=true",
                                        headers=HDRS, json={"n": i}) as r:
                                    last = (r.status, await r.json(
                                        content_type=None))
                                    if r.status == 200:
                                        return last
                            except (aiohttp.ClientError,
                                    asyncio.TimeoutError,
                                    ValueError):  # non-JSON error body
                                pass
                            await asyncio.sleep(1.0)
                        return last

                    return await asyncio.gather(*[one(i) for i in range(8)])

            out = asyncio.run(drive())
            assert all(st == 200 and body["alive"] for st, body in out), out
            assert sorted(body["n"] for _, body in out) == list(range(8))
            # both controllers' placements executed (even n via controller0,
            # odd via controller1 — all landed on the single shared invoker)
            evens = [body["n"] for st, body in out if body["n"] % 2 == 0]
            odds = [body["n"] for st, body in out if body["n"] % 2 == 1]
            assert len(evens) == 4 and len(odds) == 4
        finally:
            cluster.stop()


@pytest.mark.slow
class TestDeviceRateLimitOverHttp:
    def test_balancer_rate_limit_flag_returns_429(self, tmp_path):
        """--balancer-rate-limit wires ops/throttle.py's device token bucket
        into the TPU placement step: past the per-namespace budget, blocking
        invokes surface as 429 at the REST API (entitlement-throttle shape),
        while the front-door RateThrottler (default 60/min) never fires."""
        cluster = Cluster(tmp_path, n_controllers=1, balancer="tpu",
                          ctrl_env={"JAX_PLATFORMS": "cpu"})
        cluster.ctrl_extra_argv = ["--balancer-rate-limit", "2"]
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s, timeout=120)
                    base = cluster.api()
                    async with s.put(f"{base}/namespaces/_/actions/dev429",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200
                    statuses = []
                    for _ in range(4):
                        async with s.post(
                                f"{base}/namespaces/_/actions/dev429"
                                "?blocking=true",
                                headers=HDRS, json={}) as r:
                            statuses.append(r.status)
                            body = await r.json()
                    return statuses, body

            statuses, last_body = asyncio.run(drive())
            assert statuses[:2] == [200, 200], statuses
            assert 429 in statuses[2:], statuses
            assert "error" in last_body
        finally:
            cluster.stop()


@pytest.mark.slow
class TestUserEventsService:
    def test_monitoring_process_exports_prometheus(self, tmp_path):
        """The standalone user-events service consumes the events topic from
        the bus and serves Prometheus series (ref core/monitoring)."""
        cluster = Cluster(tmp_path, n_controllers=1)
        cluster.start()
        mon_port = _free_port()
        cluster.spawn("monitoring",
                      [sys.executable, "-m",
                       "openwhisk_tpu.controller.monitoring",
                       "--bus", f"127.0.0.1:{cluster.bus_port}",
                       "--port", str(mon_port)])
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s)
                    base = cluster.api()
                    async with s.put(f"{base}/namespaces/_/actions/mon",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200
                    async with s.post(
                            f"{base}/namespaces/_/actions/mon?blocking=true",
                            headers=HDRS, json={}) as r:
                        assert r.status == 200
                    for _ in range(40):
                        try:
                            async with s.get(
                                    f"http://127.0.0.1:{mon_port}/metrics") as r:
                                text = await r.text()
                                if "userevents_activations" in text:
                                    return text
                        except aiohttp.ClientError:
                            pass
                        await asyncio.sleep(0.5)
                    raise AssertionError("user-events series never appeared")

            text = asyncio.run(drive())
            assert "userevents_activations_" in text
        finally:
            cluster.stop()


@pytest.mark.slow
class TestJournaledFailover:
    def test_hard_kill_active_mid_burst_fails_over_without_double_placement(
            self, tmp_path):
        """ISSUE 9 tentpole, chaos half: two --ha controllers share a
        snapshot + write-ahead journal; open-loop load (tools/loadgen.py
        schedule/driver — arrivals fire at scheduled times, never waiting
        on earlier completions) runs through the edge while the ACTIVE is
        SIGKILLed mid-burst. The standby must detect the silence, claim
        the next epoch, restore snapshot+journal and resume placement —
        with bounded downtime and ZERO double-executed activations (each
        request's side-effect file is written at most once; epoch fencing
        discards any zombie leftovers). Books bit-parity is asserted by
        the fast in-process suite (tests/test_journal.py) where both
        sides are observable."""
        from tools.loadgen import make_schedule, open_loop

        effects = tmp_path / "effects"
        effects.mkdir()
        snap = str(tmp_path / "ha.snap")
        jdir = str(tmp_path / "wal")
        # the action writes one unique file per EXECUTION: a double
        # placement that actually runs twice leaves two files for one n
        side_code = (
            "import os, uuid\n"
            "def main(a):\n"
            "    p = os.path.join(a['dir'], '%s-%s' % (a['n'],"
            " uuid.uuid4().hex))\n"
            "    open(p, 'w').close()\n"
            "    return {'n': a['n']}\n")
        # raise the front-door throttles: the burst is ~240 invokes/min
        # (default 60/min), and a request the standby refuses at publish
        # has already consumed rate budget on BOTH upstreams via the edge
        # retry — the test measures failover, not entitlement
        cluster = Cluster(tmp_path, n_controllers=2, edge=True,
                          balancer="tpu", ctrl_env={
                              "CONFIG_whisk_limits_invocationsPerMinute":
                                  "100000",
                              "CONFIG_whisk_limits_concurrentInvocations":
                                  "1000"})
        cluster.ctrl_extra_argv = [
            "--balancer-snapshot", snap,
            "--balancer-snapshot-interval", "1",
            "--balancer-journal", jdir, "--ha"]
        cluster.start()
        try:
            async def drive():
                timeout = aiohttp.ClientTimeout(total=30)
                async with aiohttp.ClientSession(timeout=timeout) as s:
                    assert await cluster.wait_healthy(s, timeout=180)
                    assert await cluster.wait_healthy(
                        s, port=cluster.ctrl_ports[1], timeout=180)
                    base = cluster.api()  # through the edge
                    async with s.put(f"{base}/namespaces/_/actions/haj",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": side_code}}) as r:
                        assert r.status == 200, await r.text()

                    async def invoke(n):
                        try:
                            async with s.post(
                                    f"{base}/namespaces/_/actions/haj"
                                    "?blocking=true&result=true",
                                    headers=HDRS,
                                    json={"n": n,
                                          "dir": str(effects)}) as r:
                                body = await r.json(content_type=None)
                                return (r.status == 200
                                        and body.get("n") == n)
                        except (aiohttp.ClientError, asyncio.TimeoutError,
                                ValueError):
                            return False

                    # leadership settles (boot grace ~5 s): poll until the
                    # elected active serves a placement through the edge
                    for n in range(120):
                        if await invoke(10000 + n):
                            break
                        await asyncio.sleep(0.5)
                    else:
                        raise AssertionError("no active leader emerged")

                    # open-loop burst: unique n per request, NO client
                    # retries (a retry would legitimately re-execute and
                    # read as a false double placement)
                    success_t: list = []

                    async def one(i, sched_ns):
                        ok = await invoke(i)
                        if ok:
                            success_t.append(time.monotonic())
                        return ok

                    rate, duration = 4.0, 45.0
                    offsets = make_schedule(rate, int(rate * duration),
                                            dist="constant")
                    kill_at = duration / 3.0
                    t0 = time.monotonic()

                    async def killer():
                        await asyncio.sleep(kill_at)
                        cluster.kill("controller0")  # SIGKILL the active
                        return time.monotonic()

                    kill_task = asyncio.ensure_future(killer())
                    row = await open_loop(one, offsets, drain_timeout=60.0)
                    t_kill = await kill_task

                    # the standby took over: placements succeed after the
                    # kill, and a final confirmatory invoke works NOW
                    post = [t for t in success_t if t > t_kill]
                    assert post, (
                        f"no successful placements after the active was "
                        f"killed (completed {row['completed']}/"
                        f"{row['offered']})")
                    assert await invoke(99999), \
                        "survivor must serve after the burst"
                    # bounded downtime: the longest gap between successive
                    # successful completions covers detection (5 s default
                    # silence timeout) + restore + replay; bound it well
                    # under the forced-timeout self-heal horizon
                    stamps = sorted(success_t)
                    gaps = [b - a for a, b in zip(stamps, stamps[1:])]
                    max_gap = max(gaps) if gaps else 0.0
                    assert max_gap < 45.0, \
                        f"failover downtime {max_gap:.1f}s exceeds bound"
                    return row, max_gap, t_kill - t0

            row, max_gap, kill_off = asyncio.run(drive())

            # ZERO double placement: every n executed at most once
            seen = {}
            for name in os.listdir(effects):
                n = name.split("-", 1)[0]
                seen[n] = seen.get(n, 0) + 1
            doubles = {n: c for n, c in seen.items() if c > 1}
            assert not doubles, f"double-executed activations: {doubles}"
            assert seen, "the burst must have executed something"
        finally:
            cluster.stop()


@pytest.mark.slow
class TestActiveActivePartitionChaos:
    def test_kill_one_of_three_actives_survivors_absorb_partitions(
            self, tmp_path):
        """ISSUE 15 tentpole, chaos half: THREE active/active partitioned
        controllers (CONFIG_whisk_ha_activeActive + --ha) share the
        journal/snapshot storage root; open-loop no-retry traffic over
        several namespaces runs through the edge while one active is
        SIGKILLed mid-burst. The survivors must claim its partitions
        (higher epochs), absorb its journal tail, and keep serving every
        namespace — with ZERO double-executed side effects and bounded
        downtime. Per-partition ownership is probed over /admin/ready."""
        effects = tmp_path / "effects"
        effects.mkdir()
        snap = str(tmp_path / "aa.snap")
        jdir = str(tmp_path / "wal")
        side_code = (
            "import os, uuid\n"
            "def main(a):\n"
            "    p = os.path.join(a['dir'], '%s-%s' % (a['n'],"
            " uuid.uuid4().hex))\n"
            "    open(p, 'w').close()\n"
            "    return {'n': a['n']}\n")
        cluster = Cluster(tmp_path, n_controllers=3, edge=True,
                          balancer="tpu", ctrl_env={
                              "CONFIG_whisk_ha_activeActive": "true",
                              "CONFIG_whisk_ha_activeActive_partitions":
                                  "8",
                              "CONFIG_whisk_limits_invocationsPerMinute":
                                  "100000",
                              "CONFIG_whisk_limits_concurrentInvocations":
                                  "1000"})
        cluster.ctrl_extra_argv = [
            "--balancer-snapshot", snap,
            "--balancer-snapshot-interval", "1",
            "--balancer-journal", jdir, "--ha"]
        cluster.start()
        try:
            async def drive():
                timeout = aiohttp.ClientTimeout(total=30)
                async with aiohttp.ClientSession(timeout=timeout) as s:
                    for port in cluster.ctrl_ports:
                        assert await cluster.wait_healthy(s, port=port,
                                                          timeout=240)
                    base = cluster.api()  # through the edge

                    async def ready(port):
                        try:
                            async with s.get(
                                    f"http://127.0.0.1:{port}/admin/ready",
                                    headers=HDRS) as r:
                                return r.status, await r.json(
                                    content_type=None)
                        except (aiohttp.ClientError,
                                asyncio.TimeoutError):
                            return 0, {}

                    # every controller owns a ring slice (200 = owns >=1)
                    for _ in range(240):
                        rs = [await ready(p) for p in cluster.ctrl_ports]
                        if all(st == 200 for st, _ in rs) and sum(
                                d.get("owned_partitions", 0)
                                for _, d in rs) == 8:
                            break
                        await asyncio.sleep(0.5)
                    else:
                        raise AssertionError(
                            f"ownership never converged: {rs}")
                    dead_owned = {
                        p["partition"]
                        for p in rs[0][1]["partitions"]
                        if p["role"] == "active"}
                    assert dead_owned, "controller0 must own something"

                    async with s.put(f"{base}/namespaces/_/actions/aaj",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": side_code}}
                                     ) as r:
                        assert r.status == 200, await r.text()

                    async def invoke(n):
                        # NO client retries: a retry would legitimately
                        # re-execute and read as a false double execution
                        try:
                            async with s.post(
                                    f"{base}/namespaces/_/actions/aaj"
                                    "?blocking=true&result=true",
                                    headers=HDRS,
                                    json={"n": n,
                                          "dir": str(effects)}) as r:
                                body = await r.json(content_type=None)
                                return (r.status == 200
                                        and body.get("n") == n)
                        except (aiohttp.ClientError, asyncio.TimeoutError,
                                ValueError):
                            return False

                    for n in range(120):
                        if await invoke(10000 + n):
                            break
                        await asyncio.sleep(0.5)
                    else:
                        raise AssertionError("no active emerged")

                    from tools.loadgen import make_schedule, open_loop
                    success_t: list = []

                    async def one(i, sched_ns):
                        ok = await invoke(i)
                        if ok:
                            success_t.append(time.monotonic())
                        return ok

                    rate, duration = 4.0, 45.0
                    offsets = make_schedule(rate, int(rate * duration),
                                            dist="constant")
                    kill_at = duration / 3.0

                    async def killer():
                        await asyncio.sleep(kill_at)
                        cluster.kill("controller0")  # SIGKILL an active
                        return time.monotonic()

                    kill_task = asyncio.ensure_future(killer())
                    row = await open_loop(one, offsets, drain_timeout=60.0)
                    t_kill = await kill_task

                    post = [t for t in success_t if t > t_kill]
                    assert post, (
                        f"no successes after the kill (completed "
                        f"{row['completed']}/{row['offered']})")
                    assert await invoke(99999), \
                        "survivors must serve after the burst"
                    # the dead controller's partitions were absorbed by
                    # the two survivors, at bumped epochs
                    for _ in range(120):
                        rs = [await ready(p)
                              for p in cluster.ctrl_ports[1:]]
                        owned = set()
                        for _st, d in rs:
                            owned |= {p["partition"]
                                      for p in d.get("partitions", [])
                                      if p["role"] == "active"}
                        if owned == set(range(8)):
                            break
                        await asyncio.sleep(0.5)
                    assert owned == set(range(8)), \
                        f"survivors absorbed only {sorted(owned)} " \
                        f"(dead owned {sorted(dead_owned)})"
                    stamps = sorted(success_t)
                    gaps = [b - a for a, b in zip(stamps, stamps[1:])]
                    max_gap = max(gaps) if gaps else 0.0
                    assert max_gap < 45.0, \
                        f"absorb downtime {max_gap:.1f}s exceeds bound"
                    return row

            row = asyncio.run(drive())

            # ZERO double execution: every n's side effect at most once
            seen = {}
            for name in os.listdir(effects):
                n = name.split("-", 1)[0]
                seen[n] = seen.get(n, 0) + 1
            doubles = {n: c for n, c in seen.items() if c > 1}
            assert not doubles, f"double-executed activations: {doubles}"
            assert seen, "the burst must have executed something"

            # zero lost/duplicated journal seqs, per instance journal
            from openwhisk_tpu.controller.loadbalancer.journal import \
                PlacementJournal
            checked = 0
            for i in range(3):
                d = os.path.join(jdir, f"ctrl{i}")
                if not os.path.isdir(d):
                    continue
                seqs = [int(r["seq"])
                        for r in PlacementJournal(d).records(0)]
                if not seqs:
                    continue
                checked += 1
                assert len(seqs) == len(set(seqs)), \
                    f"ctrl{i}: duplicated journal seqs"
                assert seqs == sorted(seqs), \
                    f"ctrl{i}: journal seqs out of order"
            assert checked >= 1, "at least one journal must have records"
        finally:
            cluster.stop()


@pytest.mark.slow
class TestBalancerSnapshotResume:
    def test_hard_killed_controller_resumes_from_snapshot(self, tmp_path):
        """SURVEY §5.4 end-to-end: a TPU controller running with
        --balancer-snapshot is SIGKILLed mid-life and restarted with the
        same argv; it restores the dumped registry/books at boot and
        serves traffic again."""
        snap = str(tmp_path / "c0.snap")
        cluster = Cluster(tmp_path, n_controllers=1, balancer="tpu")
        cluster.ctrl_extra_argv = ["--balancer-snapshot", snap,
                                   "--balancer-snapshot-interval", "1"]
        cluster.start()
        try:
            async def drive():
                async with aiohttp.ClientSession() as s:
                    assert await cluster.wait_healthy(s)
                    base = cluster.api()
                    async with s.put(f"{base}/namespaces/_/actions/snapres",
                                     headers=HDRS,
                                     json={"exec": {"kind": "python:3",
                                                    "code": CODE}}) as r:
                        assert r.status == 200
                    async with s.post(
                            f"{base}/namespaces/_/actions/snapres"
                            "?blocking=true", headers=HDRS, json={"n": 1}) as r:
                        assert r.status == 200
                    # a periodic dump must appear with the live registry
                    import json
                    for _ in range(40):
                        if os.path.exists(snap):
                            break
                        await asyncio.sleep(0.25)
                    assert os.path.exists(snap), \
                        "no periodic balancer dump within 10s"
                    with open(snap) as f:
                        doc = json.load(f)
                    assert doc["registry"], "snapshot must carry the fleet"

                    cluster.kill("controller0")
                    cluster.restart_controller(0)
                    assert await cluster.wait_healthy(s), \
                        "restarted controller must come back healthy"
                    async with s.post(
                            f"{base}/namespaces/_/actions/snapres"
                            "?blocking=true", headers=HDRS, json={"n": 2}) as r:
                        body = await r.json()
                        assert r.status == 200, body
                        assert body["response"]["result"]["n"] == 2

            asyncio.run(drive())
        finally:
            cluster.stop()

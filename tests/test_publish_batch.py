"""ISSUE 14: batch-shaped publish SPI + lazy ack result column.

Covers the acceptance contracts:
  * publish_many vs serial publish parity: identical placement decisions
    and books over fuzzed mixed-action batches, identical waterfall
    stamps, the serial path's exact exception texts (standby /
    no-invoker / device-throttle 429), and per-row capacity return on
    cancellation/abandonment;
  * off-switches: CONFIG_whisk_loadBalancer_batchPublish=false routes
    publish_many through the serial per-pair path (and
    maybe_batch_publish builds nothing); lazy_results=False keeps the
    PR 11 ack batch record byte-exact;
  * the one-shared-clock arrival fix: _note_arrivals(now, 1) is
    bit-exact with _note_arrival(now);
  * lazy ack result column: framed-wire roundtrip for every ack kind,
    the consumer-never-reads case asserted via the host observatory's
    `openwhisk_host_serde_*` counters (zero `ack_result` deserializes
    until a consumer touches the result), and the coalescing producer
    shipping the lazy frame end-to-end.
"""
from __future__ import annotations

import asyncio
import json
import random
import time

import numpy as np
import pytest

from openwhisk_tpu.controller.loadbalancer import (LoadBalancerException,
                                                   TpuBalancer)
from openwhisk_tpu.controller.loadbalancer.base import (
    LoadBalancerThrottleException, PublishCoalescer, maybe_batch_publish)
from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       ActionLimits, CodeExec,
                                       ControllerInstanceId, EntityName,
                                       EntityPath, ExecutableWhiskAction,
                                       Identity, InvokerInstanceId, MB,
                                       MemoryLimit, TimeLimit,
                                       WhiskActivation)
from openwhisk_tpu.core.entity.ids import DocRevision
from openwhisk_tpu.messaging import (ActivationMessage,
                                     MemoryMessagingProvider, PingMessage)
from openwhisk_tpu.messaging.coalesce import CoalescingProducer
from openwhisk_tpu.messaging.columnar import (AckBatchMessage, KIND_ACK,
                                              KIND_ACK_LAZY,
                                              LazyWhiskActivation,
                                              is_batch_payload, parse_batch)
from openwhisk_tpu.messaging.message import (
    CombinedCompletionAndResultMessage, CompletionMessage, ResultMessage)
from openwhisk_tpu.utils.hostprof import GLOBAL_HOST_OBSERVATORY
from openwhisk_tpu.utils.ring_buffer import ColumnRing
from openwhisk_tpu.utils.transaction import TransactionId
from openwhisk_tpu.utils.waterfall import (ActivationWaterfall,
                                           STAGE_PUBLISH_ENQUEUE,
                                           WaterfallConfig, _CTX_BASE)


def make_action(name="act", memory=256):
    a = ExecutableWhiskAction(EntityPath("guest"), EntityName(name),
                              CodeExec(kind="python:3", code="x"),
                              limits=ActionLimits(TimeLimit(5000),
                                                  MemoryLimit(MB(memory))))
    a.rev = DocRevision("1-b")
    return a


def make_msg(action, ident, blocking=False):
    return ActivationMessage(
        TransactionId(), action.fully_qualified_name, action.rev.rev, ident,
        ActivationId.generate(), ControllerInstanceId("0"), blocking, {})


async def _healthy_balancer(provider, n_invokers=4, mem=4096, **kw):
    """A TpuBalancer with `n_invokers` registered-and-healthy rows (pings
    only — no consumers ack, so placements hold until released)."""
    bal = TpuBalancer(provider, ControllerInstanceId("0"),
                      managed_fraction=1.0, blackbox_fraction=0.0,
                      prewarm=False, **kw)
    await bal.start()
    producer = provider.get_producer()
    provider.ensure_topic("health")
    instances = [InvokerInstanceId(i, user_memory=MB(mem))
                 for i in range(n_invokers)]
    for _ in range(120):
        for inst in instances:
            await producer.send("health", PingMessage(inst))
        await asyncio.sleep(0.05)
        health = await bal.invoker_health()
        if sum(h.status == "up" for h in health) >= n_invokers:
            break
    else:
        raise RuntimeError("fleet never became healthy")
    return bal


async def _drain(bal, timeout=5.0):
    """Wait until no device step is in flight and no work is queued."""
    t0 = time.monotonic()
    while (bal._inflight_steps or bal._pending or bal._releases):
        if time.monotonic() - t0 > timeout:
            raise RuntimeError("balancer did not drain")
        await asyncio.sleep(0.02)
    # one idle fold may still be pending on the flush task
    await asyncio.sleep(0.05)


def _placements(bal, aids):
    return [bal.activation_slots[a].invoker.instance for a in aids]


class TestPublishManyParity:
    def test_parity_fuzz_decisions_books_stamps(self):
        """Serial publish and publish_many over the same fuzzed mixed
        batch produce identical per-row placements, identical device
        books, and both stamp PUBLISH_ENQUEUE."""
        async def go():
            rng = random.Random(11)
            ident = Identity.generate("guest")
            actions = [make_action(f"p{i}", memory=rng.choice([128, 256]))
                       for i in range(5)]
            k = 24
            seq = [actions[rng.randrange(len(actions))] for _ in range(k)]

            async def run_serial():
                provider = MemoryMessagingProvider()
                bal = await _healthy_balancer(provider)
                bal.waterfall = ActivationWaterfall(WaterfallConfig())
                aids = []
                for a in seq:
                    msg = make_msg(a, ident)
                    aid = msg.activation_id.asString
                    ctx = bal.waterfall.begin(aid)
                    aids.append((aid, ctx))
                    await bal.publish(a, msg)
                await _drain(bal)
                out = (_placements(bal, [a for a, _ in aids]),
                       np.asarray(bal.state.free_mb).copy(),
                       [ctx[_CTX_BASE + STAGE_PUBLISH_ENQUEUE] != 0
                        for _, ctx in aids])
                await bal.close()
                return out

            async def run_batched():
                provider = MemoryMessagingProvider()
                bal = await _healthy_balancer(provider)
                assert bal.batch_publish
                bal.waterfall = ActivationWaterfall(WaterfallConfig())
                pairs, aids = [], []
                for a in seq:
                    msg = make_msg(a, ident)
                    aid = msg.activation_id.asString
                    aids.append((aid, bal.waterfall.begin(aid)))
                    pairs.append((a, msg))
                outs = bal.publish_many(pairs)
                await asyncio.gather(*outs)
                await _drain(bal)
                out = (_placements(bal, [a for a, _ in aids]),
                       np.asarray(bal.state.free_mb).copy(),
                       [ctx[_CTX_BASE + STAGE_PUBLISH_ENQUEUE] != 0
                        for _, ctx in aids])
                await bal.close()
                return out

            ser_dec, ser_books, ser_stamps = await run_serial()
            bat_dec, bat_books, bat_stamps = await run_batched()
            assert ser_dec == bat_dec
            assert np.array_equal(ser_books, bat_books)
            assert all(ser_stamps) and all(bat_stamps)

        asyncio.run(go())

    def test_exception_texts_match_serial(self):
        """standby / no-invoker refusals through publish_many carry the
        serial path's exact texts, per row."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action("t")
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider)
            try:
                bal.ha_standby = True
                with pytest.raises(LoadBalancerException) as e_serial:
                    await bal.publish(action, make_msg(action, ident))
                outs = bal.publish_many([(action, make_msg(action, ident))])
                with pytest.raises(LoadBalancerException) as e_batch:
                    await outs[0]
                assert str(e_serial.value) == str(e_batch.value)
                bal.ha_standby = False
            finally:
                await bal.close()

            # empty fleet: same no-invoker text both ways
            provider2 = MemoryMessagingProvider()
            bal2 = TpuBalancer(provider2, ControllerInstanceId("0"),
                               prewarm=False)
            try:
                with pytest.raises(LoadBalancerException) as s2:
                    await bal2.publish(action, make_msg(action, ident))
                outs = bal2.publish_many([(action, make_msg(action, ident))])
                with pytest.raises(LoadBalancerException) as b2:
                    await outs[0]
                assert str(s2.value) == str(b2.value)
            finally:
                await bal2.close()

        asyncio.run(go())

    def test_device_throttle_429_text(self):
        """Device rate admission rejections through publish_many raise
        LoadBalancerThrottleException with the serial path's text."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action("thr", memory=128)
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider,
                                          rate_limit_per_minute=2)
            try:
                pairs = [(action, make_msg(action, ident))
                         for _ in range(16)]
                outs = bal.publish_many(pairs)
                results = await asyncio.gather(*outs,
                                               return_exceptions=True)
                throttled = [r for r in results
                             if isinstance(r, LoadBalancerThrottleException)]
                assert throttled, "expected some device-throttled rows"
                assert str(throttled[0]) == ("Too many requests in the "
                                             "last minute (device rate "
                                             "admission).")
            finally:
                await bal.close()

        asyncio.run(go())

    def test_cancellation_returns_capacity_per_row(self):
        """Rows whose caller future is cancelled before placement give
        their reserved capacity back; surviving rows keep theirs."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action("c", memory=256)
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider, n_invokers=2)
            try:
                free0 = int(np.asarray(bal.state.free_mb).sum())
                pairs = [(action, make_msg(action, ident))
                         for _ in range(8)]
                outs = bal.publish_many(pairs)
                for out in outs[:4]:
                    out.cancel()
                results = await asyncio.gather(*outs,
                                               return_exceptions=True)
                assert sum(isinstance(r, asyncio.CancelledError)
                           for r in results) == 4
                await _drain(bal)
                free1 = int(np.asarray(bal.state.free_mb).sum())
                # only the 4 surviving placements hold memory
                assert free0 - free1 == 4 * 256
                # host slot refcounts balanced back to the survivors
                assert bal._slots.refcount.get(
                    f"{action.fully_qualified_name}:256") == 4
            finally:
                await bal.close()

        asyncio.run(go())

    def test_off_switch_serial_path(self):
        """batch_publish=False: publish_many degrades to the serial
        per-pair path (no finisher tasks), and maybe_batch_publish
        builds nothing."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action("o")
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider, batch_publish=False)
            try:
                assert maybe_batch_publish(bal) is None
                outs = bal.publish_many([(action, make_msg(action, ident))
                                         for _ in range(4)])
                await asyncio.gather(*outs)
                assert not bal._publish_finishers
                assert bal.total_active_activations == 4
            finally:
                await bal.close()

        asyncio.run(go())

    def test_cancelled_send_flush_cancels_caller(self):
        """A dispatch handed to the bus coalescer whose flush future is
        CANCELLED (drainer torn down with the send still queued) must
        cancel the caller — serial parity is the awaited send raising
        CancelledError, never success for an unsent dispatch."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action("sc")
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider, n_invokers=2)
            real = bal.producer
            try:
                sendfs = []

                class StubProducer:
                    def send_nowait(self, topic, msg):
                        f = asyncio.get_event_loop().create_future()
                        sendfs.append(f)
                        return f

                    def __getattr__(self, name):
                        return getattr(real, name)

                bal.producer = StubProducer()
                outs = bal.publish_many([(action, make_msg(action, ident))])
                for _ in range(200):
                    if sendfs:
                        break
                    await asyncio.sleep(0.02)
                assert sendfs, "dispatch never reached send_nowait"
                assert not outs[0].done()
                sendfs[0].cancel()
                await asyncio.sleep(0)
                with pytest.raises(asyncio.CancelledError):
                    await outs[0]
            finally:
                bal.producer = real
                await bal.close()

        asyncio.run(go())

    def test_failing_rows_skip_arrival_note(self):
        """Rows whose _build_row raises never reach the serial path's
        _note_arrival, so the batched shared clock read must count only
        BUILT rows — else a burst of failing rows decays the arrival
        EWMA (and the coalesce-window policy it feeds) where serial
        stays eager."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action("f")
            bad = make_action("bad")
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider, n_invokers=2)
            try:
                noted = []
                orig_note = bal._note_arrivals
                bal._note_arrivals = (
                    lambda t, n: (noted.append(n), orig_note(t, n))[1])
                real_build = bal._build_row

                def build(a, m):
                    if a is bad:
                        raise RuntimeError("boom")
                    return real_build(a, m)

                bal._build_row = build
                outs = bal.publish_many([(action, make_msg(action, ident)),
                                         (bad, make_msg(bad, ident)),
                                         (action, make_msg(action, ident))])
                with pytest.raises(RuntimeError):
                    await outs[1]
                await asyncio.gather(outs[0], outs[2])
                assert noted == [2]
                # an all-failing batch notes no arrivals at all
                outs2 = bal.publish_many([(bad, make_msg(bad, ident))])
                with pytest.raises(RuntimeError):
                    await outs2[0]
                assert noted == [2]
            finally:
                await bal.close()

        asyncio.run(go())

    def test_note_arrivals_n1_bit_exact(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider, n_invokers=1)
            try:
                bal._gap_ewma_ms = 123.456
                bal._last_pub_t = 10.0
                bal._last_gap_ms = 9.0
                a = (bal._gap_ewma_ms, bal._last_pub_t, bal._last_gap_ms)
                bal._note_arrivals(10.5, 1)
                n1 = (bal._gap_ewma_ms, bal._last_pub_t, bal._last_gap_ms)
                bal._gap_ewma_ms, bal._last_pub_t, bal._last_gap_ms = a
                bal._note_arrival(10.5)
                serial = (bal._gap_ewma_ms, bal._last_pub_t,
                          bal._last_gap_ms)
                assert n1 == serial
                # n>1: pure decay of the n=1 blend, zero last gap
                bal._gap_ewma_ms, bal._last_pub_t, bal._last_gap_ms = a
                bal._note_arrivals(10.5, 4)
                assert bal._last_gap_ms == 0.0
                assert bal._gap_ewma_ms == pytest.approx(
                    serial[0] * 0.9 ** 3)
            finally:
                await bal.close()

        asyncio.run(go())


class TestPublishCoalescer:
    def test_bridges_result_exception_and_cancel(self):
        """The front-door coalescer resolves waiters to publish_many's
        row outcomes without minting tasks, and cancellation flows back
        to the row future."""
        async def go():
            calls = []

            class FakeBal:
                batch_publish = True
                max_batch = 256

                def publish_many(self, pairs):
                    loop = asyncio.get_event_loop()
                    rows = [loop.create_future() for _ in pairs]
                    calls.append((pairs, rows))
                    return rows

            co = PublishCoalescer(FakeBal())
            w1 = co.submit("a", "m1")
            w2 = co.submit("a", "m2")
            w3 = co.submit("a", "m3")
            await asyncio.sleep(0)  # end-of-sweep flush
            assert len(calls) == 1 and len(calls[0][0]) == 3
            rows = calls[0][1]
            rows[0].set_result("promise")
            rows[1].set_exception(LoadBalancerException("nope"))
            w3.cancel()
            await asyncio.sleep(0)
            assert await w1 == "promise"
            with pytest.raises(LoadBalancerException):
                await w2
            assert rows[2].cancelled()

        asyncio.run(go())

    def test_full_batch_flushes_inline(self):
        async def go():
            flushed = []

            class FakeBal:
                batch_publish = True
                max_batch = 2

                def publish_many(self, pairs):
                    flushed.append(len(pairs))
                    loop = asyncio.get_event_loop()
                    rows = [loop.create_future() for _ in pairs]
                    for r in rows:
                        r.set_result(None)
                    return rows

            co = PublishCoalescer(FakeBal(), max_batch=2)
            co.submit("a", "m1")
            co.submit("a", "m2")  # fills the batch: flush NOW, no sweep
            assert flushed == [2]

        asyncio.run(go())


class TestLazyAckResults:
    def _acks(self, n=3):
        ident = Identity.generate("guest")
        inv = InvokerInstanceId(1, user_memory=MB(1024))
        now = time.time()
        acks = []
        for i in range(n):
            act = WhiskActivation(
                EntityPath("guest"), EntityName(f"a{i}"), ident.subject,
                ActivationId.generate(), now, now,
                ActivationResponse.success({"i": i}), duration=1)
            acks.append(CombinedCompletionAndResultMessage(
                TransactionId(), act, inv))
        acks.append(CompletionMessage(TransactionId(),
                                      ActivationId.generate(), False, inv))
        acks.append(ResultMessage(TransactionId(), WhiskActivation(
            EntityPath("guest"), EntityName("r"), ident.subject,
            ActivationId.generate(), now, now,
            ActivationResponse.success({"r": 1}), duration=2)))
        return acks

    def test_lazy_frame_roundtrip_all_kinds(self):
        acks = self._acks()
        plain = AckBatchMessage(acks).serialize()
        lazy = AckBatchMessage(acks, lazy_results=True).serialize()
        assert is_batch_payload(plain) and is_batch_payload(lazy)
        assert b"\n" not in plain and b"\n" in lazy
        k1, out1 = parse_batch(plain)
        k2, out2 = parse_batch(lazy)
        assert (k1, k2) == (KIND_ACK, KIND_ACK_LAZY)
        for a, b in zip(out1, out2):
            assert a.kind == b.kind
            assert a.activation_id.asString == b.activation_id.asString
            assert a.is_system_error == b.is_system_error
            assert (a.invoker is None) == (b.invoker is None)
            if a.activation is None:
                assert b.activation is None
                continue
            assert isinstance(b.activation, LazyWhiskActivation)
            assert not b.activation.materialized
            # materializing yields the same activation (modulo the
            # `updated` stamp minted fresh at every to_json call)
            ja = dict(a.activation.to_json())
            jb = dict(b.activation.to_json())
            ja.pop("updated", None)
            jb.pop("updated", None)
            assert ja == jb
            assert b.activation.materialized

    def test_lazy_relay_passes_raw_bytes_through(self):
        """Re-encoding an unread lazy ack reuses the raw payload — no
        parse, no re-serialize."""
        acks = self._acks(2)
        lazy = AckBatchMessage(acks, lazy_results=True).serialize()
        _k, out = parse_batch(lazy)
        relay = AckBatchMessage(out, lazy_results=True).serialize()
        _k2, out2 = parse_batch(relay)
        for a, b in zip(out, out2):
            assert not (a.activation is not None
                        and a.activation.materialized)
            if a.activation is not None:
                assert b.activation.raw == a.activation.raw

    def test_off_switch_byte_exact(self):
        """lazy_results=False serializes exactly the PR 11 record."""
        acks = self._acks(2)
        msg = AckBatchMessage(acks)
        assert not msg.lazy_results
        assert msg.serialize() == json.dumps(
            msg.to_json(), separators=(",", ":")).encode()

    def test_corrupt_lazy_body_rejected(self):
        acks = self._acks(2)
        lazy = AckBatchMessage(acks, lazy_results=True).serialize()
        with pytest.raises(ValueError):
            parse_batch(lazy[:-3])  # truncated body != respLen sum

    def test_corrupt_body_behind_consistent_frame(self):
        """A garbled response payload behind a CONSISTENT frame (header
        and per-row lengths intact) decodes fine and only fails on the
        consumer's first read — which must be the well-defined
        'corrupt lazy ack result' ValueError, not a JSONDecodeError
        escaping deep inside response rendering."""
        acks = self._acks(2)
        lazy = AckBatchMessage(acks, lazy_results=True).serialize()
        header, _, body = lazy.partition(b"\n")
        garbled = header + b"\n" + b"\x00" * len(body)
        _k, out = parse_batch(garbled)  # frame-level decode succeeds
        bad = next(a.activation for a in out if a.activation is not None)
        assert isinstance(bad, LazyWhiskActivation)
        assert not bad.materialized
        with pytest.raises(ValueError, match="corrupt lazy ack result"):
            _ = bad.response

    def test_consumer_never_reads_skips_parse(self):
        """The acceptance counter check: a lazy ack frame processed by
        the balancer's completion path books ZERO `ack_result`
        deserializes until a consumer touches the result — then exactly
        the touched rows parse."""
        async def go():
            ident = Identity.generate("guest")
            action = make_action("z", memory=128)
            provider = MemoryMessagingProvider()
            bal = await _healthy_balancer(provider, n_invokers=2)
            was_enabled = GLOBAL_HOST_OBSERVATORY.enabled
            GLOBAL_HOST_OBSERVATORY.enabled = True
            try:
                GLOBAL_HOST_OBSERVATORY.reset()
                inv = InvokerInstanceId(0, user_memory=MB(4096))
                msgs, promises = [], []
                for i in range(4):
                    msg = make_msg(action, ident, blocking=True)
                    msgs.append(msg)
                    promises.append(bal.setup_activation(msg, action, inv))
                now = time.time()
                acks = [CombinedCompletionAndResultMessage(
                    m.transid,
                    WhiskActivation(EntityPath("guest"), EntityName("z"),
                                    ident.subject, m.activation_id, now,
                                    now,
                                    ActivationResponse.success({"ok": 1}),
                                    duration=1),
                    inv) for m in msgs]
                payload = AckBatchMessage(
                    acks, lazy_results=True).serialize()
                bal.process_acknowledgement_frame(payload)

                def ack_result_count():
                    snap = GLOBAL_HOST_OBSERVATORY.snapshot()
                    return sum(row["count"] for row in snap["serde"]
                               if row["hop"] == "ack_result"
                               and row["direction"] == "deserialize")

                # every promise resolved, nothing parsed
                results = [p.result() for p in promises]
                assert all(isinstance(r, LazyWhiskActivation)
                           for r in results)
                assert ack_result_count() == 0
                # one consumer reads its result -> exactly one parse
                assert results[0].response.status_code == 0
                assert ack_result_count() == 1
            finally:
                GLOBAL_HOST_OBSERVATORY.enabled = was_enabled
                GLOBAL_HOST_OBSERVATORY.reset()
                await bal.close()

        asyncio.run(go())

    def test_coalescing_producer_ships_lazy_frames(self):
        """End to end through the CoalescingProducer: two acks to one
        topic flush as ONE lazy frame; lazy_results=False ships the
        plain columnar record."""
        async def go():
            for lazy in (True, False):
                provider = MemoryMessagingProvider()
                provider.ensure_topic("completed0")
                consumer = provider.get_consumer("completed0", "g0")
                prod = CoalescingProducer(provider.get_producer(),
                                          batch_wire=True,
                                          lazy_results=lazy)
                await prod.send_batch("completed0", self._acks(2)[:2])
                await prod.flush()
                got = await consumer.peek(8, timeout=1.0)
                assert len(got) == 1
                payload = got[0][3]
                assert is_batch_payload(payload)
                assert (b"\n" in bytes(payload)) == lazy
                kind, out = parse_batch(payload)
                assert kind == (KIND_ACK_LAZY if lazy else KIND_ACK)
                assert len(out) == 2
                await prod.close()

        asyncio.run(go())


class TestColumnRingPushBlock:
    def test_push_block_equals_pushes(self):
        rng = np.random.RandomState(3)
        for trial in range(20):
            a = ColumnRing(4, 8)
            b = ColumnRing(4, 8)
            # interleave singles, blocks, and pops to exercise wrap+grow
            for step in range(rng.randint(1, 8)):
                k = rng.randint(1, 13)
                block = rng.randint(0, 1000, size=(4, k)).astype(np.int32)
                for j in range(k):
                    a.push(block[:, j])
                b.push_block(block)
                assert len(a) == len(b)
                if rng.rand() < 0.5 and len(a):
                    n = rng.randint(1, len(a) + 1)
                    oa = np.zeros((4, n), np.int32)
                    ob = np.zeros((4, n), np.int32)
                    a.pop_into(oa, n)
                    b.pop_into(ob, n)
                    assert np.array_equal(oa, ob)
            n = len(a)
            if n:
                oa = np.zeros((4, n), np.int32)
                ob = np.zeros((4, n), np.int32)
                a.pop_into(oa, n)
                b.pop_into(ob, n)
                assert np.array_equal(oa, ob)

"""Runtime-contract tests: the /init + /run HTTP contract of action
sandboxes, driven directly against the action proxy as a real subprocess —
the reference's tests/.../actionContainers suite (ActionProxyContainerTests,
PythonActionContainerTests) for this framework's runtime image equivalent.
"""
import base64
import io
import json
import os
import socket
import subprocess
import sys
import time
import zipfile

import aiohttp
import asyncio
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROXY = os.path.join(REPO, "openwhisk_tpu", "containerpool", "actionproxy.py")
SENTINEL = "XXX_THE_END_OF_A_WHISK_ACTIVATION_XXX"


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def proxy():
    port = _free_port()
    proc = subprocess.Popen([sys.executable, "-u", PROXY, str(port)],
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with socket.socket() as s:
            s.settimeout(0.5)
            try:
                s.connect(("127.0.0.1", port))
                break
            except OSError:
                time.sleep(0.1)
    else:
        proc.kill()
        raise AssertionError("proxy never started")
    yield f"http://127.0.0.1:{port}", proc
    proc.kill()
    proc.wait(timeout=5)


def _post(base, path, payload):
    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.post(base + path, json=payload) as r:
                return r.status, await r.json(content_type=None)
    return asyncio.run(go())


class TestInitRunContract:
    def test_init_then_run(self, proxy):
        base, _ = proxy
        st, body = _post(base, "/init", {"value": {
            "code": "def main(args):\n    return {'echo': args.get('x')}\n"}})
        assert (st, body) == (200, {"ok": True})
        st, body = _post(base, "/run", {"value": {"x": 42}})
        assert (st, body) == (200, {"echo": 42})

    def test_run_before_init_fails(self, proxy):
        base, _ = proxy
        st, body = _post(base, "/run", {"value": {}})
        assert st == 502 and "uninitialized" in body["error"]

    def test_init_broken_code_reports_error(self, proxy):
        base, _ = proxy
        st, body = _post(base, "/init", {"value": {"code": "def main(:\n"}})
        assert st == 502 and "Initialization has failed" in body["error"]

    def test_custom_main(self, proxy):
        base, _ = proxy
        st, _ = _post(base, "/init", {"value": {
            "code": "def other(args):\n    return {'via': 'other'}\n",
            "main": "other"}})
        assert st == 200
        st, body = _post(base, "/run", {"value": {}})
        assert body == {"via": "other"}

    def test_env_and_activation_context(self, proxy):
        base, _ = proxy
        code = ("import os\n"
                "def main(args):\n"
                "    return {'key': os.environ.get('SECRET'),\n"
                "            'ns': os.environ.get('__OW_NAMESPACE')}\n")
        st, _ = _post(base, "/init",
                      {"value": {"code": code, "env": {"SECRET": "s3cr3t"}}})
        assert st == 200
        st, body = _post(base, "/run", {"value": {}, "namespace": "guest"})
        assert body == {"key": "s3cr3t", "ns": "guest"}

    def test_log_sentinel_framing(self, proxy):
        base, proc = proxy
        _post(base, "/init", {"value": {
            "code": "def main(args):\n    print('hello log')\n    return {}\n"}})
        _post(base, "/run", {"value": {}})
        time.sleep(0.3)
        proc.kill()
        out = proc.stdout.read().decode()
        assert "hello log" in out
        assert out.count(SENTINEL) >= 1
        assert out.index("hello log") < out.index(SENTINEL)

    def test_non_dict_result_is_error(self, proxy):
        base, _ = proxy
        _post(base, "/init", {"value": {
            "code": "def main(args):\n    return 'not a dict'\n"}})
        st, body = _post(base, "/run", {"value": {}})
        assert st == 502
        assert "error" in body


class TestBinaryActions:
    def _zip_b64(self, files: dict) -> str:
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            for name, content in files.items():
                z.writestr(name, content)
        return base64.b64encode(buf.getvalue()).decode()

    def test_binary_zip_with_package(self, proxy):
        base, _ = proxy
        code = self._zip_b64({
            "__main__.py": "from helpers.lib import greet\n"
                           "def main(args):\n"
                           "    return {'msg': greet(args.get('who', 'zip'))}\n",
            "helpers/__init__.py": "",
            "helpers/lib.py": "def greet(w):\n    return 'hi ' + w\n",
        })
        st, body = _post(base, "/init",
                         {"value": {"code": code, "binary": True}})
        assert (st, body) == (200, {"ok": True}), body
        st, body = _post(base, "/run", {"value": {"who": "pkg"}})
        assert (st, body) == (200, {"msg": "hi pkg"})

    def test_binary_zip_without_main_fails(self, proxy):
        base, _ = proxy
        code = self._zip_b64({"other.py": "x = 1\n"})
        st, body = _post(base, "/init",
                         {"value": {"code": code, "binary": True}})
        assert st == 502 and "__main__.py" in body["error"]

    def test_zip_path_traversal_rejected(self, proxy):
        base, _ = proxy
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            z.writestr("../evil.py", "x = 1")
            z.writestr("__main__.py", "def main(a):\n    return {}\n")
        code = base64.b64encode(buf.getvalue()).decode()
        st, body = _post(base, "/init",
                         {"value": {"code": code, "binary": True}})
        assert st == 502 and "escapes" in body["error"]


def test_init_gate_waits_for_inflight_runs_and_blocks_new_ones():
    """ThreadingHTTPServer serves /run concurrently; a re-init must drain
    in-flight runs before evicting the old zip, and block new runs until
    the new code is installed."""
    import threading
    import time

    from openwhisk_tpu.containerpool.actionproxy import _InitRunGate

    gate = _InitRunGate()
    order = []

    def runner():
        gate.begin_run()
        order.append("run-start")
        time.sleep(0.15)
        order.append("run-end")
        gate.end_run()

    def initer():
        time.sleep(0.05)  # let the run start first
        gate.begin_init()
        order.append("init-start")
        time.sleep(0.05)
        order.append("init-end")
        gate.end_init()

    def late_runner():
        time.sleep(0.1)  # arrives while init is waiting/active
        gate.begin_run()
        order.append("late-run")
        gate.end_run()

    threads = [threading.Thread(target=f)
               for f in (runner, initer, late_runner)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert order.index("run-end") < order.index("init-start")
    assert order.index("init-end") < order.index("late-run")

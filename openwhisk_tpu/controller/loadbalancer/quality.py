"""Placement quality plane: regret, imbalance, shadow counterfactuals.

The fifth observability plane. Telemetry (PR 2) measures *realized*
latency and anomaly (PR 4) scores *invokers* — but nothing measures
whether the placement kernel's DECISIONS are good, which makes turning
the anomaly feedback into placement (ROADMAP item 4) a leap of faith.
This plane scores every committed micro-batch on device
(ops/decision_quality.py) against the predictive signals the balancer
already holds — per-invoker latency EWMAs from the anomaly plane and the
post-commit capacity books — and, every K batches, runs the
anomaly-penalty-augmented probe geometry as a decision-only SHADOW pass
over the same inputs and diffs it against production. The result is the
A/B evidence item 4's follow-up needs: how much predicted latency the
current geometry leaves on the table (regret), and how differently the
penalized geometry would have placed (divergence).

Wiring mirrors the other planes (base-class hook):
  * TPU balancer: `use_device()` allocates the device `QualityState` and
    the jitted step; the balancer dispatches the scorer right after the
    production step on its readback cadence (TELEMETRY_FOLD_MIN
    discipline — never a device sync on the API path) and feeds the
    per-batch summary row back through `note_summary()` from the
    readback worker.
  * CPU balancers (sharding, lean): `observe_decision()` rides the
    `record_placement` hook — attribution counters only, since those
    balancers hold no post-commit books or EWMAs at that point
    (documented scope: regret/imbalance are device-path signals).

Read sides: three `/metrics` families
(`openwhisk_loadbalancer_placement_regret` histogram on the telemetry
bucket grid, `openwhisk_loadbalancer_decision_divergence_total` per
invoker, `openwhisk_loadbalancer_fleet_imbalance` gauge), the auth-gated
`GET /admin/placement/quality` report, and a `raw_counts()` export the
fleet federation merges bucket-wise bit-exactly (ISSUE 16 pattern).

Off-switch: `CONFIG_whisk_placementQuality_enabled` (default OFF — the
plane exists to gate item 4, it must not tax fleets that have not opted
in); `CONFIG_whisk_placementQuality_shadowEveryN` sets the shadow
cadence (0 keeps regret scoring on with no shadow pass). Disabled is a
true no-op: nothing allocates, every entry point returns immediately,
and production decisions are bit-exact either way — the shadow pass
never writes the live books (parity-asserted in tests).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

import numpy as np

from ...ops.decision_quality import (C_FORCED, C_PLACED, C_ROWS,
                                     C_THROTTLED, C_UNPLACED, COUNTERS,
                                     N_SUMMARY, S_IMBALANCE_COV, S_ROWS,
                                     QualityState, init_quality_state,
                                     make_quality_step)
from ...ops.telemetry import DEFAULT_BUCKETS, bucket_bounds_ms
from ...utils.config import load_config
from ...utils.eventlog import identity


@dataclass(frozen=True)
class QualityConfig:
    """`CONFIG_whisk_placementQuality_*` env overrides."""
    #: default OFF: this plane gates the item-4 rollout, it is opt-in
    enabled: bool = False
    #: shadow counterfactual cadence (every N micro-batches; 0 = regret
    #: scoring only, no shadow pass)
    shadow_every_n: int = 16
    #: regret histogram buckets (telemetry log2 grid, so the fleet
    #: federation can merge regret and latency histograms the same way)
    buckets: int = DEFAULT_BUCKETS


class QualityPlane:
    """One per balancer (base-class hook, like the other four planes)."""

    def __init__(self, config: Optional[QualityConfig] = None):
        self.config = config or QualityConfig()
        self.enabled = self.config.enabled
        self.n_buckets = max(2, int(self.config.buckets))
        # attached collaborators (base-class wiring)
        self._anomaly = None
        self._names_fn: Optional[Callable[[], List[str]]] = None
        # accumulator state: allocated lazily (disabled allocates nothing)
        self._qstate: Optional[QualityState] = None
        self._kernel = "cpu"
        self._step = None
        #: host aggregates fed by note_summary() from readback workers
        #: while tick()/reports read on other threads — tiny critical
        #: sections under one lock, never a device handle inside
        self._lock = threading.Lock()
        self._batches = 0
        self._shadow_batches = 0
        self._rows_total = 0
        self._regret_sum_ms = 0.0
        self._divergent_total = 0
        self._shadow_rows_total = 0
        self._last_imbalance = 0.0
        self._last_summary: Optional[List[float]] = None
        self._last_tick = 0.0

    @classmethod
    def from_config(cls) -> "QualityPlane":
        return cls(config=load_config(QualityConfig,
                                      env_path="placement_quality"))

    def attach(self, anomaly=None,
               invoker_names: Optional[Callable[[], List[str]]] = None
               ) -> None:
        """Wire the plane to its collaborators (called by the balancer
        base class; harmless when disabled — nothing allocates)."""
        self._anomaly = anomaly
        self._names_fn = invoker_names

    @property
    def SYNCS_DEVICE(self) -> bool:
        """True when reading the accumulated state forces a device->host
        sync (report callers then use a worker thread, like /admin/slo)."""
        return self._kernel == "device"

    @property
    def shadow_every_n(self) -> int:
        return max(0, int(self.config.shadow_every_n))

    # -- device path (TPU balancer) ---------------------------------------
    def use_device(self, n_pad: int, transposed: bool = False) -> None:
        """Allocate the device QualityState and build the jitted step.
        `transposed` follows the resolved kernel's conc layout ([A, N]
        for the Pallas kernels, [N, A] for XLA/sharded)."""
        if not self.enabled:
            return
        self._qstate = init_quality_state(max(1, n_pad), self.n_buckets)
        self._step = make_quality_step(self.n_buckets, transposed=transposed)
        self._kernel = "device"

    def device_step(self, free_post, conc_post, health, ewma_ms, cap_mb,
                    req, out_vec, shadow_vec=None):
        """Dispatch one scoring step on the balancer's dispatch thread
        (async — reads possibly-in-flight device buffers, writes only the
        plane's own state). Returns the summary device array; the caller
        hands it to the readback worker, which resolves it alongside the
        books it already pulls and calls note_summary()."""
        if not self.enabled or self._step is None:
            return None
        self._qstate, summary = self._step(
            self._qstate, free_post, conc_post, health, ewma_ms, cap_mb,
            req, out_vec, shadow_vec)
        return summary

    def note_summary(self, summary) -> None:
        """Fold one resolved per-batch summary row into the host
        aggregates (readback worker thread; `summary` is host numpy)."""
        if not self.enabled or summary is None:
            return
        s = np.asarray(summary, np.float32)
        if s.shape[0] < N_SUMMARY:
            return
        from ...ops.decision_quality import (S_DIVERGENT, S_REGRET_SUM_MS,
                                             S_SHADOW_ROWS)
        with self._lock:
            self._batches += 1
            self._rows_total += int(s[S_ROWS])
            self._regret_sum_ms += float(s[S_REGRET_SUM_MS])
            self._last_imbalance = float(s[S_IMBALANCE_COV])
            self._last_summary = [round(float(v), 6) for v in s]
            if s[S_SHADOW_ROWS] > 0:
                self._shadow_batches += 1
                self._shadow_rows_total += int(s[S_SHADOW_ROWS])
                self._divergent_total += int(s[S_DIVERGENT])

    # -- CPU path (record_placement hook) ---------------------------------
    def observe_decision(self, placed: bool, forced: bool,
                         throttled: bool) -> None:
        """Attribution counters for the CPU balancers (no books or EWMAs
        at the hook, so no regret — documented scope)."""
        if not self.enabled or self._kernel != "cpu":
            return
        if self._qstate is None:
            self._qstate = init_quality_state(1, self.n_buckets, numpy=True)
        ctr = self._qstate.counters
        ctr[C_ROWS] += 1
        if throttled:
            ctr[C_THROTTLED] += 1
        elif placed:
            ctr[C_PLACED] += 1
            if forced:
                ctr[C_FORCED] += 1
        else:
            ctr[C_UNPLACED] += 1

    # -- supervision tick (host aggregates only, never a device sync) -----
    def tick(self, metrics=None, now: Optional[float] = None) -> dict:
        if not self.enabled:
            return {}
        self._last_tick = time.monotonic() if now is None else now
        with self._lock:
            vals = {
                "placement_quality_batches": self._batches,
                "placement_fleet_imbalance": round(self._last_imbalance, 4),
                "placement_shadow_divergence_ratio": round(
                    self._divergent_total
                    / max(1, self._shadow_rows_total), 6),
            }
        if metrics is not None:
            for k, v in vals.items():
                metrics.gauge(f"loadbalancer_{k}", v)
        return vals

    def maybe_tick(self, metrics=None) -> None:
        """Rate-limited tick for balancers without a supervision
        scheduler (lean): freshness rides the completion stream."""
        if self.enabled and time.monotonic() - self._last_tick >= 1.0:
            self.tick(metrics)

    # -- read side ---------------------------------------------------------
    def counts(self) -> Optional[dict]:
        """Accumulated arrays as host numpy (device sync on the TPU path
        — cold path only; callers off the event loop when SYNCS_DEVICE)."""
        qs = self._qstate
        if not self.enabled or qs is None:
            return None
        return {
            "regret_hist": np.asarray(qs.regret_hist, np.int64),
            "counters": np.asarray(qs.counters, np.int64),
            "inv_regret_ms": np.asarray(qs.inv_regret_ms, np.float64),
            "inv_divergence": np.asarray(qs.inv_divergence, np.int64),
        }

    def bounds_ms(self) -> List[float]:
        return bucket_bounds_ms(self.n_buckets)

    def prometheus_text(self, invoker_names: Optional[List[str]] = None,
                        openmetrics: bool = False) -> str:
        """The three quality families (rendering in monitoring.py). Reads
        the state reference once — the dispatch thread replaces it
        wholesale, never mutates it in place (device path)."""
        if not self.enabled:
            return ""
        from ..monitoring import (counter_family_text, gauge_family_text,
                                  histogram_family_text)
        c = self.counts()
        out: List[str] = []
        if c is not None and int(c["regret_hist"].sum()) > 0:
            out += histogram_family_text(
                "openwhisk_loadbalancer_placement_regret", "scope",
                [("fleet", c["regret_hist"],
                  float(c["inv_regret_ms"].sum()))],
                self.bounds_ms())
        if c is not None:
            names = invoker_names or []

            def inv_name(i: int) -> str:
                return names[i] if i < len(names) else f"invoker{i}"

            out += counter_family_text(
                "openwhisk_loadbalancer_decision_divergence_total",
                [({"invoker": inv_name(i)}, int(v))
                 for i, v in enumerate(c["inv_divergence"]) if v > 0],
                openmetrics=openmetrics)
        with self._lock:
            imb = self._last_imbalance
        out += gauge_family_text(
            "openwhisk_loadbalancer_fleet_imbalance",
            [({"scope": "fleet"}, round(imb, 6))])
        return "\n".join(out)

    def quality_report(self, invoker_names: Optional[List[str]] = None
                       ) -> dict:
        """The `GET /admin/placement/quality` payload. A device sync on
        the TPU path — callers run it on a worker thread (SYNCS_DEVICE)."""
        if not self.enabled:
            return {"enabled": False}
        from ..monitoring import _pctl_from_hist
        c = self.counts()
        names = invoker_names or []
        with self._lock:
            host = {
                "batches": self._batches,
                "shadow_batches": self._shadow_batches,
                "rows": self._rows_total,
                "regret_sum_ms": round(self._regret_sum_ms, 3),
                "divergent_rows": self._divergent_total,
                "shadow_rows": self._shadow_rows_total,
                "divergence_ratio": round(
                    self._divergent_total / max(1, self._shadow_rows_total),
                    6),
                "fleet_imbalance_cov": round(self._last_imbalance, 6),
                "last_batch": self._last_summary,
            }
        report = {
            "enabled": True,
            "kernel": self._kernel,
            "config": {"shadow_every_n": self.shadow_every_n,
                       "buckets": self.n_buckets},
            "buckets_le_ms": self.bounds_ms(),
            **host,
        }
        if c is not None:
            bounds = self.bounds_ms()
            hist = c["regret_hist"]
            bi = _pctl_from_hist([int(v) for v in hist], 0.99)
            report["regret_hist"] = [int(v) for v in hist]
            report["regret_p99_le_ms"] = (bounds[bi] if bi < len(bounds)
                                          else None)  # None: +Inf bucket
            report["counters"] = {name: int(c["counters"][i])
                                  for i, name in enumerate(COUNTERS)}
            invokers = []
            for i in range(c["inv_regret_ms"].shape[0]):
                reg = float(c["inv_regret_ms"][i])
                div = int(c["inv_divergence"][i])
                if reg <= 0.0 and div <= 0:
                    continue
                invokers.append({
                    "invoker": (names[i] if i < len(names)
                                else f"invoker{i}"),
                    "regret_ms": round(reg, 3),
                    "divergent_rows": div,
                })
            report["invokers"] = invokers
        return report

    def raw_counts(self, invoker_names: Optional[List[str]] = None) -> dict:
        """The exact-merge export behind `/admin/placement/quality?raw=1`
        (ISSUE 16 pattern): histogram + counters merge positionally,
        per-invoker series by LABEL. Shares counts()'s device-sync caveat."""
        if not self.enabled:
            return {"enabled": False}
        c = self.counts()
        names = invoker_names or []
        invokers = {}
        if c is not None:
            for i in range(c["inv_regret_ms"].shape[0]):
                reg = float(c["inv_regret_ms"][i])
                div = int(c["inv_divergence"][i])
                if reg <= 0.0 and div <= 0:
                    continue
                name = names[i] if i < len(names) else f"invoker{i}"
                invokers[name] = {"regret_ms": reg, "divergence": div}
        with self._lock:
            host = {
                "batches": self._batches,
                "shadow_batches": self._shadow_batches,
                "divergent_rows": self._divergent_total,
                "shadow_rows": self._shadow_rows_total,
                "regret_sum_ms": float(self._regret_sum_ms),
                "fleet_imbalance_cov": float(self._last_imbalance),
            }
        return {
            "identity": identity(),
            "enabled": True,
            "kernel": self._kernel,
            "buckets": self.n_buckets,
            "regret_hist": ([int(v) for v in c["regret_hist"]]
                            if c is not None else [0] * self.n_buckets),
            "counters": ([int(v) for v in c["counters"]]
                         if c is not None else [0] * len(COUNTERS)),
            "counter_names": list(COUNTERS),
            "invokers": invokers,
            **host,
        }

"""Networked ArtifactStore tests: DocStoreServer + RemoteArtifactStore
(the CouchDbRestStore-equivalent seam; ref ArtifactStore.scala:41-150).

Multi-host semantics the shared-sqlite-file deployment could not provide:
distinct processes (here: distinct clients) sharing one revisioned
document database over TCP."""
import asyncio

import pytest

from openwhisk_tpu.core.entity import (CodeExec, EntityName, EntityPath,
                                       Identity, WhiskAction, WhiskAuthRecord)
from openwhisk_tpu.database import (AuthStore, DocStoreServer, DocumentConflict,
                                    EntityStore, MemoryArtifactStore,
                                    NoDocumentException, RemoteArtifactStore,
                                    SqliteArtifactStore, open_store)
from openwhisk_tpu.messaging.tcp import _frame, _read_frame


def run(coro):
    return asyncio.run(coro)


async def _server(backing=None, port: int = 0):
    srv = DocStoreServer(backing or MemoryArtifactStore(), port=port)
    await srv.start()
    return srv, srv._server.sockets[0].getsockname()[1]


DOC = {"entityType": "actions", "namespace": "ns", "name": "a", "updated": 1}


class TestSharedStoreAcrossClients:
    def test_two_controllers_share_entities_and_conflicts(self):
        """Client B sees client A's writes; stale-rev updates lose with
        DocumentConflict exactly as on a local store."""
        async def go():
            srv, port = await _server()
            a = RemoteArtifactStore("127.0.0.1", port)
            b = RemoteArtifactStore("127.0.0.1", port)
            rev1 = await a.put("ns/a", DOC)
            got = await b.get("ns/a")
            assert got["_rev"] == rev1
            rev2 = await b.put("ns/a", dict(DOC, updated=2), rev=rev1)
            with pytest.raises(DocumentConflict):
                await a.put("ns/a", dict(DOC, updated=3), rev=rev1)
            assert (await a.get("ns/a"))["_rev"] == rev2
            with pytest.raises(NoDocumentException):
                await b.get("ns/missing")
            await a.close(); await b.close(); await srv.stop()
        run(go())

    def test_typed_entity_and_auth_stores_over_remote(self):
        """The typed stores (EntityStore/AuthStore) run unchanged over the
        remote client — the controller boot path for multi-host mode."""
        async def go():
            srv, port = await _server()
            writer = EntityStore(RemoteArtifactStore("127.0.0.1", port))
            reader = EntityStore(RemoteArtifactStore("127.0.0.1", port))
            act = WhiskAction(EntityPath("ns"), EntityName("act"),
                              CodeExec(kind="python:3", code="def main(a): return a"))
            await writer.put(act)
            got = await reader.get(WhiskAction, "ns/act", use_cache=False)
            assert got.exec.code == act.exec.code

            auth = AuthStore(RemoteArtifactStore("127.0.0.1", port))
            ident = Identity.generate("shared-ns")
            await auth.put(WhiskAuthRecord(ident.subject, [ident.namespace],
                                           [ident.authkey]))
            found = await auth.identity_by_key(ident.authkey.uuid.asString,
                                               ident.authkey.key.asString)
            assert found is not None
            assert str(found.namespace.name) == "shared-ns"
            await srv.stop()
        run(go())

    def test_attachments_round_trip(self):
        async def go():
            srv, port = await _server()
            st = RemoteArtifactStore("127.0.0.1", port)
            await st.put("ns/a", DOC)
            blob = bytes(range(256)) * 64
            await st.attach("ns/a", "code", "application/octet-stream", blob)
            ct, data = await st.read_attachment("ns/a", "code")
            assert ct == "application/octet-stream" and data == blob
            await st.delete_attachments("ns/a")
            with pytest.raises(NoDocumentException):
                await st.read_attachment("ns/a", "code")
            await st.close(); await srv.stop()
        run(go())


class TestEffectivelyOnceMutations:
    def test_retried_put_frame_does_not_double_bump_revision(self):
        """A put whose response frame was lost is retried with the same rid;
        the server must replay the recorded response, not apply twice."""
        async def go():
            srv, port = await _server()
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            req = {"op": "put", "rid": "fixed-rid", "id": "ns/a", "doc": DOC,
                   "rev": None}
            writer.write(_frame(req)); await writer.drain()
            first = await _read_frame(reader)
            writer.write(_frame(req)); await writer.drain()  # the "retry"
            second = await _read_frame(reader)
            assert first == second
            check = RemoteArtifactStore("127.0.0.1", port)
            assert (await check.get("ns/a"))["_rev"] == first["rev"]
            writer.close(); await check.close(); await srv.stop()
        run(go())


class TestDurabilityAndResolution:
    def test_documents_survive_server_restart(self, tmp_path):
        path = str(tmp_path / "whisks.db")

        async def write():
            srv, port = await _server(SqliteArtifactStore(path))
            st = RemoteArtifactStore("127.0.0.1", port)
            rev = await st.put("ns/a", DOC)
            await st.close(); await srv.stop()
            return rev

        async def read():
            srv, port = await _server(SqliteArtifactStore(path))
            st = RemoteArtifactStore("127.0.0.1", port)
            doc = await st.get("ns/a")
            await st.close(); await srv.stop()
            return doc

        rev = run(write())
        assert run(read())["_rev"] == rev

    def test_open_store_url_resolution(self, tmp_path):
        st = open_store("docstore://10.0.0.5:4223")
        assert isinstance(st, RemoteArtifactStore)
        assert (st.host, st.port) == ("10.0.0.5", 4223)
        st2 = open_store(str(tmp_path / "local.db"))
        assert isinstance(st2, SqliteArtifactStore)

    def test_concurrent_clients_hammer_one_counter(self):
        """N concurrent writers CAS-update one document; revision semantics
        must serialize them into exactly N successful bumps."""
        async def go():
            srv, port = await _server()
            async def bump(st):
                while True:
                    doc = await st.get("ns/ctr")
                    body = {k: v for k, v in doc.items()
                            if not k.startswith("_")}
                    body["n"] = body.get("n", 0) + 1
                    try:
                        await st.put("ns/ctr", body, rev=doc["_rev"])
                        return
                    except DocumentConflict:
                        await asyncio.sleep(0)
            seed = RemoteArtifactStore("127.0.0.1", port)
            await seed.put("ns/ctr", dict(DOC, name="ctr", n=0))
            clients = [RemoteArtifactStore("127.0.0.1", port) for _ in range(8)]
            await asyncio.gather(*[bump(c) for c in clients])
            final = await seed.get("ns/ctr")
            for c in clients:
                await c.close()
            await seed.close(); await srv.stop()
            return final["n"], final["_rev"]
        n, rev = run(go())
        assert n == 8
        assert rev.startswith("9-")  # 1 seed + 8 bumps


class TestRestartRetryAmbiguity:
    def test_retried_put_conflict_resolves_when_own_write_landed(self):
        """Server restart eats the rid cache: a retried put that actually
        applied comes back as a conflict — the client must recognize its
        own stored body and return the committed revision."""
        async def go():
            st = RemoteArtifactStore("127.0.0.1", 1)  # never dialed

            async def fake_request(obj):
                if obj["op"] == "put":
                    exc = DocumentConflict("conflict")
                    exc.retried = True
                    raise exc
                assert obj["op"] == "get"
                return {"doc": dict(DOC, _id="ns/a", _rev="1-abc")}

            st._request = fake_request
            assert await st.put("ns/a", dict(DOC)) == "1-abc"
        run(go())

    def test_retried_put_conflict_with_foreign_body_still_raises(self):
        async def go():
            st = RemoteArtifactStore("127.0.0.1", 1)

            async def fake_request(obj):
                if obj["op"] == "put":
                    exc = DocumentConflict("conflict")
                    exc.retried = True
                    raise exc
                return {"doc": dict(DOC, updated=999, _id="ns/a",
                                    _rev="2-other")}

            st._request = fake_request
            with pytest.raises(DocumentConflict):
                await st.put("ns/a", dict(DOC))
        run(go())

    def test_unretried_conflict_never_second_guessed(self):
        async def go():
            st = RemoteArtifactStore("127.0.0.1", 1)

            async def fake_request(obj):
                exc = DocumentConflict("conflict")
                exc.retried = False
                raise exc

            st._request = fake_request
            with pytest.raises(DocumentConflict):
                await st.put("ns/a", dict(DOC))
        run(go())

    def test_retried_delete_no_document_treated_as_applied(self):
        async def go():
            st = RemoteArtifactStore("127.0.0.1", 1)

            async def fake_request(obj):
                exc = NoDocumentException("gone")
                exc.retried = True
                raise exc

            st._request = fake_request
            assert await st.delete("ns/a") is True
        run(go())

"""Feature flags (ref common/scala/.../core/FeatureFlags.scala).

The reference exposes one flag, `whisk.feature-flags.require-api-key-annotation`
(application.conf feature-flags block): when enabled, newly *created* actions
that do not already declare the `provide-api-key` annotation have it stamped
`false` (Actions.scala:55-73), and the invoker withholds the API key from the
action container unless the annotation is truthy — with a missing annotation
treated as truthy for backward compatibility (ContainerProxy.scala:688-693).

Config channel: `CONFIG_whisk_featureFlags_requireApiKeyAnnotation=true`.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..utils.config import load_config

# ref common/scala/.../core/entity/Annotations.scala:26
PROVIDE_API_KEY_ANNOTATION = "provide-api-key"
# ref Actions.scala execAnnotation (WhiskAction.execFieldName)
EXEC_ANNOTATION = "exec"


@dataclass
class FeatureFlagConfig:
    require_api_key_annotation: bool = False


def feature_flags() -> FeatureFlagConfig:
    """Load the flags fresh from the env channel (cheap; keeps tests able to
    toggle flags without cache invalidation hooks)."""
    return load_config(FeatureFlagConfig, env_path="feature_flags")

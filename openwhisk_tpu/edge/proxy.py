"""Asyncio edge reverse-proxy — the nginx role of the reference deployment.

Behavior ported from ansible/roles/nginx/templates/nginx.conf.j2:
  * upstream pool over all controllers with keepalive + failover: a
    connect-failed upstream is skipped for `fail_timeout` seconds
    (nginx `server ... fail_timeout=60s`);
  * vanity URLs: a request whose Host is `{namespace}.{domain}` is rewritten
    to `/api/v1/web/{namespace}{path}` (root → `/public/index.html`);
  * `/metrics` is denied from the edge (`location /metrics { deny all; }`);
  * a per-request transaction id header is injected and echoed
    (`proxy_set_header X-Request-ID`);
  * optional TLS termination via an `ssl.SSLContext`.

On top of that it serves API-gateway routes (reference: external gateway +
core/routemgmt): requests matching a registered (basePath, relPath, verb)
are forwarded to the backing web action.

Active/active partitioned controllers (ISSUE 15): with a `ring`
(controller/loadbalancer/partitions.py — upstream list order must match
controller instance numbering), requests whose path names an explicit
namespace are routed OWNER-FIRST: the upstream order is the partition's
rendezvous ranking, so the first hop is the controller that owns the
namespace's partition, and a 503 (an owner mid-handoff, or a stale
ranking during a rebalance) walks to the next candidate. Retries are
BOUNDED with jittered exponential backoff (`retry_attempts`,
`retry_backoff_ms`) on 503/connect-error — the first pass over the pool
walks sleep-free (the pre-existing behavior), then backoff bridges the
membership detection window during a failover instead of burning the
attempt budget in the first milliseconds — and every retry counts into
`retry_total[reason]` (the `edge_retry_total{reason}` family) so chaos
riders assert retries stayed bounded.
"""
from __future__ import annotations

import asyncio
import random
import secrets
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional

import aiohttp
from aiohttp import web

TRANSACTION_HEADER = "X-Request-ID"
MAX_BODY = 50 * 1024 * 1024  # nginx client_max_body_size 50M
HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "upgrade",
               "proxy-authenticate", "proxy-authorization", "te", "trailers",
               "host", "content-length"}


@dataclass
class Upstream:
    url: str  # e.g. http://127.0.0.1:3233
    fail_until: float = 0.0
    fails: int = 0
    #: fleet observatory (ISSUE 16): requests sent to / 503s answered by
    #: this upstream — which controller the edge actually leans on
    attempts: int = 0
    http_503: int = 0

    def usable(self) -> bool:
        return time.monotonic() >= self.fail_until


@dataclass
class EdgeProxy:
    upstreams: List[Upstream]
    domain: str = ""  # vanity base domain; "" disables subdomain rewrite
    fail_timeout: float = 60.0
    read_timeout: float = 75.0  # nginx proxy_read_timeout 75s
    route_matcher: Optional[Callable[[str, str], Awaitable[Optional[Dict]]]] = None
    #: active/active: PartitionRing for owner-first routing (module doc);
    #: None keeps the round-robin order bit-exactly
    ring: Optional[object] = None
    #: total upstream attempts per request; 0 = auto (two passes over the
    #: pool, min 4 — one pass is today's behavior, the second rides the
    #: backoff through a failover's detection window)
    retry_attempts: int = 0
    retry_backoff_ms: float = 25.0
    retry_backoff_max_ms: float = 400.0
    #: retries performed, by reason ("http_503" | "connect" | "read") —
    #: the edge_retry_total{reason} counter family
    retry_total: Dict[str, int] = field(default_factory=dict)
    _rr: int = 0
    #: partition -> upstream-index ranking, computed once per pid: the
    #: member set here is always the fixed range(len(upstreams)), so the
    #: per-request rendezvous hash+sort is pure repeated work
    _rank_cache: Dict[int, List[int]] = field(default_factory=dict)
    _session: Optional[aiohttp.ClientSession] = None
    _runner: Optional[web.AppRunner] = None
    extra_denied_paths: tuple = ("/metrics",)
    #: bearer token for GET /admin/edge/stats (ISSUE 16). Empty = the
    #: endpoint always answers 403 — the nginx-era `/metrics { deny
    #: all; }` posture stays the default, stats are strictly opt-in
    admin_token: str = ""

    @classmethod
    def for_controllers(cls, urls: List[str], **kwargs) -> "EdgeProxy":
        return cls(upstreams=[Upstream(u.rstrip("/")) for u in urls], **kwargs)

    # --------------------------------------------------------------- server
    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=MAX_BODY)
        app.router.add_route("*", "/{tail:.*}", self.handle)
        return app

    async def start(self, host: str = "0.0.0.0", port: int = 8080,
                    ssl_context=None) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.read_timeout))
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        await web.TCPSite(self._runner, host, port,
                          ssl_context=ssl_context).start()

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
        if self._session:
            await self._session.close()

    # -------------------------------------------------------------- routing
    def _vanity_namespace(self, request: web.Request) -> Optional[str]:
        if not self.domain:
            return None
        host = request.host.split(":")[0]
        suffix = "." + self.domain
        if host.endswith(suffix):
            ns = host[: -len(suffix)]
            if ns and all(c.isalnum() or c == "-" for c in ns):
                return ns
        return None

    async def _rewrite(self, request: web.Request) -> str:
        """Return the upstream path for this request; raise to deny/404."""
        path = request.path
        if path in self.extra_denied_paths:
            raise web.HTTPForbidden(text="forbidden")
        if path.startswith("/api/"):
            return path
        ns = self._vanity_namespace(request)
        if ns is not None:
            target = "/public/index.html" if path == "/" else path
            return f"/api/v1/web/{ns}{target}"
        if self.route_matcher is not None:
            op = await self.route_matcher(request.method, path)
            if op is not None:
                url = op.get("url", "")
                # strip any host prefix the route doc may carry
                if "://" in url:
                    rest = url.split("://", 1)[1]
                    _, _, tail = rest.partition("/")
                    url = "/" + tail
                return url
        # no API path, no vanity host, no gateway route: nothing to serve
        raise web.HTTPNotFound(text="no route")

    # ----------------------------------------------------------- edge stats
    def _edge_stats(self, request: web.Request) -> web.Response:
        """`GET /admin/edge/stats`: the edge's in-process counters, shaped
        so the fleet metrics merger folds the edge in as one more member
        (`counters` rows are the federation wire format). Bearer-gated on
        `admin_token`; `/metrics` itself stays denied."""
        auth = request.headers.get("Authorization", "")
        token = auth[len("Bearer "):] if auth.startswith("Bearer ") else ""
        if not self.admin_token or not token or \
                not secrets.compare_digest(token, self.admin_token):
            raise web.HTTPForbidden(text="forbidden")
        from ..utils.eventlog import identity
        ident = {**identity(), "role": "edge"}
        counters = [["edge_retry_total", [["reason", reason]], n]
                    for reason, n in sorted(self.retry_total.items())]
        for u in self.upstreams:
            counters.append(["edge_upstream_attempts_total",
                             [["upstream", u.url]], u.attempts])
            counters.append(["edge_upstream_http_503_total",
                             [["upstream", u.url]], u.http_503])
        return web.json_response({
            "identity": ident,
            "counters": counters,
            "retry_total": dict(self.retry_total),
            "upstreams": [{"url": u.url, "attempts": u.attempts,
                           "http_503": u.http_503, "fails": u.fails,
                           "usable": u.usable()} for u in self.upstreams],
        })

    # ---------------------------------------------------------------- proxy
    async def handle(self, request: web.Request) -> web.Response:
        if request.path == "/admin/edge/stats" and request.method == "GET":
            return self._edge_stats(request)
        target = await self._rewrite(request)
        transid = request.headers.get(TRANSACTION_HEADER) or secrets.token_hex(8)
        body = await request.read() if request.can_read_body else None
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in HOP_HEADERS}
        headers[TRANSACTION_HEADER] = transid

        qs = request.query_string
        suffix = target + (("?" + qs) if qs else "")
        last_error: Optional[Exception] = None
        last_503: Optional[web.Response] = None
        order = self._pick_order(self._path_namespace(request.path))
        attempts = self.retry_attempts or max(4, 2 * len(order))
        for attempt in range(attempts):
            if attempt >= len(order):
                # past the first pass over the pool. The first walk stays
                # sleep-free (a standby's 503 forwards to the active with
                # zero added latency, exactly the pre-retry behavior);
                # later passes back off with full jitter so a failover's
                # synchronized retry wave doesn't hammer the surviving
                # controllers in lockstep — the backoff is what bridges
                # the membership detection window
                await asyncio.sleep(self._backoff_s(attempt - len(order) + 1))
            upstream = order[attempt % len(order)]
            upstream.attempts += 1
            try:
                async with self._session.request(
                        request.method, upstream.url + suffix,
                        headers=headers, data=body,
                        allow_redirects=False) as resp:
                    payload = await resp.read()
                    upstream.fails = 0
                    out_headers = {k: v for k, v in resp.headers.items()
                                   if k.lower() not in HOP_HEADERS
                                   and k.lower() != "content-encoding"}
                    out_headers[TRANSACTION_HEADER] = transid
                    if resp.status == 503:
                        # a 503 is emitted BEFORE any state change (an HA
                        # standby refusing placement, a partition owned
                        # elsewhere, or no usable fleet): trying the next
                        # upstream is safe for any method (nginx
                        # `proxy_next_upstream http_503`). No blacklist —
                        # a standby answers everything else fine and
                        # becomes active without re-resolving.
                        upstream.http_503 += 1
                        last_503 = web.Response(status=503, body=payload,
                                                headers=out_headers)
                        if attempt + 1 < attempts:
                            self._count_retry("http_503")
                        continue
                    return web.Response(status=resp.status, body=payload,
                                        headers=out_headers)
            except aiohttp.ClientConnectorError as e:
                # connect failed — the request was never sent, so retrying
                # the next upstream is safe for ANY method; blacklist this
                # upstream for fail_timeout (nginx `fail_timeout=60s`)
                upstream.fails += 1
                upstream.fail_until = time.monotonic() + self.fail_timeout
                last_error = e
                if attempt + 1 < attempts:
                    self._count_retry("connect")
            except (aiohttp.ClientConnectionError, asyncio.TimeoutError):
                # the request may already be executing upstream (e.g. a slow
                # blocking invoke hit read_timeout): do NOT re-send non-
                # idempotent methods (nginx proxy_next_upstream excludes
                # them), and a slow request is no reason to blacklist
                if request.method in ("GET", "HEAD", "OPTIONS"):
                    last_error = RuntimeError("upstream read failed")
                    if attempt + 1 < attempts:
                        self._count_retry("read")
                    continue
                return web.Response(status=504, text="upstream timeout")
        if last_503 is not None:
            # every attempt said 503: surface the real refusal (body and
            # all) instead of a generic 502
            return last_503
        return web.Response(status=502, text=f"no upstream available: {last_error}")

    def _count_retry(self, reason: str) -> None:
        self.retry_total[reason] = self.retry_total.get(reason, 0) + 1

    def _backoff_s(self, attempt: int) -> float:
        """Full-jitter exponential backoff for retry `attempt` (>= 1)."""
        cap = min(self.retry_backoff_max_ms,
                  self.retry_backoff_ms * (2 ** (attempt - 1)))
        return random.uniform(0.0, cap) / 1e3

    def _path_namespace(self, path: str) -> Optional[str]:
        """The explicit namespace in an API path, for ring routing
        (`/api/v1/namespaces/{ns}/...`). `_` resolves to the caller's
        subject namespace upstream — unknowable here, so it falls back to
        round-robin (the bounded 503 retry still finds the owner).

        The hint is approximate by design: controllers partition by the
        AUTHENTICATED identity's namespace (tenant affinity — the edge
        has no auth store to resolve a key to one), which equals the
        path namespace for ordinary self-namespace invokes but not for
        cross-namespace shared-package calls. A miss costs extra
        sleep-free 503 hops on the first pass over the pool — the
        owner-side refusal stays the correctness gate either way."""
        prefix = "/api/v1/namespaces/"
        if not path.startswith(prefix):
            return None
        ns = path[len(prefix):].split("/", 1)[0]
        return ns if ns and ns != "_" else None

    def _pick_order(self, namespace: Optional[str] = None) -> List[Upstream]:
        """Round-robin over usable upstreams; all down → try everyone anyway
        (nginx resurrects a dead pool rather than hard-failing). With a
        ring and an explicit namespace, the order is the partition's
        rendezvous ranking instead — the first hop is the owner."""
        n = len(self.upstreams)
        if self.ring is not None and namespace is not None:
            pid = self.ring.partition_of(namespace)
            ranked = self._rank_cache.get(pid)
            if ranked is None:
                ranked = self._rank_cache[pid] = self.ring.rank(
                    pid, range(n))
            order = [self.upstreams[i] for i in ranked]
        else:
            order = [self.upstreams[(self._rr + i) % n] for i in range(n)]
            self._rr = (self._rr + 1) % n
        usable = [u for u in order if u.usable()]
        return usable or order

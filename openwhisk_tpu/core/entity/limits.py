"""Action resource limits.

Refs: MemoryLimit.scala:49-51, TimeLimit.scala:54-56, LogLimit.scala,
ConcurrencyLimit.scala:51-53, ActionLimits.scala. Defaults mirror the
reference's application.conf:368-394 (memory 128-512 MB std 256; time
100 ms - 5 min std 1 min; logs 0-10 MB std 10 MB; concurrency 1-1 std 1 —
intra-container concurrency is opt-in by raising `ConcurrencyLimit.MAX`).
All are class-configurable the way the reference reads them from config.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .parameters import MalformedEntity
from .size import MB, ByteSize


def _int_value(j, what: str) -> int:
    """Limits are JSON numbers on the wire; anything else — booleans,
    strings (even numeric ones, matching the reference's JsNumber-only
    contract), non-integral or non-finite floats — is a malformed body,
    not a server error (and not a silent truncation)."""
    if isinstance(j, bool) or not isinstance(j, (int, float)):
        raise MalformedEntity(f"{what} limit must be an integer")
    try:
        n = int(j)
    except (TypeError, ValueError, OverflowError):
        raise MalformedEntity(f"{what} limit must be an integer") from None
    if isinstance(j, float) and j != n:
        raise MalformedEntity(f"{what} limit must be an integer")
    return n


class LimitViolation(ValueError):
    pass


class MemoryLimit:
    MIN = MB(128)
    STD = MB(256)
    MAX = MB(512)

    __slots__ = ("megabytes",)

    def __init__(self, size: Optional[ByteSize] = None):
        size = size if size is not None else self.STD
        if size < self.MIN:
            raise LimitViolation(f"memory {size} below allowed threshold {self.MIN}")
        if size > self.MAX:
            raise LimitViolation(f"memory {size} exceeds allowed threshold {self.MAX}")
        self.megabytes = size.to_mb

    @property
    def size(self) -> ByteSize:
        return MB(self.megabytes)

    def to_json(self):
        return self.megabytes

    @classmethod
    def from_json(cls, j) -> "MemoryLimit":
        return cls(MB(_int_value(j, "memory")))

    def __eq__(self, other):
        return isinstance(other, MemoryLimit) and self.megabytes == other.megabytes

    def __repr__(self):
        return f"{self.megabytes} MB"


class TimeLimit:
    MIN_MS = 100
    STD_MS = 60_000
    MAX_MS = 300_000

    __slots__ = ("millis",)

    def __init__(self, millis: Optional[int] = None):
        millis = millis if millis is not None else self.STD_MS
        if millis < self.MIN_MS:
            raise LimitViolation(f"duration {millis}ms below allowed threshold {self.MIN_MS}ms")
        if millis > self.MAX_MS:
            raise LimitViolation(f"duration {millis}ms exceeds allowed threshold {self.MAX_MS}ms")
        self.millis = millis

    @property
    def seconds(self) -> float:
        return self.millis / 1000.0

    def to_json(self):
        return self.millis

    @classmethod
    def from_json(cls, j) -> "TimeLimit":
        return cls(_int_value(j, "timeout"))

    def __eq__(self, other):
        return isinstance(other, TimeLimit) and self.millis == other.millis

    def __repr__(self):
        return f"{self.millis} ms"


class LogLimit:
    MIN = MB(0)
    STD = MB(10)
    MAX = MB(10)

    __slots__ = ("megabytes",)

    def __init__(self, size: Optional[ByteSize] = None):
        size = size if size is not None else self.STD
        if size < self.MIN or size > self.MAX:
            raise LimitViolation(f"logs {size} outside allowed range [{self.MIN}, {self.MAX}]")
        self.megabytes = size.to_mb

    @property
    def size(self) -> ByteSize:
        return MB(self.megabytes)

    def to_json(self):
        return self.megabytes

    @classmethod
    def from_json(cls, j) -> "LogLimit":
        return cls(MB(_int_value(j, "logs")))

    def __eq__(self, other):
        return isinstance(other, LogLimit) and self.megabytes == other.megabytes

    def __repr__(self):
        return f"{self.megabytes} MB"


class ConcurrencyLimit:
    """Intra-container concurrency (ref ConcurrencyLimit.scala:51-53,
    docs/concurrency.md): number of activations one warm container may
    process at once. Disabled (max=1) by default, exactly as the reference."""
    MIN = 1
    STD = 1
    MAX = 1  # deployments raise this to opt in (e.g. 500)

    __slots__ = ("max_concurrent",)

    def __init__(self, concurrency: Optional[int] = None):
        c = concurrency if concurrency is not None else self.STD
        if c < self.MIN:
            raise LimitViolation(f"concurrency {c} below allowed threshold {self.MIN}")
        if c > self.MAX:
            raise LimitViolation(f"concurrency {c} exceeds allowed threshold {self.MAX}")
        self.max_concurrent = c

    def to_json(self):
        return self.max_concurrent

    @classmethod
    def from_json(cls, j) -> "ConcurrencyLimit":
        return cls(_int_value(j, "concurrency"))

    def __eq__(self, other):
        return isinstance(other, ConcurrencyLimit) and self.max_concurrent == other.max_concurrent

    def __repr__(self):
        return str(self.max_concurrent)


@dataclass
class ActionLimits:
    """Bundle of limits on an action (ref ActionLimits.scala)."""
    timeout: TimeLimit = None  # type: ignore[assignment]
    memory: MemoryLimit = None  # type: ignore[assignment]
    logs: LogLimit = None  # type: ignore[assignment]
    concurrency: ConcurrencyLimit = None  # type: ignore[assignment]

    def __post_init__(self):
        self.timeout = self.timeout or TimeLimit()
        self.memory = self.memory or MemoryLimit()
        self.logs = self.logs or LogLimit()
        self.concurrency = self.concurrency or ConcurrencyLimit()

    def to_json(self):
        return {"timeout": self.timeout.to_json(), "memory": self.memory.to_json(),
                "logs": self.logs.to_json(), "concurrency": self.concurrency.to_json()}

    @classmethod
    def from_json(cls, j) -> "ActionLimits":
        if j is not None and not isinstance(j, dict):
            raise MalformedEntity("limits must be an object")
        j = j or {}
        return cls(
            TimeLimit.from_json(j["timeout"]) if "timeout" in j else None,
            MemoryLimit.from_json(j["memory"]) if "memory" in j else None,
            LogLimit.from_json(j["logs"]) if "logs" in j else None,
            ConcurrencyLimit.from_json(j["concurrency"]) if "concurrency" in j else None,
        )

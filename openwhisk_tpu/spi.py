"""SPI (service provider interface) machinery.

Rebuild of the reference's SpiLoader (common/scala/.../spi/SpiLoader.scala:31-51
+ reference.conf:20-31): each extension point is a named key resolved to an
implementation factory. The ten reference extension points are reproduced so a
deployment can swap, e.g., the load balancer (`LoadBalancerProvider`) between
the CPU sharding balancer, the lean balancer, and the TPU balancer without
touching the controller (docs/spi.md:20-75).

Resolution order: explicit `bind()` > env var `CONFIG_whisk_spi_<Name>` >
registered default. Implementations are addressed as "module.path:AttrName".
"""
from __future__ import annotations

import importlib
import os
from typing import Any, Callable, Dict

# the reference extension points (reference.conf:20-31), plus the
# AttachmentStore seam (reference: S3AttachmentStoreProvider wired into the
# artifact store's attachment slot)
SPI_NAMES = (
    "AttachmentStoreProvider",
    "ArtifactStoreProvider",
    "ActivationStoreProvider",
    "MessagingProvider",
    "ContainerFactoryProvider",
    "LogStoreProvider",
    "LoadBalancerProvider",
    "EntitlementSpiProvider",
    "AuthenticationDirectiveProvider",
    "InvokerProvider",
    "InvokerServerProvider",
)

_DEFAULTS: Dict[str, str] = {
    "AttachmentStoreProvider": "openwhisk_tpu.database.attachment_store:MemoryAttachmentStoreProvider",
    "ArtifactStoreProvider": "openwhisk_tpu.database.memory_store:MemoryArtifactStoreProvider",
    "ActivationStoreProvider": "openwhisk_tpu.database.activation_store:ArtifactActivationStoreProvider",
    "MessagingProvider": "openwhisk_tpu.messaging.memory:MemoryMessagingProvider",
    "ContainerFactoryProvider": "openwhisk_tpu.containerpool.process_factory:ProcessContainerFactoryProvider",
    "LogStoreProvider": "openwhisk_tpu.containerpool.logstore:ContainerLogStoreProvider",
    "LoadBalancerProvider": "openwhisk_tpu.controller.loadbalancer.tpu_balancer:TpuBalancerProvider",
    "EntitlementSpiProvider": "openwhisk_tpu.controller.entitlement:LocalEntitlementProvider",
    "AuthenticationDirectiveProvider": "openwhisk_tpu.controller.authentication:BasicAuthenticationProvider",
    "InvokerProvider": "openwhisk_tpu.invoker.reactive:InvokerReactiveProvider",
    "InvokerServerProvider": "openwhisk_tpu.invoker.server:DefaultInvokerServerProvider",
}

_bindings: Dict[str, Any] = {}


class SpiResolutionError(Exception):
    pass


def overridden(name: str) -> bool:
    """True when `name` resolves to something other than the library
    default — an explicit bind() or the CONFIG_whisk_spi_<Name> env var."""
    return name in _bindings or \
        bool(os.environ.get(f"CONFIG_whisk_spi_{name}"))


def bind(name: str, impl: Any) -> None:
    """Explicitly bind an SPI to an implementation (object or 'mod:attr')."""
    _bindings[name] = impl


def unbind(name: str) -> None:
    _bindings.pop(name, None)


def reset() -> None:
    _bindings.clear()


def _load(path: str) -> Any:
    mod, _, attr = path.partition(":")
    if not attr:
        raise SpiResolutionError(f"invalid SPI path {path!r} (want 'module:Attr')")
    try:
        return getattr(importlib.import_module(mod), attr)
    except (ImportError, AttributeError) as e:
        raise SpiResolutionError(f"cannot load SPI impl {path!r}: {e}") from e


def get(name: str) -> Any:
    """Resolve an SPI extension point to its implementation object.

    Mirrors SpiLoader.get[T] (SpiLoader.scala:31-43): singletons addressed by
    a config key, here CONFIG_whisk_spi_<Name>.
    """
    if name in _bindings:
        impl = _bindings[name]
        return _load(impl) if isinstance(impl, str) else impl
    env = os.environ.get(f"CONFIG_whisk_spi_{name}")
    if env:
        return _load(env)
    default = _DEFAULTS.get(name)
    if default is None:
        raise SpiResolutionError(f"unknown SPI extension point {name!r}")
    return _load(default)


def register_default(name: str, path: str) -> None:
    _DEFAULTS[name] = path

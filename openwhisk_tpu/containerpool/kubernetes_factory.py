"""Kubernetes container driver: action pods via the k8s REST API.

Rebuild of core/invoker/.../containerpool/kubernetes/ (KubernetesClient.scala,
KubernetesContainer.scala, KubernetesContainerFactory.scala,
WhiskPodBuilder.scala): each activation container is a Pod created through
the API server, labelled for janitorial cleanup, addressed by its podIP, and
log-streamed over the pods/{name}/log subresource. Where the reference uses
the fabric8 JVM client, this speaks the REST API directly over aiohttp —
there is no TPU involvement here (host-side control plane), so the driver
stays a thin async HTTP client that any conformant API server satisfies
(tests run it against an in-process fake server).

Pause/resume: Kubernetes has no pod-pause primitive; like the reference the
driver treats suspend/resume as no-ops and relies on the pool's idle-timeout
eviction instead.
"""
from __future__ import annotations

import asyncio
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import aiohttp

from ..core.entity import ByteSize
from .container import Container, ContainerError
from .factory import ContainerFactory

INVOKER_LABEL = "openwhisk/invoker"
ACTION_LABEL = "openwhisk/action"

_LABEL_OK = set("abcdefghijklmnopqrstuvwxyz"
                "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789")


def _label_value(name: str) -> str:
    """Sanitize to a valid k8s label value: ASCII [A-Za-z0-9._-], ≤63 chars,
    starts and ends alphanumeric."""
    cleaned = "".join(c if (c in _LABEL_OK or c in "._-") else "."
                      for c in name)[:63]
    cleaned = cleaned.strip("._-")
    return cleaned or "unknown"


@dataclass
class KubernetesClientConfig:
    """Ref KubernetesClientConfig (application.conf whisk.kubernetes)."""
    api_server: str = "http://127.0.0.1:8001"   # e.g. kubectl proxy
    namespace: str = "openwhisk"
    token: Optional[str] = None
    timeout_s: float = 60.0
    cpu_scale_millis_per_mb: Optional[float] = None  # ref: cpu-scaling
    user_pod_node_affinity: Optional[Dict[str, str]] = None
    pod_template: Dict[str, Any] = field(default_factory=dict)
    action_port: int = 8080


class WhiskPodBuilder:
    """Builds the action-pod manifest (ref WhiskPodBuilder.scala): image,
    memory request==limit, optional cpu scaled from memory, restart-never,
    labels for cleanup + per-invoker accounting, optional node affinity, and
    an operator-supplied pod template merged underneath."""

    def __init__(self, config: KubernetesClientConfig, invoker_name: str):
        self.config = config
        self.invoker_name = invoker_name

    def build(self, name: str, image: str, memory: ByteSize,
              action_name: str = "") -> Dict[str, Any]:
        resources: Dict[str, Any] = {
            "requests": {"memory": f"{memory.to_mb}Mi"},
            "limits": {"memory": f"{memory.to_mb}Mi"},
        }
        if self.config.cpu_scale_millis_per_mb:
            millis = max(1, int(memory.to_mb * self.config.cpu_scale_millis_per_mb))
            resources["requests"]["cpu"] = f"{millis}m"
            resources["limits"]["cpu"] = f"{millis}m"
        spec: Dict[str, Any] = {
            "restartPolicy": "Never",
            "containers": [{
                "name": "user-action",
                "image": image,
                "ports": [{"containerPort": self.config.action_port,
                           "name": "action"}],
                "resources": resources,
            }],
        }
        if self.config.user_pod_node_affinity:
            spec["nodeSelector"] = dict(self.config.user_pod_node_affinity)
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": self.config.namespace,
                "labels": {
                    "name": name,
                    INVOKER_LABEL: self.invoker_name,
                    # label values allow [A-Za-z0-9._-] only, max 63 chars,
                    # and must start/end alphanumeric (ASCII)
                    ACTION_LABEL: _label_value(action_name),
                },
            },
            "spec": spec,
        }
        # operator template merged underneath (explicit fields win)
        tmpl = self.config.pod_template
        if tmpl:
            merged = _deep_merge(tmpl, pod)
            return merged
        return pod


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class KubernetesClient:
    """Async REST client for the pod lifecycle (ref KubernetesClient.scala).
    Only the five calls the invoker needs: create, wait-ready, delete,
    list-by-label, and log read."""

    def __init__(self, config: Optional[KubernetesClientConfig] = None):
        self.config = config or KubernetesClientConfig()
        self._session: Optional[aiohttp.ClientSession] = None

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            headers = {}
            if self.config.token:
                headers["Authorization"] = f"Bearer {self.config.token}"
            self._session = aiohttp.ClientSession(headers=headers)
        return self._session

    def _url(self, path: str) -> str:
        return (f"{self.config.api_server}/api/v1/namespaces/"
                f"{self.config.namespace}{path}")

    async def create_pod(self, manifest: Dict[str, Any]) -> Dict[str, Any]:
        async with self._http().post(self._url("/pods"), json=manifest,
                                     timeout=aiohttp.ClientTimeout(
                                         total=self.config.timeout_s)) as resp:
            if resp.status not in (200, 201):
                raise ContainerError(
                    f"pod create failed ({resp.status}): "
                    f"{(await resp.text())[:512]}")
            return await resp.json(content_type=None)

    async def get_pod(self, name: str) -> Dict[str, Any]:
        async with self._http().get(self._url(f"/pods/{name}")) as resp:
            if resp.status == 404:
                raise ContainerError(f"pod {name} not found")
            return await resp.json(content_type=None)

    async def wait_ready(self, name: str, poll_s: float = 0.05) -> str:
        """Poll until the pod is Running with a podIP; return the IP
        (ref KubernetesClient.run's readiness watch)."""
        deadline = asyncio.get_event_loop().time() + self.config.timeout_s
        while True:
            pod = await self.get_pod(name)
            status = pod.get("status", {})
            phase = status.get("phase")
            ip = status.get("podIP")
            if phase == "Running" and ip:
                return ip
            if phase in ("Failed", "Succeeded"):
                raise ContainerError(f"pod {name} entered terminal phase {phase}")
            if asyncio.get_event_loop().time() > deadline:
                raise ContainerError(f"pod {name} not ready within "
                                     f"{self.config.timeout_s}s (phase={phase})")
            await asyncio.sleep(poll_s)

    async def delete_pod(self, name: str) -> None:
        async with self._http().delete(self._url(f"/pods/{name}")) as resp:
            if resp.status not in (200, 202, 404):
                raise ContainerError(f"pod delete failed ({resp.status})")
            await resp.read()

    async def list_pods(self, label_selector: str) -> List[Dict[str, Any]]:
        async with self._http().get(
                self._url("/pods"),
                params={"labelSelector": label_selector}) as resp:
            if resp.status != 200:
                raise ContainerError(
                    f"pod list failed ({resp.status}): "
                    f"{(await resp.text())[:512]}")
            body = await resp.json(content_type=None)
            return body.get("items", [])

    async def read_log(self, name: str, since_time: Optional[str] = None) -> str:
        params = {}
        if since_time:
            params["sinceTime"] = since_time
        async with self._http().get(self._url(f"/pods/{name}/log"),
                                    params=params) as resp:
            return await resp.text()

    async def close(self) -> None:
        if self._session:
            await self._session.close()
            self._session = None


class KubernetesContainer(Container):
    """A pod-backed container (ref KubernetesContainer.scala). suspend and
    resume are no-ops: k8s cannot freeze a pod."""

    def __init__(self, client: KubernetesClient, pod_name: str, ip: str,
                 port: int = 8080):
        super().__init__(pod_name, (ip, port))
        self.client = client
        self._log_offset = 0  # chars already attributed to past activations

    async def suspend(self) -> None:
        pass

    async def resume(self) -> None:
        pass

    async def destroy(self) -> None:
        await super().destroy()
        await self.client.delete_pod(self.container_id)

    async def logs(self, limit_bytes: int = 10 * 1024 * 1024,
                   wait_for_sentinel: bool = True,
                   sentinel_timeout: float = 2.0) -> List[str]:
        """Only the lines this activation produced: the k8s log endpoint
        always returns the full stream, so the driver tracks a per-container
        offset (warm reuse). Polls until the runtime's end-of-activation
        sentinel shows up past the offset (the runtime may not have flushed
        yet when /run returns), then advances the offset past it so a late
        tail is never misattributed to the next activation — same contract
        as the process/docker drivers."""
        import asyncio

        from .container import ACTIVATION_LOG_SENTINEL
        # the pod log endpoint merges stdout+stderr, and the runtime writes
        # the sentinel to BOTH streams — a complete activation therefore ends
        # with two complete sentinel lines in the merged stream
        marker = ACTIVATION_LOG_SENTINEL + "\n"
        deadline = asyncio.get_event_loop().time() + sentinel_timeout
        while True:
            raw = await self.client.read_log(self.container_id)
            fresh = raw[self._log_offset:]
            complete = fresh.count(marker)  # only fully-written sentinel lines
            if complete >= 2 or not wait_for_sentinel:
                break
            if asyncio.get_event_loop().time() > deadline:
                break
            await asyncio.sleep(0.05)
        if complete:
            # consume through the LAST complete sentinel line; a partial
            # sentinel still being written stays for the next call
            end = 0
            for _ in range(complete):
                end = fresh.index(marker, end) + len(marker)
            head = fresh[:end]
            self._log_offset += end
        else:
            head = fresh
            self._log_offset += len(fresh)
        lines = [l for l in head.splitlines()
                 if ACTIVATION_LOG_SENTINEL not in l and l]
        out, total = [], 0
        for l in lines:
            total += len(l.encode()) + 1
            if total > limit_bytes:
                break
            out.append(l)
        return out


class KubernetesContainerFactory(ContainerFactory):
    """ContainerFactory over pods (ref KubernetesContainerFactory.scala):
    create builds + waits on a labelled pod; cleanup deletes every pod this
    invoker ever labelled (leftovers of a previous life)."""

    def __init__(self, invoker_name: str = "invoker0",
                 config: Optional[KubernetesClientConfig] = None,
                 client: Optional[KubernetesClient] = None):
        self.config = config or KubernetesClientConfig()
        self.client = client or KubernetesClient(self.config)
        self.invoker_name = invoker_name
        self.builder = WhiskPodBuilder(self.config, invoker_name)

    async def create_container(self, transid, name: str, image: str,
                               memory: ByteSize, cpu_shares: int = 0,
                               action=None) -> KubernetesContainer:
        pod_name = f"wsk-{name}-{uuid.uuid4().hex[:8]}".lower().replace("_", "-")
        action_name = str(getattr(action, "fully_qualified_name", "") or "") \
            if action else ""
        manifest = self.builder.build(pod_name, image, memory, str(action_name))
        await self.client.create_pod(manifest)
        try:
            ip = await self.client.wait_ready(pod_name)
        except ContainerError:
            await self.client.delete_pod(pod_name)
            raise
        return KubernetesContainer(self.client, pod_name, ip,
                                   port=self.config.action_port)

    async def cleanup(self) -> None:
        try:
            pods = await self.client.list_pods(
                f"{INVOKER_LABEL}={self.invoker_name}")
        except (ContainerError, aiohttp.ClientError, OSError):
            return  # janitorial only — an unreachable API must not abort close
        for pod in pods:
            name = pod.get("metadata", {}).get("name")
            if name:
                try:
                    await self.client.delete_pod(name)
                except ContainerError:
                    pass

    async def close(self) -> None:
        await self.cleanup()
        await self.client.close()


class KubernetesContainerFactoryProvider:
    """ContainerFactoryProvider SPI binding
    (CONFIG_whisk_spi_ContainerFactoryProvider=
     openwhisk_tpu.containerpool.kubernetes_factory:KubernetesContainerFactoryProvider)."""

    @staticmethod
    def instance(invoker_name: str = "invoker0", logger=None,
                 **kwargs) -> KubernetesContainerFactory:
        return KubernetesContainerFactory(invoker_name, **kwargs)

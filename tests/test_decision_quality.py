"""Placement quality observatory (ISSUE 17): kernel-level proofs.

Four contracts back the plane's headline claim ("measure placement
quality without changing placement"):

  * the jitted on-device scorer and its NumPy twin are the SAME
    arithmetic — integer outputs (histogram, counters, divergence)
    bit-identical, float32 accumulations within reduction-order
    tolerance, across both conc layouts and both shadow cadences;
  * the shadow counterfactual step with a ZERO penalty reproduces the
    production packed decision vector bit-for-bit (scan and repair
    kernel families, plain and admit variants) and never touches the
    live books;
  * a nonzero penalty means the same thing to every kernel family
    (scan == repair == pallas == pallas-repair under one penalty
    vector), one probe-ring lap of demotion per penalty level, and
    `penalty=None` stays the identity;
  * a disabled plane is a TRUE no-op (tracemalloc-asserted, the PR 3/10
    pattern) and the fleet merger (`merged_quality_report`) sums
    member histograms/counters bit-exactly — two members' merged counts
    equal one member that scored the pooled batches.
"""
import tracemalloc

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from openwhisk_tpu.controller.loadbalancer.quality import (  # noqa: E402
    QualityConfig, QualityPlane)
from openwhisk_tpu.controller.monitoring import (  # noqa: E402
    _pctl_from_hist, merged_quality_report)
from openwhisk_tpu.ops.decision_quality import (  # noqa: E402
    COUNTERS, C_PLACED, C_ROWS, C_SHADOW_DIVERGENT, C_SHADOW_ROWS,
    init_quality_state, make_quality_step, quality_step_np)
from openwhisk_tpu.ops.placement import (  # noqa: E402
    RequestBatch, init_state, make_fused_admit_step_packed,
    make_fused_step_packed, make_shadow_admit_step_packed,
    make_shadow_step_packed, release_batch, release_batch_vector,
    schedule_batch, schedule_batch_repair, unpack_step_output)
from openwhisk_tpu.ops.placement_pallas import (  # noqa: E402
    schedule_batch_pallas, schedule_batch_repair_pallas, to_transposed)
from openwhisk_tpu.ops.throttle import init_buckets  # noqa: E402


# -- randomized fixtures (the test_placement_repair fuzz idiom) ------------

def _random_batch(n, b, rng, slots=16, valid_p=0.9):
    import math
    off = rng.randint(0, max(1, n // 2), b).astype(np.int32)
    size = np.maximum(1, rng.randint(1, n + 1, b) - off).astype(np.int32)
    size = np.minimum(size, n - off).astype(np.int32)
    home = (rng.randint(0, 1 << 16, b) % size).astype(np.int32)
    step_inv = np.zeros(b, np.int32)
    for i in range(b):
        s = int(size[i])
        st = rng.randint(1, s + 1)
        while math.gcd(int(st), s) != 1:
            st = rng.randint(1, s + 1)
        step_inv[i] = pow(int(st), -1, s) if s > 1 else 0
    need = rng.choice([128, 256, 512], b).astype(np.int32)
    slot = rng.randint(0, slots, b).astype(np.int32)
    maxc = rng.choice([1, 1, 4], b).astype(np.int32)
    rand = (rng.randint(0, 1 << 20, b).astype(np.int32)
            % np.maximum(size, 1))
    valid = rng.rand(b) < valid_p
    return RequestBatch(*[jnp.asarray(x) for x in
                          (off, size, home, step_inv, need, slot, maxc,
                           rand, valid)])


def _random_state(n, rng, mem=1024, slots=16, unhealthy_p=0.2):
    st = init_state(n, [mem] * n, action_slots=slots)
    health = ~(rng.rand(n) < unhealthy_p)
    if not health.any():
        health[rng.randint(0, n)] = True
    conc = np.where(rng.rand(n, slots) < 0.3,
                    rng.randint(1, 4, (n, slots)), 0).astype(np.int32)
    return st._replace(health=jnp.asarray(health),
                       conc_free=jnp.asarray(conc))


def _packed_buf(rng, n, r, h, b, rows=9, slots=16):
    batch = _random_batch(n, b, rng, slots=slots)
    rel = np.zeros((5, r), np.int32)
    rel[3] = 1
    health = np.zeros((3, h), np.int32)
    req = np.stack([np.asarray(x, np.int32) for x in
                    (batch.offset, batch.size, batch.home, batch.step_inv,
                     batch.need_mb, batch.conc_slot, batch.max_conc,
                     batch.rand, batch.valid)])
    if rows == 10:
        req = np.concatenate(
            [req, rng.randint(0, 4, (1, b)).astype(np.int32)])
    return np.concatenate([rel.ravel(), health.ravel(), req.ravel()])


def _fuzz_scorer_inputs(rng, n, b, slots=8, shadow=True):
    """Random post-commit books + a random (but well-formed) packed
    decision vector — the scorer consumes decisions, it need not have
    produced them, so the fuzz space is wider than any one kernel's."""
    req = np.zeros((9, b), np.int32)
    off = rng.randint(0, max(1, n // 2), b).astype(np.int32)
    size = np.minimum(np.maximum(1, rng.randint(1, n + 1, b) - off),
                      n - off).astype(np.int32)
    req[0], req[1] = off, size
    req[2] = rng.randint(0, 1 << 16, b) % size
    req[4] = rng.choice([128, 256, 512], b)
    req[5] = rng.randint(0, slots, b)
    req[8] = (rng.rand(b) < 0.9).astype(np.int32)
    free = rng.randint(0, 2048, n).astype(np.int32)
    conc = np.where(rng.rand(n, slots) < 0.4,
                    rng.randint(1, 4, (n, slots)), 0).astype(np.int32)
    health = rng.rand(n) < 0.85
    if not health.any():
        health[0] = True
    # a mix of measured and unmeasured (cost-0 optimistic) invokers
    ewma = np.where(rng.rand(n) < 0.7, rng.rand(n) * 500.0,
                    0.0).astype(np.float32)
    cap = np.full(n, 2048, np.int32)
    cap[rng.rand(n) < 0.1] = 0

    def vec():
        chosen = rng.randint(-1, n, b).astype(np.int32)
        throttled = ((rng.rand(b) < 0.1) & (chosen < 0)).astype(np.int32)
        forced = ((rng.rand(b) < 0.2) & (chosen >= 0)).astype(np.int32)
        return (((chosen + 1) << 2) | (throttled << 1)
                | forced).astype(np.int32)

    return (free, conc, health, ewma, cap, req, vec(),
            vec() if shadow else None)


# -- scorer parity: jitted step vs NumPy twin ------------------------------

class TestScorerParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_fuzz_parity_jit_vs_numpy(self, seed):
        """Chained steps over random books/decisions: ints exact, floats
        to reduction-order tolerance. Layout and shadow cadence vary with
        the seed so both traced programs get coverage."""
        rng = np.random.RandomState(seed)
        n = int(rng.choice([4, 8, 32]))
        b = int(rng.choice([8, 16, 64]))
        nb = int(rng.choice([8, 24]))
        transposed = bool(seed % 2)
        shadow = seed != 2  # one seed exercises the no-shadow program
        step = make_quality_step(nb, transposed=transposed)
        qs_j = init_quality_state(n, nb)
        qs_n = init_quality_state(n, nb, numpy=True)
        for _ in range(3):
            free, conc, health, ewma, cap, req, out, sh = \
                _fuzz_scorer_inputs(rng, n, b, shadow=shadow)
            conc_in = conc.T.copy() if transposed else conc
            qs_j, sum_j = step(
                qs_j, jnp.asarray(free), jnp.asarray(conc_in),
                jnp.asarray(health), jnp.asarray(ewma), jnp.asarray(cap),
                jnp.asarray(req), jnp.asarray(out),
                jnp.asarray(sh) if sh is not None else None)
            qs_n, sum_n = quality_step_np(
                qs_n, free, conc_in, health, ewma, cap, req, out, sh,
                transposed=transposed)
        np.testing.assert_array_equal(np.asarray(qs_j.regret_hist),
                                      qs_n.regret_hist)
        np.testing.assert_array_equal(np.asarray(qs_j.counters),
                                      qs_n.counters)
        np.testing.assert_array_equal(np.asarray(qs_j.inv_divergence),
                                      qs_n.inv_divergence)
        np.testing.assert_allclose(np.asarray(qs_j.inv_regret_ms),
                                   qs_n.inv_regret_ms, rtol=1e-5, atol=1e-2)
        np.testing.assert_allclose(np.asarray(sum_j), sum_n,
                                   rtol=1e-5, atol=1e-2)
        # conservation: every placed row lands in exactly one bucket
        assert int(qs_n.regret_hist.sum()) == int(qs_n.counters[C_PLACED])

    def test_layouts_agree_on_same_books(self):
        """[N, A] and the Pallas [A, N] layout are the same books — the
        scorer must not care which one it was built for."""
        rng = np.random.RandomState(17)
        n, b, nb = 8, 16, 8
        free, conc, health, ewma, cap, req, out, sh = \
            _fuzz_scorer_inputs(rng, n, b)
        a = quality_step_np(init_quality_state(n, nb, numpy=True), free,
                            conc, health, ewma, cap, req, out, sh)
        t = quality_step_np(init_quality_state(n, nb, numpy=True), free,
                            conc.T.copy(), health, ewma, cap, req, out, sh,
                            transposed=True)
        for x, y in zip(a[0], t[0]):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        np.testing.assert_array_equal(a[1], t[1])

    def test_counter_semantics(self):
        """Hand-built single batch: every attribution counter lands where
        the layout says it does."""
        n, nb = 4, 8
        free = np.asarray([512, 512, 512, 512], np.int32)
        conc = np.zeros((n, 2), np.int32)
        conc[1, 0] = 1  # invoker1 slot0 has a warm permit
        health = np.asarray([True, True, True, False])
        ewma = np.asarray([100.0, 5.0, 0.0, 0.0], np.float32)
        cap = np.full(n, 1024, np.int32)
        # rows: placed@home(0), overflow(chosen=1,home=0), throttled,
        #       unplaced, invalid
        req = np.zeros((9, 5), np.int32)
        req[1] = n          # size: whole fleet
        req[4] = 128        # need_mb
        req[8] = [1, 1, 1, 1, 0]
        chosen = np.asarray([0, 1, -1, -1, 0], np.int32)
        throttled = np.asarray([0, 0, 1, 0, 0], np.int32)
        out = (((chosen + 1) << 2) | (throttled << 1)).astype(np.int32)
        qs, summary = quality_step_np(
            init_quality_state(n, nb, numpy=True), free, conc, health,
            ewma, cap, req, out)
        got = {name: int(qs.counters[i]) for i, name in enumerate(COUNTERS)}
        assert got == {"rows": 4, "placed": 2, "forced": 0, "overflow": 1,
                       "throttled": 1, "unplaced": 1, "cold_start": 1,
                       "shadow_rows": 0, "shadow_divergent": 0}
        # row 0 chose the 100ms invoker while 5ms and 0ms (unmeasured,
        # optimistic) alternatives were feasible: regret = 100 - 0
        assert qs.inv_regret_ms[0] == pytest.approx(100.0)
        # row 1 chose the cheapest measured invoker but invoker2 is
        # unmeasured AND feasible via free memory -> regret 5 - 0
        assert qs.inv_regret_ms[1] == pytest.approx(5.0)

    def test_shadow_divergence_attribution(self):
        n, nb = 4, 8
        free = np.full(n, 512, np.int32)
        conc = np.zeros((n, 2), np.int32)
        health = np.ones(n, bool)
        ewma = np.asarray([50.0, 10.0, 0.0, 0.0], np.float32)
        cap = np.full(n, 1024, np.int32)
        req = np.zeros((9, 3), np.int32)
        req[1] = n
        req[4] = 128
        req[8] = 1
        out = (((np.asarray([0, 1, 2]) + 1) << 2)).astype(np.int32)
        shadow = (((np.asarray([1, 1, 2]) + 1) << 2)).astype(np.int32)
        qs, summary = quality_step_np(
            init_quality_state(n, nb, numpy=True), free, conc, health,
            ewma, cap, req, out, shadow)
        assert int(qs.counters[C_SHADOW_ROWS]) == 3
        assert int(qs.counters[C_SHADOW_DIVERGENT]) == 1
        # divergence is attributed at the PRODUCTION choice
        np.testing.assert_array_equal(qs.inv_divergence, [1, 0, 0, 0])
        # delta = cost[prod=0] - cost[shadow=1] = 50 - 10 (predicted
        # saving had the shadow's choice been taken)
        from openwhisk_tpu.ops.decision_quality import S_SHADOW_DELTA_MS
        assert summary[S_SHADOW_DELTA_MS] == pytest.approx(40.0)


# -- shadow counterfactual: bit-exactness against production ---------------

class TestShadowCounterfactual:
    @pytest.mark.parametrize("rel_fn,sched_fn", [
        (release_batch, schedule_batch),
        (release_batch_vector, schedule_batch_repair),
    ], ids=["scan", "repair"])
    def test_zero_penalty_shadow_matches_production(self, rel_fn, sched_fn):
        """The acceptance contract: with the penalty zeroed, the shadow's
        packed decisions equal the production step's bit-for-bit, and the
        live books the production step is about to consume are untouched."""
        rng = np.random.RandomState(5)
        n, r, h, b = 32, 8, 4, 16
        state = _random_state(n, rng)
        free0 = np.asarray(state.free_mb).copy()
        conc0 = np.asarray(state.conc_free).copy()
        buf = jnp.asarray(_packed_buf(rng, n, r, h, b))
        s_out = make_shadow_step_packed(rel_fn, sched_fn)(
            state, buf, jnp.zeros((n,), jnp.int32), r, h, b)
        assert s_out.shape == (b,)  # no repair-round tail on the shadow
        _, p_out = make_fused_step_packed(rel_fn, sched_fn)(
            state, buf, r, h, b)
        np.testing.assert_array_equal(np.asarray(s_out),
                                      np.asarray(p_out)[:-1])
        np.testing.assert_array_equal(np.asarray(state.free_mb), free0)
        np.testing.assert_array_equal(np.asarray(state.conc_free), conc0)

    def test_zero_penalty_admit_shadow_matches_production(self):
        """Admit variant: same bucket state + now -> identical throttle
        bits and decisions, and the shadow returns neither books nor
        buckets to mutate."""
        rng = np.random.RandomState(6)
        n, r, h, b = 32, 8, 4, 16
        state = _random_state(n, rng)
        buckets = init_buckets(64, 6)
        tokens0 = np.asarray(buckets.tokens).copy()
        buf = jnp.asarray(_packed_buf(rng, n, r, h, b, rows=10))
        s_out = make_shadow_admit_step_packed(release_batch, schedule_batch)(
            (state, buckets), buf, jnp.zeros((n,), jnp.int32),
            np.float32(1.0), r, h, b)
        _, p_out = make_fused_admit_step_packed(release_batch,
                                                schedule_batch)(
            (state, buckets), buf, np.float32(1.0), r, h, b)
        p = np.asarray(p_out)
        np.testing.assert_array_equal(np.asarray(s_out), p[:-1])
        # the tight bucket actually throttled something, so bit 1 is live
        _, _, throttled, _ = unpack_step_output(p)
        assert throttled.any()
        np.testing.assert_array_equal(np.asarray(buckets.tokens), tokens0)

    @pytest.mark.pallas
    def test_penalized_parity_across_kernel_families(self):
        """One penalty vector means one thing: scan, repair, pallas and
        pallas-repair (interpret mode) agree on every placement, forced
        flag AND the post-commit books under the same nonzero penalty."""
        rng = np.random.RandomState(11)
        n, b = 32, 24
        state = _random_state(n, rng, slots=8)
        batch = _random_batch(n, b, rng, slots=8)
        pen = jnp.asarray(np.where(rng.rand(n) < 0.3,
                                   rng.randint(1, 4, n), 0), jnp.int32)
        ref = schedule_batch(state, batch, pen)
        outs = [
            schedule_batch_repair(state, batch, pen),
            schedule_batch_pallas(to_transposed(state), batch,
                                  interpret=True, penalty=pen),
            schedule_batch_repair_pallas(to_transposed(state), batch,
                                         interpret=True, penalty=pen),
        ]
        for i, out in enumerate(outs):
            np.testing.assert_array_equal(np.asarray(ref[1]),
                                          np.asarray(out[1]), err_msg=str(i))
            np.testing.assert_array_equal(np.asarray(ref[2]),
                                          np.asarray(out[2]), err_msg=str(i))
            np.testing.assert_array_equal(np.asarray(ref[0].free_mb),
                                          np.asarray(out[0].free_mb))

    @pytest.mark.pallas
    def test_zero_penalty_is_identity_everywhere(self):
        """penalty=0 and penalty=None are the same schedule — the shadow
        with no active penalties measures exactly zero divergence."""
        rng = np.random.RandomState(13)
        n, b = 16, 16
        state = _random_state(n, rng, slots=8)
        batch = _random_batch(n, b, rng, slots=8)
        zero = jnp.zeros((n,), jnp.int32)
        for none_out, zero_out in [
                (schedule_batch(state, batch),
                 schedule_batch(state, batch, zero)),
                (schedule_batch_repair(state, batch),
                 schedule_batch_repair(state, batch, zero)),
                (schedule_batch_pallas(to_transposed(state), batch,
                                       interpret=True),
                 schedule_batch_pallas(to_transposed(state), batch,
                                       interpret=True, penalty=zero))]:
            np.testing.assert_array_equal(np.asarray(none_out[1]),
                                          np.asarray(zero_out[1]))
            np.testing.assert_array_equal(np.asarray(none_out[2]),
                                          np.asarray(zero_out[2]))

    def test_penalty_demotes_straggler_by_probe_laps(self):
        """The augmented geometry: each penalty level pushes the invoker
        one full probe-ring lap down the preference order, so a penalized
        home loses to the next probe stop — without ever making an
        infeasible invoker placeable."""
        n = 4
        state = init_state(n, [1024] * n, action_slots=4)
        z = jnp.zeros((1,), jnp.int32)
        batch = RequestBatch(
            offset=z, size=jnp.full((1,), n, jnp.int32), home=z,
            step_inv=jnp.ones((1,), jnp.int32),
            need_mb=jnp.full((1,), 128, jnp.int32), conc_slot=z,
            max_conc=jnp.ones((1,), jnp.int32), rand=z,
            valid=jnp.ones((1,), bool))
        _, chosen0, forced0 = schedule_batch(state, batch)
        assert int(chosen0[0]) == 0 and not bool(forced0[0])
        pen = jnp.asarray([2, 0, 0, 0], jnp.int32)
        _, chosen_p, forced_p = schedule_batch(state, batch, pen)
        assert int(chosen_p[0]) == 1  # next probe stop, not the home
        assert not bool(forced_p[0])
        # penalizing everything reorders, never unplaces: still placed
        _, chosen_all, _ = schedule_batch(
            state, batch, jnp.full((n,), 3, jnp.int32))
        assert int(chosen_all[0]) >= 0


# -- disabled plane: a true no-op ------------------------------------------

class TestDisabledPlane:
    def test_disabled_plane_is_a_true_noop(self):
        """PR 3/10 contract, tracemalloc-asserted: every hook a disabled
        plane sits on (record_placement attribution, the dispatch-side
        device step, readback fold, supervision tick) allocates nothing."""
        qp = QualityPlane(QualityConfig(enabled=False))
        qp.attach(anomaly=None, invoker_names=lambda: ["invoker0"])

        def drive():
            qp.observe_decision(True, False, False)
            assert qp.device_step(None, None, None, None, None, None,
                                  None) is None
            qp.note_summary(None)
            qp.use_device(8)
            qp.maybe_tick(None)

        drive()  # warm every path once
        tracemalloc.start()
        try:
            s1 = tracemalloc.take_snapshot()
            for _ in range(256):
                drive()
            s2 = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        flt = [tracemalloc.Filter(True, "*loadbalancer/quality.py")]
        grown = [d for d in s2.filter_traces(flt).compare_to(
            s1.filter_traces(flt), "lineno") if d.size_diff > 0]
        total = sum(d.size_diff for d in grown)
        assert total < 2048, f"disabled quality plane allocated {total}B"
        # and it never allocated device or host state
        assert qp._qstate is None
        assert qp.tick() == {}
        assert qp.prometheus_text(["invoker0"]) == ""
        assert qp.quality_report(["invoker0"]) == {"enabled": False}
        assert qp.raw_counts(["invoker0"]) == {"enabled": False}


# -- fleet federation: bit-exact bucket-wise merge -------------------------

def _raw_member(qs, names, ident, imbalance=0.1):
    """A `/admin/placement/quality?raw=1` body built from a scored
    numpy QualityState (the shape QualityPlane.raw_counts exports)."""
    return {
        "identity": {"instance": ident}, "enabled": True, "kernel": "numpy",
        "buckets": int(qs.regret_hist.shape[0]),
        "regret_hist": [int(v) for v in qs.regret_hist],
        "counters": [int(v) for v in qs.counters],
        "counter_names": list(COUNTERS),
        "invokers": {nm: {"regret_ms": float(qs.inv_regret_ms[i]),
                          "divergence": int(qs.inv_divergence[i])}
                     for i, nm in enumerate(names)
                     if qs.inv_regret_ms[i] > 0 or qs.inv_divergence[i] > 0},
        "batches": 2, "shadow_batches": 1,
        "divergent_rows": int(qs.counters[C_SHADOW_DIVERGENT]),
        "shadow_rows": int(qs.counters[C_SHADOW_ROWS]),
        "regret_sum_ms": float(qs.inv_regret_ms.sum()),
        "fleet_imbalance_cov": imbalance,
    }


class TestFleetQualityMerge:
    def test_merge_is_bit_exact_with_pooled_scoring(self):
        """The federation property: score four batches split across two
        members, merge their raw exports — the merged histogram, counters
        and per-invoker divergence equal ONE member that scored all four
        batches. The fleet p99 then re-derives from merged counts."""
        n, b, nb = 8, 32, 8
        names = [f"invoker{i}" for i in range(n)]
        rng = np.random.RandomState(23)
        batches = [_fuzz_scorer_inputs(np.random.RandomState(100 + i), n, b)
                   for i in range(4)]
        member_states, pooled = [], init_quality_state(n, nb, numpy=True)
        for half in (batches[:2], batches[2:]):
            qs = init_quality_state(n, nb, numpy=True)
            for args in half:
                qs, _ = quality_step_np(qs, *args)
            member_states.append(qs)
        for args in batches:
            pooled, _ = quality_step_np(pooled, *args)

        raws = [_raw_member(qs, names, f"m{i}")
                for i, qs in enumerate(member_states)]
        merged = merged_quality_report(raws)
        assert merged["enabled"]
        assert merged["regret_hist"] == [int(v) for v in pooled.regret_hist]
        assert merged["counters"] == {
            name: int(pooled.counters[i])
            for i, name in enumerate(COUNTERS)}
        by_name = {row["invoker"]: row for row in merged["invokers"]}
        for i, nm in enumerate(names):
            div = int(pooled.inv_divergence[i])
            reg = float(pooled.inv_regret_ms[i])
            if reg <= 0 and div <= 0:
                assert nm not in by_name
                continue
            assert by_name[nm]["divergent_rows"] == div
            assert by_name[nm]["regret_ms"] == pytest.approx(reg, abs=1e-2)
        # fleet percentile from MERGED counts, not an average of p99s
        bounds = merged["buckets_le_ms"]
        bi = _pctl_from_hist([int(v) for v in pooled.regret_hist], 0.99)
        expect = bounds[bi] if bi < len(bounds) else None
        assert merged["regret_p99_le_ms"] == expect
        assert merged["shadow_rows"] == int(pooled.counters[C_SHADOW_ROWS])
        assert merged["divergent_rows"] == \
            int(pooled.counters[C_SHADOW_DIVERGENT])
        assert merged["divergence_ratio"] == pytest.approx(
            merged["divergent_rows"] / max(1, merged["shadow_rows"]),
            abs=1e-6)
        assert [m["instance"] for m in merged["members"]] == ["m0", "m1"]

    def test_plane_raw_export_feeds_the_merger(self):
        """End-to-end shape contract: QualityPlane.raw_counts (what the
        endpoint scrapes with ?raw=1) merges against a hand-built member
        without translation."""
        n, b, nb = 4, 16, 8
        qp = QualityPlane(QualityConfig(enabled=True, buckets=nb))
        qs = init_quality_state(n, nb, numpy=True)
        free, conc, health, ewma, cap, req, out, sh = \
            _fuzz_scorer_inputs(np.random.RandomState(31), n, b)
        qs, summary = quality_step_np(qs, free, conc, health, ewma, cap,
                                      req, out, sh)
        qp._qstate = qs
        qp.note_summary(summary)
        raw = qp.raw_counts([f"invoker{i}" for i in range(n)])
        other = _raw_member(qs, [f"invoker{i}" for i in range(n)], "m1")
        merged = merged_quality_report([raw, other])
        assert merged["enabled"]
        assert merged["regret_hist"] == \
            [2 * int(v) for v in qs.regret_hist]
        assert merged["counters"]["rows"] == 2 * int(qs.counters[C_ROWS])

    def test_bucket_mismatch_skipped_with_provenance(self):
        n, nb = 4, 8
        names = [f"invoker{i}" for i in range(n)]
        qs = init_quality_state(n, nb, numpy=True)
        free, conc, health, ewma, cap, req, out, sh = \
            _fuzz_scorer_inputs(np.random.RandomState(41), n, 16)
        qs, _ = quality_step_np(qs, free, conc, health, ewma, cap, req,
                                out, sh)
        good = _raw_member(qs, names, "good")
        odd = _raw_member(init_quality_state(n, nb + 4, numpy=True),
                          names, "odd")
        merged = merged_quality_report([good, odd])
        assert [m["instance"] for m in merged["members"]] == ["good"]
        assert [m["instance"] for m in merged["members_skipped"]] == ["odd"]
        # the mismatched member contributed nothing to the sums
        assert merged["regret_hist"] == [int(v) for v in qs.regret_hist]

    def test_disabled_and_empty_members(self):
        assert merged_quality_report([]) == {"enabled": False,
                                             "members": []}
        assert merged_quality_report(
            [{"enabled": False}]) == {"enabled": False, "members": []}

"""An in-process CouchDB fake, faithful to the wire surface
CouchDbArtifactStore uses: database create, MVCC document CRUD (revision
checks return real 409s), design-doc view queries with CouchDB array-key
collation (startkey/endkey/descending/skip/limit/include_docs), and native
attachments with per-operation revision bumps. State survives server
restarts (the test harness restarts the HTTP front per event loop)."""
from __future__ import annotations

import json
import uuid
from urllib.parse import unquote

from aiohttp import web


def _rank(v):
    if v is None:
        return 0
    if isinstance(v, bool):
        return 1
    if isinstance(v, (int, float)):
        return 2
    if isinstance(v, str):
        return 3
    if isinstance(v, list):
        return 4
    return 5  # objects sort last (CouchDB collation)


def key_cmp(a, b) -> int:
    """CouchDB view-key collation for the key shapes the store emits."""
    if isinstance(a, list) and isinstance(b, list):
        for x, y in zip(a, b):
            c = key_cmp(x, y)
            if c:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    ra, rb = _rank(a), _rank(b)
    if ra != rb:
        return (ra > rb) - (ra < rb)
    if ra in (2, 3):
        return (a > b) - (a < b)
    return 0  # same-rank null/bool/object: equal for our key shapes


class FakeCouchDB:
    def __init__(self):
        self.dbs = {}      # db -> {docid -> doc (with _rev, _attachments)}
        self.blobs = {}    # (db, docid, att) -> bytes
        self.runner = None

    # ------------------------------------------------------------- lifecycle
    async def start(self):
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.dispatch)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{port}"

    async def stop(self):
        await self.runner.cleanup()

    # -------------------------------------------------------------- dispatch
    async def dispatch(self, request: web.Request) -> web.Response:
        raw = request.rel_url.raw_path.split("?")[0]
        segs = [s for s in raw.split("/") if s]
        if not segs:
            return web.json_response({"couchdb": "fake"}, status=200)
        db, rest = segs[0], segs[1:]
        if not rest:
            return await self.db_op(request, db)
        docs = self.dbs.get(db)
        if docs is None:
            return web.json_response({"error": "not_found"}, status=404)
        if rest[0] == "_design" and len(rest) >= 4 and rest[2] == "_view":
            return self.view(request, db, unquote(rest[1]), rest[3])
        if len(rest) == 1:
            return await self.doc_op(request, db, unquote(rest[0]))
        if len(rest) == 2:
            return await self.att_op(request, db, unquote(rest[0]),
                                     unquote(rest[1]))
        return web.json_response({"error": "bad_request"}, status=400)

    async def db_op(self, request, db):
        if request.method == "PUT":
            if db in self.dbs:
                return web.json_response({"error": "file_exists"}, status=412)
            self.dbs[db] = {}
            return web.json_response({"ok": True}, status=201)
        if request.method == "GET" and db in self.dbs:
            return web.json_response({"db_name": db})
        return web.json_response({"error": "not_found"}, status=404)

    def _new_rev(self, old):
        n = int(old.split("-")[0]) + 1 if old else 1
        return f"{n}-{uuid.uuid4().hex[:8]}"

    async def doc_op(self, request, db, doc_id):
        docs = self.dbs[db]
        if request.method == "GET":
            doc = docs.get(doc_id)
            if doc is None:
                return web.json_response({"error": "not_found"}, status=404)
            return web.json_response(doc)
        if request.method == "PUT":
            body = await request.json()
            cur = docs.get(doc_id)
            sent_rev = body.pop("_rev", None) or \
                request.rel_url.query.get("rev")
            if cur is not None and sent_rev != cur["_rev"]:
                return web.json_response({"error": "conflict"}, status=409)
            if cur is None and sent_rev is not None:
                return web.json_response({"error": "conflict"}, status=409)
            rev = self._new_rev(cur["_rev"] if cur else None)
            body["_id"] = doc_id
            body["_rev"] = rev
            # REAL CouchDB semantics: a PUT whose body carries no
            # _attachments stubs REMOVES existing attachments
            if cur and "_attachments" in cur and "_attachments" not in body:
                for key in [k for k in self.blobs
                            if k[0] == db and k[1] == doc_id]:
                    del self.blobs[key]
            docs[doc_id] = body
            return web.json_response({"ok": True, "id": doc_id, "rev": rev},
                                     status=201)
        if request.method == "DELETE":
            cur = docs.get(doc_id)
            if cur is None:
                return web.json_response({"error": "not_found"}, status=404)
            if request.rel_url.query.get("rev") != cur["_rev"]:
                return web.json_response({"error": "conflict"}, status=409)
            del docs[doc_id]
            for key in [k for k in self.blobs if k[0] == db and k[1] == doc_id]:
                del self.blobs[key]
            return web.json_response({"ok": True}, status=200)
        return web.json_response({"error": "method_not_allowed"}, status=405)

    async def att_op(self, request, db, doc_id, att):
        docs = self.dbs[db]
        cur = docs.get(doc_id)
        if request.method == "GET":
            blob = self.blobs.get((db, doc_id, att))
            if cur is None or blob is None:
                return web.json_response({"error": "not_found"}, status=404)
            ct = cur.get("_attachments", {}).get(att, {}).get(
                "content_type", "application/octet-stream")
            return web.Response(body=blob, content_type=ct)
        if cur is None:
            return web.json_response({"error": "not_found"}, status=404)
        if request.rel_url.query.get("rev") != cur["_rev"]:
            return web.json_response({"error": "conflict"}, status=409)
        if request.method == "PUT":
            data = await request.read()
            cur.setdefault("_attachments", {})[att] = {
                "content_type": request.content_type,
                "length": len(data), "stub": True}
            self.blobs[(db, doc_id, att)] = data
            cur["_rev"] = self._new_rev(cur["_rev"])
            return web.json_response({"ok": True, "rev": cur["_rev"]},
                                     status=201)
        if request.method == "DELETE":
            cur.get("_attachments", {}).pop(att, None)
            if not cur.get("_attachments"):
                cur.pop("_attachments", None)
            self.blobs.pop((db, doc_id, att), None)
            cur["_rev"] = self._new_rev(cur["_rev"])
            return web.json_response({"ok": True, "rev": cur["_rev"]},
                                     status=200)
        return web.json_response({"error": "method_not_allowed"}, status=405)

    def view(self, request, db, design, view):
        design_doc = self.dbs[db].get(f"_design/{design}")
        if design_doc is None or view not in design_doc.get("views", {}):
            return web.json_response({"error": "not_found"}, status=404)
        q = request.rel_url.query
        # native implementation of the `all` map function the store installs
        rows = []
        for doc_id, doc in self.dbs[db].items():
            if doc_id.startswith("_design/"):
                continue
            if not doc.get("entityType"):
                continue
            ns = str(doc.get("namespace", "")).split("/")[0]
            key = [doc["entityType"], ns,
                   doc.get("start") or doc.get("updated") or 0]
            rows.append({"id": doc_id, "key": key, "value": None,
                         "doc": doc})
        rows.sort(key=lambda r: _SortKey(r["key"]))
        descending = q.get("descending") == "true"
        if descending:
            rows.reverse()
        start = json.loads(q["startkey"]) if "startkey" in q else None
        end = json.loads(q["endkey"]) if "endkey" in q else None
        if start is not None:
            rows = [r for r in rows
                    if (key_cmp(r["key"], start) >= 0 if not descending
                        else key_cmp(r["key"], start) <= 0)]
        if end is not None:
            rows = [r for r in rows
                    if (key_cmp(r["key"], end) <= 0 if not descending
                        else key_cmp(r["key"], end) >= 0)]
        rows = rows[int(q.get("skip", 0)):]
        if "limit" in q:
            rows = rows[: int(q["limit"])]
        if q.get("include_docs") != "true":
            for r in rows:
                r.pop("doc", None)
        return web.json_response({"total_rows": len(rows), "rows": rows})


class _SortKey:
    __slots__ = ("k",)

    def __init__(self, k):
        self.k = k

    def __lt__(self, other):
        return key_cmp(self.k, other.k) < 0

"""In-memory message bus.

Rebuild of the reference's lean connector (common/scala/.../connector/lean/:
LeanMessagingProvider/LeanProducer/LeanConsumer — a BlockingQueue per topic),
used for single-process deployments and as the test bus (the reference's
TestConnector pattern, tests/.../connector/test/TestConnector.scala:36-109).

Competing consumers in the same group share a queue (each message is
delivered once per group); distinct groups each get every message — the same
observable semantics as Kafka consumer groups on a single partition.
"""
from __future__ import annotations

import asyncio
import itertools
from collections import deque
from typing import Dict, List, Optional, Tuple

from .connector import MessageConsumer, MessageProducer, MessagingProvider


class _Topic:
    def __init__(self, name: str):
        self.name = name
        self.offset = itertools.count()
        self.groups: Dict[str, deque] = {}
        self.cond = asyncio.Condition()

    def queue_for(self, group: str) -> deque:
        if group not in self.groups:
            self.groups[group] = deque()
        return self.groups[group]


class MemoryBus:
    """Topic registry shared by producers/consumers of one provider."""

    def __init__(self):
        self.topics: Dict[str, _Topic] = {}

    def topic(self, name: str) -> _Topic:
        t = self.topics.get(name)
        if t is None:
            t = _Topic(name)
            self.topics[name] = t
        return t


class MemoryProducer(MessageProducer):
    def __init__(self, bus: MemoryBus):
        self.bus = bus
        self._sent = 0

    @property
    def sent_count(self) -> int:
        return self._sent

    async def send(self, topic: str, msg) -> None:
        payload = msg if isinstance(msg, (bytes, bytearray)) else msg.serialize()
        t = self.bus.topic(topic)
        off = next(t.offset)
        async with t.cond:
            for q in t.groups.values():
                q.append((off, bytes(payload)))
            if not t.groups:
                # retain for the first group to subscribe (queue semantics)
                t.queue_for("__default__").append((off, bytes(payload)))
            self._sent += 1
            t.cond.notify_all()


class MemoryConsumer(MessageConsumer):
    def __init__(self, bus: MemoryBus, topic: str, group: str, max_peek: int = 128):
        self.bus = bus
        self.topic_name = topic
        self.group = group
        self.max_peek = max_peek
        t = self.bus.topic(topic)
        # adopt messages produced before any subscriber existed
        if group not in t.groups and "__default__" in t.groups:
            t.groups[group] = t.groups.pop("__default__")
        else:
            t.queue_for(group)
        self._uncommitted: List[Tuple[str, int, int, bytes]] = []

    async def peek(self, max_messages: int, timeout: float = 0.5
                   ) -> List[Tuple[str, int, int, bytes]]:
        n = min(max_messages, self.max_peek)
        t = self.bus.topic(self.topic_name)
        q = t.queue_for(self.group)
        out: List[Tuple[str, int, int, bytes]] = []
        async with t.cond:
            if not q:
                try:
                    await asyncio.wait_for(t.cond.wait_for(lambda: len(q) > 0), timeout)
                except asyncio.TimeoutError:
                    return []
            while q and len(out) < n:
                off, payload = q.popleft()
                out.append((self.topic_name, 0, off, payload))
        self._uncommitted = out
        return out

    def commit(self) -> None:
        self._uncommitted = []


class MemoryMessagingProvider(MessagingProvider):
    """One bus per instance; `shared()` returns a process-wide bus for
    lean/standalone mode where controller and invoker live in one process."""

    _shared: Optional["MemoryMessagingProvider"] = None

    def __init__(self):
        self.bus = MemoryBus()

    @classmethod
    def shared(cls) -> "MemoryMessagingProvider":
        if cls._shared is None:
            cls._shared = cls()
        return cls._shared

    @classmethod
    def reset_shared(cls) -> None:
        cls._shared = None

    def get_producer(self) -> MemoryProducer:
        return MemoryProducer(self.bus)

    def get_consumer(self, topic: str, group_id: str, max_peek: int = 128) -> MemoryConsumer:
        return MemoryConsumer(self.bus, topic, group_id, max_peek)

    def ensure_topic(self, topic: str, partitions: int = 1,
                     retention_bytes: Optional[int] = None) -> None:
        self.bus.topic(topic)

"""Action/trigger/package parameters with merge + init semantics.

Ref: common/scala/.../core/entity/Parameter.scala — an ordered key->value
map; `++` merges with right-bias (used for package -> binding -> action ->
invoke-payload inheritance, Packages.scala + Actions.scala); `init` marks
parameters only passed at container /init; `locked` (encrypted at rest in the
reference) is tracked as a flag here.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, Optional


class MalformedEntity(ValueError):
    """Wrong-typed JSON in an entity body. The REST layer maps this to the
    reference's 400 "The request content was malformed" instead of letting
    a TypeError/AttributeError surface as a 500."""


class ParameterValue:
    __slots__ = ("value", "init")

    def __init__(self, value: Any, init: bool = False):
        self.value = value
        self.init = init

    def __eq__(self, other):
        return isinstance(other, ParameterValue) and \
            (self.value, self.init) == (other.value, other.init)

    def __repr__(self):
        return f"ParameterValue({self.value!r}, init={self.init})"


class Parameters:
    """Immutable-ish parameter map, JSON form: [{"key":k,"value":v,"init":b}]."""

    def __init__(self, params: Optional[Dict[str, ParameterValue]] = None):
        self._params: Dict[str, ParameterValue] = dict(params or {})

    @classmethod
    def of(cls, **kwargs) -> "Parameters":
        return cls({k: ParameterValue(v) for k, v in kwargs.items()})

    @classmethod
    def from_arguments(cls, args: Dict[str, Any]) -> "Parameters":
        return cls({k: ParameterValue(v) for k, v in (args or {}).items()})

    def merge(self, other: Optional["Parameters"]) -> "Parameters":
        """Right-biased merge: `other` wins (ref Parameters `++`)."""
        if other is None:
            return self
        merged = dict(self._params)
        merged.update(other._params)
        return Parameters(merged)

    def __add__(self, other):
        return self.merge(other)

    def keys(self):
        return self._params.keys()

    def get(self, key: str, default=None):
        pv = self._params.get(key)
        return pv.value if pv is not None else default

    def get_bool(self, key: str) -> Optional[bool]:
        v = self.get(key)
        return v if isinstance(v, bool) else None

    def is_truthy(self, key: str, value_for_non_existent: bool = False) -> bool:
        """JSON truthiness (ref Parameter.scala:119-127 isTruthy): booleans
        as-is, numbers != 0, strings nonempty, null false, other values true;
        a missing key yields `value_for_non_existent`."""
        if key not in self._params:
            return value_for_non_existent
        v = self.get(key)
        if isinstance(v, bool):
            return v
        if isinstance(v, (int, float)):
            return v != 0
        if isinstance(v, str):
            return v != ""
        if v is None:
            return False
        return True

    def __contains__(self, key):
        return key in self._params

    def __len__(self):
        return len(self._params)

    def __eq__(self, other):
        return isinstance(other, Parameters) and self._params == other._params

    def init_parameters(self) -> Dict[str, Any]:
        return {k: v.value for k, v in self._params.items() if v.init}

    def to_arguments(self) -> Dict[str, Any]:
        """Flat {key: value} dict handed to the action at /run."""
        return {k: v.value for k, v in self._params.items()}

    def definitions(self) -> Dict[str, ParameterValue]:
        return dict(self._params)

    def to_json(self):
        return [
            {"key": k, "value": v.value, **({"init": True} if v.init else {})}
            for k, v in self._params.items()
        ]

    @classmethod
    def from_json(cls, j) -> "Parameters":
        if j is None:
            return cls()
        if isinstance(j, dict):  # accept {k: v} shorthand
            return cls.from_arguments(j)
        if not isinstance(j, list):
            raise MalformedEntity(
                "parameters/annotations must be a [{key, value}] list")
        params: Dict[str, ParameterValue] = {}
        for item in j:
            if not isinstance(item, dict) or not isinstance(item.get("key"), str):
                raise MalformedEntity(
                    "parameters/annotations entries need a string 'key'")
            params[item["key"]] = ParameterValue(item.get("value"), bool(item.get("init", False)))
        return cls(params)

    def size_bytes(self) -> int:
        return len(json.dumps(self.to_json()).encode())

    def __repr__(self):
        return f"Parameters({self.to_arguments()!r})"

"""Batched front-door admission: vectorized throttle checks for the
controller's ACTIVATE path.

The serial entitlement pipeline pays one rolling-window deque scan (rate
throttle) plus one in-flight counter read (concurrency throttle) per
request, on the event loop, per arrival. Under open-loop load those
per-request costs compound into the tail (PAPERS.md: Schroeder et al. —
open vs. closed loops; Dean & Barroso — amortize serial work over
batches). This module coalesces concurrent `_invoke_action` arrivals and
decides them in ONE vectorized pass:

  * `rate_admit_batch` — the host-side NumPy twin of the device token
    bucket's batch admission (`ops/throttle.py:admit_batch`), but with the
    HTTP front door's semantics: the reference's rolling-minute window with
    per-user overrides (RateThrottler.scala). One deque prune per TOUCHED
    namespace per batch (instead of per request) + one segmented position
    count across the batch replaces N serial scans. It operates directly
    on the serial `RateThrottler`'s deques, so the serial and batched
    paths interleave safely (triggers vs. actions, off-switch flips).
  * `AdmissionPlane` — the coalescer: concurrent checks enqueue, a drainer
    flushes on size (`max_batch`) or a bounded window (`window_ms`, same
    Nagle rule as the bus coalescer), and rejections surface as the exact
    serial `ThrottleRejectRequest`s (same messages, same throttle events).

Bit-parity with the serial path (fuzzed in tests/test_admission.py): the
batch shares one clock, so serial calls with that same clock produce the
same admit/reject decisions AND the same deque state afterward. Two
deliberate, documented divergences: (1) events aging out *during* a
sub-millisecond window are pruned at the shared flush clock instead of
per-arrival clocks; (2) the CONCURRENCY throttle does intra-batch
accounting — each admission in a flush counts against its namespace's
limit for later batch-mates — which is STRICTER than the serial race,
where N arrivals between counter updates all read the same in-flight
count and can collectively overshoot the limit.

Off switch: `CONFIG_whisk_admission_batch_enabled=false` keeps
`LocalEntitlementProvider` on the serial `_check_throttles` path —
bit-exact with today's behavior.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..utils.config import load_config
from ..utils.microbatch import MicroCoalescer


@dataclass(frozen=True)
class AdmissionBatchConfig:
    """`CONFIG_whisk_admission_batch_*` env overrides."""
    enabled: bool = True
    #: bounded accumulation delay before a flush. Default 0 = end of the
    #: current event-loop sweep: concurrent arrivals in one sweep still
    #: coalesce, and a lone request at idle pays NO added latency (the
    #: same zero-idle-tax rule as the bus coalescer's window)
    window_ms: float = 0.0
    #: flush as soon as this many checks are pending
    max_batch: int = 256

    @classmethod
    def from_env(cls) -> "AdmissionBatchConfig":
        return load_config(cls, env_path="admission.batch")


def rate_admit_batch(throttler, ns_ids: List[str], limits,
                     now: Optional[float] = None) -> np.ndarray:
    """Vectorized equivalent of N serial `RateThrottler.check(ns, limit,
    now)` calls in arrival order, against the same throttler state.

    Returns bool[B] admissions. Per TOUCHED namespace: one expiry prune of
    its deque (the serial path prunes per request); across the batch: one
    segmented position count (arrival rank within the namespace), so
    request i admits iff `len(queue) + rank_i < limit_i`. Admitted
    requests append the shared `now`, exactly like serial admits."""
    b = len(ns_ids)
    if b == 0:
        return np.zeros((0,), bool)
    now = time.monotonic() if now is None else now
    default = throttler.default_per_minute
    limits_arr = np.asarray(
        [default if lim is None else lim for lim in limits], np.int64)
    codes, idx = np.unique(np.asarray(ns_ids, object), return_inverse=True)
    horizon = now - 60.0
    base = np.empty(len(codes), np.int64)
    queues = []
    for k, ns in enumerate(codes):
        q = throttler._events.setdefault(ns, deque())
        while q and q[0] <= horizon:
            q.popleft()
        queues.append(q)
        base[k] = len(q)
    # the segmented count: arrival rank of each request within its
    # namespace, computed once for the whole batch (the NumPy analogue of
    # ops/throttle.admit_batch's one-hot prefix count)
    order = np.argsort(idx, kind="stable")
    sidx = idx[order]
    starts = np.flatnonzero(np.r_[True, sidx[1:] != sidx[:-1]])
    lens = np.diff(np.r_[starts, b])
    rank = np.empty(b, np.int64)
    rank[order] = np.arange(b) - np.repeat(starts, lens)
    admitted = base[idx] + rank < limits_arr
    # Heterogeneous per-request limits WITHIN one namespace re-introduce
    # the serial dependency (an early rejection consumes no slot, so a
    # later larger-limit request can pass where rank math says no): replay
    # exactly those groups serially. Vanishingly rare — the override comes
    # from the namespace's own identity record — but parity is parity.
    slim = limits_arr[order]
    gmin = np.minimum.reduceat(slim, starts)
    gmax = np.maximum.reduceat(slim, starts)
    for g in np.flatnonzero(gmin != gmax):
        members = order[starts[g]: starts[g] + lens[g]]  # arrival order
        count = int(base[sidx[starts[g]]])
        for i in members:
            admitted[i] = count < limits_arr[i]
            count += int(admitted[i])
    for i in range(b):
        if admitted[i]:
            queues[idx[i]].append(now)
    return admitted


class AdmissionPlane:
    """Coalesces concurrent ACTIVATE throttle checks into vectorized
    flushes (see module doc). One instance per LocalEntitlementProvider;
    the coalescing loop is the shared MicroCoalescer (utils/microbatch.py,
    the same drainer the bus producer wrapper rides)."""

    def __init__(self, provider, config: Optional[AdmissionBatchConfig] = None):
        self.provider = provider
        cfg = config if config is not None else AdmissionBatchConfig.from_env()
        self._co = MicroCoalescer(self._flush, cfg.max_batch,
                                  max(0.0, float(cfg.window_ms)) / 1e3,
                                  name="admission-batch")
        self.batches = 0
        self.checked = 0

    async def check_throttles(self, identity, is_trigger_fire: bool) -> None:
        """The batched stand-in for `_check_throttles`: returns on admit,
        raises the serial path's exact `ThrottleRejectRequest` on reject."""
        await self._co.submit((identity, is_trigger_fire))

    async def _flush(self, batch: List[tuple]) -> None:
        """One vectorized admission pass over the whole batch
        (`[((identity, is_trigger_fire), fut), ...]`). Decision order
        mirrors the serial pipeline exactly: rate first (its rejection
        skips the concurrency read), then concurrency. Rejected futures
        get their exception here; admitted ones are resolved by the
        coalescer on return."""
        from .entitlement import (CONCURRENT_LIMIT_MESSAGE,
                                  ThrottleRejectRequest, rate_limit_message)
        self.batches += 1
        self.checked += len(batch)
        p = self.provider
        now = time.monotonic()
        fire_idx = [i for i, ((_id, fire), _f) in enumerate(batch) if fire]
        invoke_idx = [i for i, ((_id, fire), _f) in enumerate(batch)
                      if not fire]
        rejection: List[Optional[Exception]] = [None] * len(batch)
        for idxs, throttler, limit_of in (
                (fire_idx, p.fire_rate,
                 lambda ident: ident.limits.fires_per_minute),
                (invoke_idx, p.invoke_rate,
                 lambda ident: ident.limits.invocations_per_minute)):
            if not idxs:
                continue
            admitted = rate_admit_batch(
                throttler,
                [batch[i][0][0].namespace.uuid.asString for i in idxs],
                [limit_of(batch[i][0][0]) for i in idxs], now)
            for j, i in enumerate(idxs):
                if not admitted[j]:
                    # the serial path's exact text (one shared copy keyed
                    # on the throttler's own description)
                    rejection[i] = ThrottleRejectRequest(
                        rate_limit_message(throttler.description))
                    p._throttle_event("TimedRateLimit", batch[i][0][0])
        # Concurrency (invoke only, rate-admitted only): ONE in-flight
        # counter read per namespace PLUS intra-batch accounting — each
        # admission here counts against the limit for later batch-mates.
        # Deliberately STRICTER than the serial race (N arrivals between
        # counter updates all read the same count and can collectively
        # blow past the limit); a coalesced burst cannot.
        if p.load_balancer is not None:
            lb = p.load_balancer
            default = p.concurrent.default_concurrent
            active_cache: dict = {}
            granted: dict = {}
            for i in invoke_idx:
                if rejection[i] is not None:
                    continue
                ident = batch[i][0][0]
                ns = ident.namespace.uuid.asString
                limit = ident.limits.concurrent_invocations
                limit = default if limit is None else limit
                active = active_cache.get(ns)
                if active is None:
                    active = lb.active_activations_for(ns)
                    active_cache[ns] = active
                if active + granted.get(ns, 0) >= limit:
                    rejection[i] = ThrottleRejectRequest(
                        CONCURRENT_LIMIT_MESSAGE)
                    p._throttle_event("ConcurrentRateLimit", ident)
                else:
                    granted[ns] = granted.get(ns, 0) + 1
        for ((_ident, _fire), fut), rej in zip(batch, rejection):
            if rej is not None and not fut.done():
                fut.set_exception(rej)

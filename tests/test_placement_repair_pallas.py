"""Fused Pallas speculate-and-repair kernel: parity, selector, calibration
(ISSUE 10).

The Pallas repair kernel (`ops.placement_pallas.schedule_batch_repair_pallas`)
claims BIT-EXACT parity with `ops.placement.schedule_batch_repair` — and
therefore with the scan oracle — by construction: the conflict rules are ONE
shared function (`repair_commit_masks`) and only the index primitives differ
(`flat_prims` scatter/sort vs `pairwise_prims` [B,B] masks). The suites here
are the proof the three-backend selector leans on, all in interpret mode on
the CPU twin (the bench parity stage asserts the same on live hardware):

  * parity fuzz reusing test_placement_repair's generators (mixed
    partitions, forced overload, container-open permit flips, cascade
    overflow, unhealthy/invalid rows, OOB slots, the 64k slow row) with
    ROUND-COUNT equality against the XLA repair kernel — same rules, same
    commit sets, same trip count;
  * prims equivalence fuzz (the only place the implementations could
    drift);
  * compile census through the packed entry point (1 compile/signature,
    zero unexpected — speculation in VMEM must not reintroduce churn);
  * the 3x3 placementKernel x kernel selector matrix (repair no longer
    pins XLA), the VMEM-budget fallback regression, and the
    calibration-driven backend swap riding the prewarm drainer with a
    quiet recompile watchdog.
"""
import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from openwhisk_tpu.ops.placement import (  # noqa: E402
    RequestBatch, flat_prims, init_state, make_fused_step_packed,
    pairwise_prims, release_batch_vector, schedule_batch,
    schedule_batch_repair, unpack_step_output)
from tests.test_placement_repair import (  # noqa: E402
    _random_batch, _random_state)

pallas_mark = pytest.mark.pallas


# ---------------------------------------------------------------------------
# prims equivalence: the only backend-specific code in the repair algorithm
# ---------------------------------------------------------------------------

class TestPrimsEquivalence:
    @pytest.mark.parametrize("seed", range(8))
    def test_pairwise_matches_flat(self, seed):
        """Every RepairPrims helper must agree bit-for-bit between the
        scatter/sort (XLA) and pairwise (Mosaic) implementations — a drift
        here is a drift between the production kernels."""
        rng = np.random.RandomState(seed)
        b = int(rng.choice([4, 8, 16, 64]))
        size = int(rng.choice([4, 16, 128]))
        flag = jnp.asarray(rng.rand(b) < 0.4)
        key = jnp.asarray(rng.randint(0, size, b).astype(np.int32))
        vals = jnp.asarray(rng.randint(0, 512, b).astype(np.int32))
        fp = flat_prims(b)
        pp = pairwise_prims(b)

        def col(x):
            return jnp.asarray(np.asarray(x).reshape(b, 1))

        np.testing.assert_array_equal(
            np.asarray(fp.first_index_where(flag, key, size)),
            np.asarray(pp.first_index_where(col(flag), col(key),
                                            size)).reshape(b))
        np.testing.assert_array_equal(
            np.asarray(fp.any_same_key(flag, key, size)),
            np.asarray(pp.any_same_key(col(flag), col(key),
                                       size)).reshape(b))
        np.testing.assert_array_equal(
            np.asarray(fp.segment_exclusive_sum(vals, key)),
            np.asarray(pp.segment_exclusive_sum(col(vals),
                                                col(key))).reshape(b))
        np.testing.assert_array_equal(
            np.asarray(fp.exclusive_cumsum(vals)),
            np.asarray(pp.exclusive_cumsum(col(vals))).reshape(b))
        np.testing.assert_array_equal(
            np.asarray(fp.exclusive_cummax(vals)),
            np.asarray(pp.exclusive_cummax(col(vals))).reshape(b))
        np.testing.assert_array_equal(
            np.asarray(fp.min_index_where(flag)).reshape(()),
            np.asarray(pp.min_index_where(col(flag))).reshape(()))


# ---------------------------------------------------------------------------
# kernel parity (interpret mode)
# ---------------------------------------------------------------------------

def _pallas_repair(state, batch):
    from openwhisk_tpu.ops.placement_pallas import (
        schedule_batch_repair_pallas, to_transposed)
    ts, chosen, forced, rounds = schedule_batch_repair_pallas(
        to_transposed(state), batch, interpret=True)
    from openwhisk_tpu.ops.placement import PlacementState
    return (PlacementState(ts.free_mb, ts.conc_free.T, ts.health), chosen,
            forced, rounds)


def _assert_repair_parity(state, batch, check_rounds=True):
    s_state, s_chosen, s_forced = schedule_batch(state, batch)
    x_state, x_chosen, x_forced, x_rounds = schedule_batch_repair(state,
                                                                 batch)
    p_state, p_chosen, p_forced, p_rounds = _pallas_repair(state, batch)
    np.testing.assert_array_equal(np.asarray(s_chosen), np.asarray(p_chosen))
    np.testing.assert_array_equal(np.asarray(s_forced), np.asarray(p_forced))
    np.testing.assert_array_equal(np.asarray(s_state.free_mb),
                                  np.asarray(p_state.free_mb))
    np.testing.assert_array_equal(np.asarray(s_state.conc_free),
                                  np.asarray(p_state.conc_free))
    if check_rounds:
        # shared rules + shared commit sets => the residue loops take the
        # SAME number of rounds (the drift canary the rounds family needs)
        assert int(p_rounds) == int(x_rounds)
    return p_state, int(p_rounds)


@pallas_mark
class TestPallasRepairParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_fuzz_parity_with_scan_oracle(self, seed):
        """Randomized fleets/batches: placements, forced flags, books AND
        round counts identical, across chained steps (the second step runs
        on books the first step dirtied)."""
        rng = np.random.RandomState(seed)
        n = int(rng.choice([4, 8, 16, 64]))
        b = int(rng.choice([8, 32, 64]))
        mem = int(rng.choice([512, 1024, 4096]))
        state = _random_state(n, rng, mem=mem)
        for _ in range(2):
            batch = _random_batch(n, b, rng)
            state, rounds = _assert_repair_parity(state, batch)
            assert rounds >= 1

    def test_overload_forced_parity(self):
        """Memory pressure forces random-rotation placement (over-commit):
        the in-kernel residue loop must serialize the forced cascade
        identically."""
        rng = np.random.RandomState(42)
        n, b = 4, 64
        state = init_state(n, [256] * n, action_slots=8)
        for _ in range(2):
            batch = _random_batch(n, b, rng, mem_choices=(256, 512))
            state, _ = _assert_repair_parity(state, batch)
        assert (np.asarray(state.free_mb) < 256).all()  # pressure was real

    def test_container_open_flips_later_choice(self):
        """A max_conc>1 placement OPENS permits on its conc column — the
        hard-conflict class the shared rules must serialize in-kernel."""
        n, b = 4, 16
        state = init_state(n, [256] * n, action_slots=4)
        mk = lambda x: jnp.full((b,), x, jnp.int32)  # noqa: E731
        batch = RequestBatch(mk(0), mk(n), jnp.arange(b, dtype=jnp.int32) % n,
                             mk(1), mk(256), mk(2), mk(4),
                             mk(0), jnp.ones((b,), bool))
        _assert_repair_parity(state, batch)

    def test_same_action_burst_memory_cascade_overflow(self):
        """A one-action burst on a tiny partition: the memory cascade
        commits the run without serializing, and must still match the scan
        exactly when the invoker overflows mid-burst."""
        n, b = 2, 32
        state = init_state(n, [1024] * n, action_slots=4)
        mk = lambda x: jnp.full((b,), x, jnp.int32)  # noqa: E731
        batch = RequestBatch(mk(0), mk(n), mk(0), mk(1), mk(128), mk(1),
                             mk(1), jnp.arange(b, dtype=jnp.int32) % n,
                             jnp.ones((b,), bool))
        _assert_repair_parity(state, batch)

    def test_no_usable_invokers_settle_in_one_round(self):
        rng = np.random.RandomState(7)
        n, b = 8, 16
        state = init_state(n, [1024] * n, action_slots=8)
        state = state._replace(health=jnp.zeros((n,), bool))
        batch = _random_batch(n, b, rng)
        p_state, p_chosen, p_forced, p_rounds = _pallas_repair(state, batch)
        assert (np.asarray(p_chosen) == -1).all()
        assert not np.asarray(p_forced).any()
        assert int(p_rounds) == 1

    def test_out_of_range_slots_match_xla_scatter_semantics(self):
        """OOB slot ids: reads clamp, writes AND slot-keyed conflict marks
        drop — the slot_ok plumbing through the shared rules."""
        n, a = 32, 4
        state = init_state(n, [512] * n, action_slots=a)

        def mk(slots, max_concs):
            b = len(slots)
            z = jnp.zeros((b,), jnp.int32)
            return RequestBatch(
                offset=z, size=jnp.full((b,), n, jnp.int32), home=z,
                step_inv=jnp.ones((b,), jnp.int32),
                need_mb=jnp.full((b,), 128, jnp.int32),
                conc_slot=jnp.asarray(slots, jnp.int32),
                max_conc=jnp.asarray(max_concs, jnp.int32),
                rand=z, valid=jnp.ones((b,), bool))

        # rounds intentionally unchecked: the XLA scatters DROP an OOB
        # writer's conflict marks while the pallas path folds slot_ok into
        # the same drop — outcome parity is the contract here
        _assert_repair_parity(state, mk([9, 3, 3, 9], [4, 4, 4, 1]),
                              check_rounds=False)

    @pytest.mark.slow
    def test_parity_at_64k_fleet_memory_dominant(self):
        """The fleet >> batch production shape at the 64k north-star size,
        memory-dominant traffic (the bulk): interpret mode is slow, so the
        batch stays modest — the [B, N] vector math is what's exercised."""
        rng = np.random.RandomState(3)
        n, b = 65536, 128
        state = _random_state(n, rng, mem=2048, unhealthy_p=0.05)
        batch = _random_batch(n, b, rng, maxc_choices=(1,))
        _, rounds = _assert_repair_parity(state, batch)
        assert rounds <= 4


# ---------------------------------------------------------------------------
# packed entry point: trailing rounds + compile census
# ---------------------------------------------------------------------------

def _packed_buf(rng, n, r, h, b, slots=16):
    batch = _random_batch(n, b, rng, slots=slots)
    rel = np.zeros((5, r), np.int32)
    rel[3] = 1
    health = np.zeros((3, h), np.int32)
    req = np.stack([np.asarray(x, np.int32) for x in
                    (batch.offset, batch.size, batch.home, batch.step_inv,
                     batch.need_mb, batch.conc_slot, batch.max_conc,
                     batch.rand, batch.valid)])
    return np.concatenate([rel.ravel(), health.ravel(), req.ravel()])


def _pallas_repair_sched():
    from openwhisk_tpu.controller.loadbalancer.tpu_balancer import \
        _pallas_pair
    return _pallas_pair("repair")


@pallas_mark
class TestPallasPackedPath:
    def test_packed_step_trailing_rounds_element(self):
        """The packed output keeps the B+1 layout (trailing repair-round
        count), so the flight recorder and loadbalancer_repair_rounds
        family work unchanged on the pallas backend."""
        rng = np.random.RandomState(0)
        n, b = 32, 16
        state = _random_state(n, rng)
        buf = _packed_buf(rng, n, 8, 4, b)
        sched, release, resolved = _pallas_repair_sched()
        assert resolved == "repair"
        fn = make_fused_step_packed(release, sched)
        _, out = fn(state, jnp.asarray(buf), 8, 4, b)
        assert out.shape == (b + 1,)
        chosen, forced, throttled, rounds = unpack_step_output(
            np.asarray(out))
        assert chosen.shape == (b,)
        assert rounds >= 1
        # and the XLA repair pair derives the SAME decisions and rounds
        fn_x = make_fused_step_packed(release_batch_vector,
                                      schedule_batch_repair)
        state_x = _random_state(n, np.random.RandomState(0))
        _, out_x = fn_x(state_x, jnp.asarray(buf), 8, 4, b)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out_x))

    def test_pallas_repair_compiles_once_per_bucket_signature(self):
        """PR-3 watchdog contract on the pallas backend: one compile per
        (R, H, B) bucket signature, zero unexpected — the in-kernel
        residue loop must not reintroduce shape churn."""
        from openwhisk_tpu.ops.profiler import (KernelProfiler,
                                                ProfilingConfig, pow2_statics)
        prof = KernelProfiler(ProfilingConfig(enabled=True))
        sched, release, _ = _pallas_repair_sched()
        fn = prof.wrap("fused_step", make_fused_step_packed(release, sched),
                       expected=pow2_statics)
        rng = np.random.RandomState(3)
        n = 32
        state = _random_state(n, rng)
        sigs = [(8, 4, 8), (8, 4, 16)]
        for repeat in range(3):
            for (r, h, b) in sigs:
                buf = jnp.asarray(_packed_buf(
                    np.random.RandomState(10 + repeat), n, r, h, b))
                state, _ = fn(state, buf, r, h, b)
        census = prof.cache_census()["fused_step"]
        assert census["compiles"] == len(sigs)
        assert census["signatures"] == len(sigs)
        assert census["calls"] == 3 * len(sigs)
        assert prof.compiles_unexpected == 0


# ---------------------------------------------------------------------------
# balancer selector, VMEM fallback, calibration
# ---------------------------------------------------------------------------

from openwhisk_tpu.controller.loadbalancer import TpuBalancer  # noqa: E402
from openwhisk_tpu.core.entity import (ControllerInstanceId,  # noqa: E402
                                       Identity)
from openwhisk_tpu.messaging import MemoryMessagingProvider  # noqa: E402
from tests.test_balancers import (_fleet, _ping_all, make_action,  # noqa: E402
                                  make_msg)


def _mk_balancer(provider, **kw):
    kw.setdefault("managed_fraction", 1.0)
    kw.setdefault("blackbox_fraction", 0.0)
    kw.setdefault("initial_pad", 16)
    kw.setdefault("action_slots", 64)
    kw.setdefault("max_batch", 64)
    return TpuBalancer(provider, ControllerInstanceId("0"), **kw)


@pallas_mark
class TestSelectorMatrix:
    @pytest.mark.parametrize("kernel,pk,want_backend,want_resolved", [
        ("xla", "scan", "xla", "scan"),
        ("xla", "repair", "xla", "repair"),
        ("xla", "auto", "xla", "repair"),
        ("pallas", "scan", "pallas", "scan"),
        ("pallas", "repair", "pallas", "repair"),
        ("pallas", "auto", "pallas", "repair"),
        # the CPU twin's static auto resolver: xla (pallas = interpret)
        ("auto", "scan", "xla", "scan"),
        ("auto", "repair", "xla", "repair"),
        ("auto", "auto", "xla", "repair"),
    ])
    def test_env_knob_matrix(self, monkeypatch, kernel, pk, want_backend,
                             want_resolved):
        """The full 3x3 placementKernel x kernel matrix through the ENV
        knobs — in particular placementKernel=repair no longer pins the
        XLA path (the fused pallas repair kernel exists now)."""
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_placementKernel", pk)
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_kernel", kernel)
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_calibrateKernel", "off")
        bal = _mk_balancer(MemoryMessagingProvider())
        assert bal.kernel == kernel  # the backend knob reads the env too
        assert bal.kernel_resolved == want_backend
        assert bal.placement_kernel_resolved == want_resolved
        if want_backend == "pallas":
            kind = getattr(bal._sched_fn, "_pallas_kind", None)
            assert kind == ("repair" if pk == "repair" else
                            "auto" if pk == "auto" else "scan")

    def test_pallas_repair_places_end_to_end(self):
        """publish() -> device step -> readback through the fused pallas
        repair kernel on the CPU twin (interpret), books and slots
        balanced, zero unexpected recompiles."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = _mk_balancer(provider, kernel="pallas",
                               placement_kernel="repair",
                               batch_window=0.001)
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=2048)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            for i in range(6):
                a = make_action(f"pr{i % 2}", memory=128)
                await (await bal.publish(a, make_msg(a, ident, True)))
            prof = bal.kernel_profile()
            assert prof["kernel"] == "pallas"
            assert prof["placement_kernel"] == "repair"
            assert prof["compiles"]["unexpected"] == 0
            await bal.close()
            for inv in invokers:
                await inv.stop()

        asyncio.run(go())

    def test_explicit_pallas_vmem_fallback_logs_and_runs_xla(self,
                                                             monkeypatch):
        """Satellite regression: explicit kernel=pallas that fails the
        (device-read) VMEM fit keeps the fall-back-and-log behavior — the
        balancer runs XLA and says why."""
        from openwhisk_tpu.ops import placement_pallas as pp
        monkeypatch.setenv("OPENWHISK_TPU_VMEM_BYTES", str(4 * 1024))
        pp._reset_vmem_budget_cache()
        try:
            logs = []

            class Log:
                def warn(self, *a, **k):
                    logs.append(" ".join(str(x) for x in a))

                def info(self, *a, **k):
                    pass

                def error(self, *a, **k):
                    pass

            bal = _mk_balancer(MemoryMessagingProvider(), kernel="pallas",
                               logger=Log())
            assert bal.kernel_resolved == "xla"
            assert bal.kernel == "xla"  # pinned off for later rebuilds
            assert any("does not fit" in line or "unavailable" in line
                       for line in logs)
        finally:
            monkeypatch.delenv("OPENWHISK_TPU_VMEM_BYTES")
            pp._reset_vmem_budget_cache()

    def test_vmem_budget_env_override_and_repair_scratch(self, monkeypatch):
        from openwhisk_tpu.ops import placement_pallas as pp
        monkeypatch.setenv("OPENWHISK_TPU_VMEM_BYTES",
                           str(64 * 1024 * 1024))
        pp._reset_vmem_budget_cache()
        try:
            assert pp.vmem_budget_bytes() == 32 * 1024 * 1024
            assert pp.fits_vmem(1024, 256)
            # the repair kernel budgets [B, N] residue scratch on top of
            # the resident state: same geometry, bigger footprint
            assert pp.fits_vmem_repair(1024, 256, 256)
            assert not pp.fits_vmem_repair(16384, 256, 1024)
        finally:
            monkeypatch.delenv("OPENWHISK_TPU_VMEM_BYTES")
            pp._reset_vmem_budget_cache()


@pallas_mark
class TestCalibration:
    def test_auto_picks_by_measured_rate_off_the_event_loop(self):
        """kernel=auto + calibrate_kernel=force on the CPU twin: the
        calibration microbench rides the prewarm drainer (never the event
        loop), caches per-bucket measured rates, applies the winner with
        prewarmed fns, and the recompile watchdog records ZERO
        expected=false trips across the mid-run swap."""
        import openwhisk_tpu.controller.loadbalancer.tpu_balancer as tb

        async def go():
            provider = MemoryMessagingProvider()
            bal = _mk_balancer(provider, kernel="auto",
                               calibrate_kernel="force", max_batch=32,
                               batch_window=0.001)
            assert bal._kernel_chosen_by in ("static", "calibration")
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=2048)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            for i in range(8):
                a = make_action(f"cal{i % 2}", memory=128)
                await (await bal.publish(a, make_msg(a, ident, True)))
            for _ in range(200):
                if (bal._calibration is not None
                        and (bal._warm_task is None
                             or bal._warm_task.done())):
                    break
                await asyncio.sleep(0.05)
            assert bal._calibration is not None
            rates = bal._calibration["rates"]
            assert rates.get("xla")  # both backends actually measured
            assert "pallas" in rates
            assert bal._kernel_chosen_by == "calibration"
            # the running backend follows the geometry's largest-bucket
            # verdict (the restart rule), not any single row
            assert bal.kernel_resolved == tb.cached_backend_choice(
                bal._n_pad, bal.action_slots, bal.placement_kernel)
            # the cache is module-level and keyed per bucket shape
            assert any(k[0] == jax.default_backend()
                       for k in tb._KERNEL_CALIBRATION)
            # a swap (if any) left the watchdog silent
            assert bal.kernel_profile()["compiles"]["unexpected"] == 0
            # and the balancer still places on the chosen backend
            a = make_action("cal9", memory=128)
            await (await bal.publish(a, make_msg(a, ident, True)))
            assert bal.kernel_profile()["compiles"]["unexpected"] == 0
            # the info-style gauge carries the verdict
            assert bal.metrics.gauge_value(
                "loadbalancer_kernel_backend",
                tags={"backend": bal.kernel_resolved,
                      "placement": bal.placement_kernel_resolved,
                      "chosen_by": "calibration"}) == 1
            await bal.close()
            for inv in invokers:
                await inv.stop()

        asyncio.run(go())

    def test_calibration_off_on_cpu_by_default(self):
        bal = _mk_balancer(MemoryMessagingProvider(), kernel="auto")
        assert bal.calibrate_kernel == "auto"
        assert bal._calibration_enabled() is (jax.default_backend() == "tpu")

    def test_cached_choice_survives_restart(self):
        """A fresh balancer with a calibrated geometry adopts the cached
        measured verdict at construction (no re-bench, no loop work)."""
        import openwhisk_tpu.controller.loadbalancer.tpu_balancer as tb
        saved = dict(tb._KERNEL_CALIBRATION)
        tb._KERNEL_CALIBRATION.clear()  # hermetic: module cache is global
        key = (jax.default_backend(), 16, 64, "auto", 8, 8, 8)
        tb._KERNEL_CALIBRATION[key] = {
            "rates": {"xla": 1.0, "pallas": 99.0}, "winner": "pallas",
            "platform": key[0], "n_pad": 16, "action_slots": 64,
            "placement_kernel": "auto", "sig": [8, 8, 8], "iters": 1}
        try:
            bal = _mk_balancer(MemoryMessagingProvider(), kernel="auto",
                               calibrate_kernel="off")
            assert bal.kernel_resolved == "pallas"
            assert bal._kernel_chosen_by == "calibration"
        finally:
            tb._KERNEL_CALIBRATION.clear()
            tb._KERNEL_CALIBRATION.update(saved)

    def test_one_sided_calibration_keeps_incumbent(self, monkeypatch):
        """Review regression: when pallas cannot be measured at the live
        geometry (repair scratch does not fit), calibration must NOT let
        an xla-only bench "win" by default and demote the statically
        chosen backend — it stands down entirely."""
        from openwhisk_tpu.ops import placement_pallas as pp
        bal = _mk_balancer(MemoryMessagingProvider(), kernel="auto",
                           calibrate_kernel="force")
        monkeypatch.setattr(pp, "fits_vmem_repair", lambda *a: False)
        monkeypatch.setattr(pp, "fits_vmem", lambda *a: False)
        assert bal._maybe_calibrate((8, 8, 8)) is None
        assert bal._calibration is None

    def test_swap_verdict_follows_largest_measured_bucket(self):
        """Review regression: the swap decision follows the LARGEST
        measured bucket for the geometry (the cached_backend_choice
        restart rule), not the just-calibrated signature's own row — a
        small bucket's noise verdict must not ping-pong the backend."""
        import openwhisk_tpu.controller.loadbalancer.tpu_balancer as tb
        saved = dict(tb._KERNEL_CALIBRATION)
        tb._KERNEL_CALIBRATION.clear()  # hermetic: module cache is global
        try:
            bal = _mk_balancer(MemoryMessagingProvider(), kernel="auto",
                               calibrate_kernel="force", max_batch=32)
            assert bal.kernel_resolved == "xla"  # static CPU resolve
            platform = jax.default_backend()
            geo = (platform, bal._n_pad, bal.action_slots, "auto")
            tb._KERNEL_CALIBRATION[geo + (8, 8, 8)] = {
                "rates": {"xla": 9.0, "pallas": 1.0}, "winner": "xla",
                "platform": platform, "n_pad": bal._n_pad,
                "action_slots": bal.action_slots, "placement_kernel": "auto",
                "sig": [8, 8, 8], "iters": 1}
            tb._KERNEL_CALIBRATION[geo + (8, 8, 32)] = {
                "rates": {"xla": 1.0, "pallas": 9.0}, "winner": "pallas",
                "platform": platform, "n_pad": bal._n_pad,
                "action_slots": bal.action_slots, "placement_kernel": "auto",
                "sig": [8, 8, 32], "iters": 1}
            # calibrating the SMALL sig cache-hits its xla row, but the
            # decision must carry the big bucket's pallas verdict
            decision = bal._maybe_calibrate((8, 8, 8))
            assert decision is not None
            assert decision["kernel"] == "pallas"
        finally:
            tb._KERNEL_CALIBRATION.clear()
            tb._KERNEL_CALIBRATION.update(saved)

    def test_profiler_classifies_swap_compiles_as_expected(self):
        """Satellite: re-wrapping an entry point (a backend swap) opens a
        rebuild window — compiles of the fresh cache classify as
        kernel_swap, never shape_churn, even past first_call."""
        from openwhisk_tpu.ops.profiler import KernelProfiler, \
            ProfilingConfig

        prof = KernelProfiler(ProfilingConfig(enabled=True))
        calls = {"a": 0, "b": 0}

        def fn_a(x):
            calls["a"] += 1
            return x

        def fn_b(x):
            calls["b"] += 1
            return x

        wrapped = prof.wrap("fused_step", fn_a)
        wrapped(np.zeros((4,)))
        assert prof.compiles_unexpected == 0
        # the swap: same name, new callable — two distinct signatures
        # compile afterwards, NEITHER may read as churn
        wrapped = prof.wrap("fused_step", fn_b)
        wrapped(np.zeros((4,)))
        wrapped(np.zeros((7,)))  # not a pow2 bucket, no predicate set
        assert prof.compiles_unexpected == 0
        reasons = [e["reason"] for e in prof.compile_log(10)
                   if e["entry"] == "fused_step"]
        assert "kernel_swap" in reasons

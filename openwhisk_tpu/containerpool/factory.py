"""ContainerFactory SPI + pool configuration.

Rebuild of common/scala/.../core/containerpool/ContainerFactory.scala:29-143:
the factory creates containers for a (kind, image, memory) request and owns
cleanup of leftovers from previous lives; ContainerPoolConfig derives cpu
shares from the memory share exactly like the reference (:46-61).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..core.entity import ByteSize, MB


@dataclass
class ContainerPoolConfig:
    """Ref ContainerPoolConfig (application.conf whisk.container-pool)."""
    user_memory: ByteSize = field(default_factory=lambda: MB(2048))
    concurrent_peek_factor: float = 0.5
    akka_client: bool = False  # kept for config parity; HTTP client is aiohttp
    prewarm_expiration_check_interval: float = 60.0
    idle_container_timeout: float = 600.0   # unusedTimeout (10 min)
    pause_grace: float = 0.05               # pauseGrace (50 ms in reference)

    def cpu_share(self, memory: ByteSize, total_share: int = 1024) -> int:
        """CPU share proportional to the container's memory share of the
        pool (ref ContainerFactory.scala:46-61)."""
        return max(2, int(total_share * memory.to_mb / max(1, self.user_memory.to_mb)))


#: shorthand name -> ContainerFactoryProvider SPI path; the single source
#: of truth for the invoker's --container-factory choices and the deploy
#: inventory's invokers.container_factory validation
FACTORY_PROVIDERS = {
    "process": "openwhisk_tpu.containerpool.process_factory:ProcessContainerFactoryProvider",
    "docker": "openwhisk_tpu.containerpool.docker_factory:DockerContainerFactoryProvider",
    "kubernetes": "openwhisk_tpu.containerpool.kubernetes_factory:KubernetesContainerFactoryProvider",
    "yarn": "openwhisk_tpu.containerpool.yarn_factory:YARNContainerFactoryProvider",
    "mesos": "openwhisk_tpu.containerpool.mesos_factory:MesosContainerFactoryProvider",
}


class ContainerFactory:
    """SPI: async container creation + janitorial cleanup."""

    async def create_container(self, transid, name: str, image: str,
                               memory: ByteSize, cpu_shares: int = 0,
                               action=None):
        raise NotImplementedError

    async def init(self) -> None:
        """Post-construction hook, run by the invoker at boot. Defaults to
        reaping containers left over from a previous life (the reference
        initializes its factory with a stale-container cleanup,
        InvokerReactive.scala:129-147); drivers with a richer bootstrap
        (e.g. YARN's service registration) override this."""
        await self.cleanup()

    async def cleanup(self) -> None:
        """Remove any containers left over from a previous life
        (ref ContainerFactory.cleanup)."""

    async def close(self) -> None:
        await self.cleanup()

"""Speculate-and-repair placement kernel: bit-exactness and host-path
regressions (ISSUE 5).

The repair kernel (`ops.placement.schedule_batch_repair`) and the
vectorized release fold (`release_batch_vector`) claim BIT-EXACT parity
with the reference lax.scan pair — the fuzz suites here are the proof the
balancer's `placement_kernel="auto"` default leans on: randomized fleets
(mixed partitions, overload-forced placement, unhealthy rows, shared
concurrency slots, invalid rows), placements AND books compared exactly,
including the throttled/admit fused variant. Host-path regressions cover
the buffer-donation materialize boundaries (snapshot mid-flight under the
pipelined dispatch), occupancy served from cached books, the scan+depth-1
legacy no-op path, and the compile census (one compile per bucketed
(R, H, B) signature — speculation must not reintroduce shape churn).
"""
import asyncio
import math

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from openwhisk_tpu.ops.placement import (  # noqa: E402
    PlacementState, RequestBatch, init_state, make_fused_admit_step_packed,
    make_fused_step_packed, release_batch, release_batch_vector,
    schedule_batch, schedule_batch_repair, unpack_step_output)
from openwhisk_tpu.ops.throttle import init_buckets  # noqa: E402


def _random_batch(n, b, rng, mem_choices=(128, 256, 512), slots=16,
                  maxc_choices=(1, 1, 4), valid_p=0.95):
    """A randomized RequestBatch over mixed sub-partitions of an n-invoker
    fleet: random offset/size windows (the managed/blackbox split and
    cluster slicing), coprime probe steps, shared conc slots, container
    actions, and some invalid (padding) rows."""
    off = rng.randint(0, max(1, n // 2), b).astype(np.int32)
    size = np.maximum(1, rng.randint(1, n + 1, b) - off).astype(np.int32)
    size = np.minimum(size, n - off).astype(np.int32)
    home = (rng.randint(0, 1 << 16, b) % size).astype(np.int32)
    step_inv = np.zeros(b, np.int32)
    for i in range(b):
        s = int(size[i])
        st = rng.randint(1, s + 1)
        while math.gcd(int(st), s) != 1:
            st = rng.randint(1, s + 1)
        step_inv[i] = pow(int(st), -1, s) if s > 1 else 0
    need = rng.choice(mem_choices, b).astype(np.int32)
    slot = rng.randint(0, slots, b).astype(np.int32)
    maxc = rng.choice(maxc_choices, b).astype(np.int32)
    rand = (rng.randint(0, 1 << 20, b).astype(np.int32)
            % np.maximum(size, 1))
    valid = rng.rand(b) < valid_p
    return RequestBatch(*[jnp.asarray(x) for x in
                          (off, size, home, step_inv, need, slot, maxc,
                           rand, valid)])


def _random_state(n, rng, mem=1024, slots=16, unhealthy_p=0.2,
                  conc_p=0.3):
    st = init_state(n, [mem] * n, action_slots=slots)
    health = ~(rng.rand(n) < unhealthy_p)
    if not health.any():
        health[rng.randint(0, n)] = True
    conc = np.where(rng.rand(n, slots) < conc_p,
                    rng.randint(1, 4, (n, slots)), 0).astype(np.int32)
    return st._replace(health=jnp.asarray(health),
                       conc_free=jnp.asarray(conc))


def _assert_same_outcome(scan_out, repair_out):
    s_state, s_chosen, s_forced = scan_out
    r_state, r_chosen, r_forced = repair_out[:3]
    np.testing.assert_array_equal(np.asarray(s_chosen), np.asarray(r_chosen))
    np.testing.assert_array_equal(np.asarray(s_forced), np.asarray(r_forced))
    np.testing.assert_array_equal(np.asarray(s_state.free_mb),
                                  np.asarray(r_state.free_mb))
    np.testing.assert_array_equal(np.asarray(s_state.conc_free),
                                  np.asarray(r_state.conc_free))


class TestRepairKernelParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_parity_with_scan_oracle(self, seed):
        """Randomized fleets/batches: placements, forced flags and books
        bit-identical to the scan oracle, across chained steps (the second
        step runs on books the first step dirtied)."""
        rng = np.random.RandomState(seed)
        n = int(rng.choice([4, 8, 16, 64, 256]))
        b = int(rng.choice([8, 32, 64]))
        mem = int(rng.choice([512, 1024, 4096]))
        s_state = r_state = _random_state(n, rng, mem=mem)
        for step in range(3):
            batch = _random_batch(n, b, rng)
            s_out = schedule_batch(s_state, batch)
            r_out = schedule_batch_repair(r_state, batch)
            _assert_same_outcome(s_out, r_out)
            s_state, r_state = s_out[0], r_out[0]
            assert int(r_out[3]) >= 1  # at least one commit round ran

    def test_overload_forced_parity(self):
        """Memory pressure forces random-rotation placement (over-commit):
        the repair loop must serialize the forced cascade identically."""
        rng = np.random.RandomState(42)
        n, b = 4, 64
        state = init_state(n, [256] * n, action_slots=8)
        for _ in range(3):
            batch = _random_batch(n, b, rng, mem_choices=(256, 512))
            s_out = schedule_batch(state, batch)
            r_out = schedule_batch_repair(state, batch)
            _assert_same_outcome(s_out, r_out)
            state = s_out[0]
        assert np.asarray(s_out[2]).any()  # the scenario actually forced

    def test_no_usable_invokers_all_unplaced(self):
        n, b = 8, 16
        rng = np.random.RandomState(7)
        state = init_state(n, [1024] * n, action_slots=8)
        state = state._replace(health=jnp.zeros((n,), bool))
        batch = _random_batch(n, b, rng)
        r_state, chosen, forced, rounds = schedule_batch_repair(state, batch)
        assert (np.asarray(chosen) == -1).all()
        assert not np.asarray(forced).any()
        np.testing.assert_array_equal(np.asarray(r_state.free_mb),
                                      np.asarray(state.free_mb))
        # unplaceable rows are outcome-invariant: one round settles them
        assert int(rounds) == 1

    def test_same_action_burst_memory_cascade(self):
        """A burst of one simple action on a tiny partition is the memory-
        cascade fast path: prefix sums commit the whole run without
        serializing — and must still match the scan exactly when the
        invoker overflows mid-burst."""
        n, b = 2, 32
        state = init_state(n, [1024] * n, action_slots=4)
        mk = lambda x: jnp.full((b,), x, jnp.int32)  # noqa: E731
        batch = RequestBatch(mk(0), mk(n), mk(0), mk(1), mk(128), mk(1),
                             mk(1), jnp.arange(b, dtype=jnp.int32) % n,
                             jnp.ones((b,), bool))
        s_out = schedule_batch(state, batch)
        r_out = schedule_batch_repair(state, batch)
        _assert_same_outcome(s_out, r_out)

    def test_container_open_flips_later_choice(self):
        """A max_conc>1 placement OPENS permits on its conc column, which
        can hand a better-ranked invoker to a later request in the same
        batch — the hard-conflict rule the repair loop must serialize."""
        n, b = 4, 16
        state = init_state(n, [256] * n, action_slots=4)
        mk = lambda x: jnp.full((b,), x, jnp.int32)  # noqa: E731
        batch = RequestBatch(mk(0), mk(n), jnp.arange(b, dtype=jnp.int32) % n,
                             mk(1), mk(256), mk(2), mk(4),
                             mk(0), jnp.ones((b,), bool))
        s_out = schedule_batch(state, batch)
        r_out = schedule_batch_repair(state, batch)
        _assert_same_outcome(s_out, r_out)

    @pytest.mark.slow
    def test_parity_at_64k_fleet(self):
        rng = np.random.RandomState(3)
        n, b = 65536, 256
        state = _random_state(n, rng, mem=2048, unhealthy_p=0.05)
        batch = _random_batch(n, b, rng)
        s_out = schedule_batch(state, batch)
        r_out = schedule_batch_repair(state, batch)
        _assert_same_outcome(s_out, r_out)
        # the mixed batch crams a third of its rows (max_conc>1) into 16
        # shared conc slots: conc-column writers are hard conflicts BY
        # DESIGN (they never commute with order-inverted column reads), so
        # this shape serializes partially — measured 23 rounds — and only
        # the "well below B" contract applies
        assert int(r_out[3]) < b // 4

        # the fleet >> batch claim proper: memory-dominant traffic (the
        # production bulk; max_conc <= 1) sees almost no conflicts
        rng2 = np.random.RandomState(3)
        state2 = _random_state(n, rng2, mem=2048, unhealthy_p=0.05)
        batch2 = _random_batch(n, b, rng2, maxc_choices=(1,))
        s2 = schedule_batch(state2, batch2)
        r2 = schedule_batch_repair(state2, batch2)
        _assert_same_outcome(s2, r2)
        assert int(r2[3]) <= 4


class TestReleaseVectorParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_fuzz_parity_with_scan_release(self, seed):
        rng = np.random.RandomState(seed)
        n = int(rng.choice([4, 16, 64]))
        r = int(rng.choice([8, 32, 64]))
        st = _random_state(n, rng, conc_p=0.5)
        inv = jnp.asarray(rng.randint(0, n, r).astype(np.int32))
        slot = jnp.asarray(rng.randint(0, 16, r).astype(np.int32))
        need = jnp.asarray(rng.choice([128, 256], r).astype(np.int32))
        maxc = jnp.asarray(rng.choice([1, 4, 4, 6], r).astype(np.int32))
        valid = jnp.asarray(rng.rand(r) < 0.9)
        a = release_batch(st, inv, slot, need, maxc, valid)
        b = release_batch_vector(st, inv, slot, need, maxc, valid)
        np.testing.assert_array_equal(np.asarray(a.free_mb),
                                      np.asarray(b.free_mb))
        np.testing.assert_array_equal(np.asarray(a.conc_free),
                                      np.asarray(b.conc_free))

    def test_heterogeneous_group_replays_every_row(self):
        """Slot-conflation regression: when two actions share a hashed slot
        on one invoker, the WHOLE group replays sequentially — the
        leader-matching rows must not be dropped with the bulk apply."""
        n = 2
        st = init_state(n, [4096] * n, action_slots=4)
        st = st._replace(conc_free=st.conc_free.at[0, 1].set(2))
        # rows 0 and 2 match the leader (need=256, maxc=3); row 1 conflates
        inv = jnp.asarray([0, 0, 0], jnp.int32)
        slot = jnp.asarray([1, 1, 1], jnp.int32)
        need = jnp.asarray([256, 512, 256], jnp.int32)
        maxc = jnp.asarray([3, 4, 3], jnp.int32)
        valid = jnp.ones((3,), bool)
        a = release_batch(st, inv, slot, need, maxc, valid)
        b = release_batch_vector(st, inv, slot, need, maxc, valid)
        np.testing.assert_array_equal(np.asarray(a.free_mb),
                                      np.asarray(b.free_mb))
        np.testing.assert_array_equal(np.asarray(a.conc_free),
                                      np.asarray(b.conc_free))


def _packed_buf(rng, n, r, h, b, rows=9, slots=16):
    batch = _random_batch(n, b, rng, slots=slots)
    rel = np.zeros((5, r), np.int32)
    rel[3] = 1
    health = np.zeros((3, h), np.int32)
    req = np.stack([np.asarray(x, np.int32) for x in
                    (batch.offset, batch.size, batch.home, batch.step_inv,
                     batch.need_mb, batch.conc_slot, batch.max_conc,
                     batch.rand, batch.valid)])
    if rows == 10:
        req = np.concatenate(
            [req, rng.randint(0, 4, (1, b)).astype(np.int32)])
    return np.concatenate([rel.ravel(), health.ravel(), req.ravel()])


class TestFusedPackedParity:
    def test_packed_step_trailing_rounds_element(self):
        rng = np.random.RandomState(0)
        n, b = 32, 16
        state = _random_state(n, rng)
        buf = _packed_buf(rng, n, 8, 4, b)
        fn = make_fused_step_packed(release_batch_vector,
                                    schedule_batch_repair)
        _, out = fn(state, jnp.asarray(buf), 8, 4, b)
        assert out.shape == (b + 1,)
        chosen, forced, throttled, rounds = unpack_step_output(
            np.asarray(out))
        assert chosen.shape == (b,)
        assert rounds >= 1
        # the scan pair reports rounds == 0 through the same contract
        _, out_s = make_fused_step_packed()(state, jnp.asarray(buf), 8, 4, b)
        s = unpack_step_output(np.asarray(out_s))
        assert s[3] == 0
        np.testing.assert_array_equal(chosen, s[0])

    def test_admit_variant_parity_scan_vs_repair(self):
        """The throttled/admit fused step: same packed buffer + bucket
        carry through both kernel pairs -> identical decisions, throttle
        flags, books AND bucket state."""
        rng = np.random.RandomState(1)
        n, r, h, b = 32, 8, 4, 16
        buf = jnp.asarray(_packed_buf(rng, n, r, h, b, rows=10))
        outs = {}
        for name, (rel_fn, sched_fn) in {
                "scan": (release_batch, schedule_batch),
                "repair": (release_batch_vector, schedule_batch_repair)}.items():
            state = _random_state(n, np.random.RandomState(99))
            buckets = init_buckets(64, 6)
            fn = make_fused_admit_step_packed(rel_fn, sched_fn)
            (state, buckets), out = fn((state, buckets), buf,
                                       np.float32(1.0), r, h, b)
            outs[name] = (np.asarray(out)[:-1], np.asarray(state.free_mb),
                          np.asarray(state.conc_free),
                          np.asarray(buckets.tokens))
        for a, bb in zip(outs["scan"], outs["repair"]):
            np.testing.assert_array_equal(a, bb)

    def test_donated_packed_step_invalidates_input_state(self):
        """donate=True consumes the input state's buffers: correctness
        first (same outputs as undonated), and the caller contract — the
        pre-call reference must not be reused (the balancer's materialize
        boundaries exist because of this)."""
        rng = np.random.RandomState(2)
        n, b = 16, 8
        state = _random_state(n, rng)
        free0 = np.asarray(state.free_mb).copy()
        buf = jnp.asarray(_packed_buf(rng, n, 8, 4, b))
        fn = make_fused_step_packed(release_batch_vector,
                                    schedule_batch_repair, donate=True)
        ref = make_fused_step_packed(release_batch_vector,
                                     schedule_batch_repair)
        state2 = PlacementState(jnp.asarray(free0),
                                jnp.asarray(np.asarray(state.conc_free)),
                                jnp.asarray(np.asarray(state.health)))
        _, out_ref = ref(state2, buf, 8, 4, b)
        new_state, out = fn(state, buf, 8, 4, b)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out_ref))
        # the output is always safe to read; the donated input may be
        # gone (backends without donation support keep it alive — both
        # are within contract, so only assert the output)
        assert np.asarray(new_state.free_mb).shape == (n,)


class TestCompileCensus:
    def test_repair_kernel_compiles_once_per_bucket_signature(self):
        """PR-3 watchdog contract: the repair kernel compiles exactly once
        per (R, H, B) bucket signature and NEVER as unexpected shape churn
        — speculation must not reintroduce per-batch recompiles."""
        from openwhisk_tpu.ops.profiler import (KernelProfiler,
                                                ProfilingConfig, pow2_statics)
        prof = KernelProfiler(ProfilingConfig(enabled=True))
        fn = prof.wrap("fused_step",
                       make_fused_step_packed(release_batch_vector,
                                              schedule_batch_repair),
                       expected=pow2_statics)
        rng = np.random.RandomState(3)
        n = 32
        state = _random_state(n, rng)
        sigs = [(8, 4, 8), (8, 4, 16), (16, 4, 16)]
        for repeat in range(3):
            for (r, h, b) in sigs:
                buf = jnp.asarray(_packed_buf(
                    np.random.RandomState(10 + repeat), n, r, h, b))
                state, _ = fn(state, buf, r, h, b)
        census = prof.cache_census()["fused_step"]
        assert census["compiles"] == len(sigs)
        assert census["signatures"] == len(sigs)
        assert census["calls"] == 3 * len(sigs)
        assert prof.compiles_unexpected == 0


# ---------------------------------------------------------------------------
# balancer host path: donation boundaries, occupancy cache, legacy no-op
# ---------------------------------------------------------------------------

from openwhisk_tpu.controller.loadbalancer import TpuBalancer  # noqa: E402
from openwhisk_tpu.core.entity import ControllerInstanceId, Identity  # noqa: E402
from openwhisk_tpu.messaging import MemoryMessagingProvider  # noqa: E402
from tests.test_balancers import (_fleet, _ping_all, make_action,  # noqa: E402
                                  make_msg)


def _mk_balancer(provider, **kw):
    kw.setdefault("managed_fraction", 1.0)
    kw.setdefault("blackbox_fraction", 0.0)
    return TpuBalancer(provider, ControllerInstanceId("0"), **kw)


class TestBalancerHostPath:
    def test_placement_kernel_env_knob(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_placementKernel",
                           "scan")
        bal = _mk_balancer(MemoryMessagingProvider())
        assert bal.placement_kernel == "scan"
        assert bal.placement_kernel_resolved == "scan"
        assert bal._sched_fn is schedule_batch
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_placementKernel",
                           "repair")
        bal2 = _mk_balancer(MemoryMessagingProvider())
        assert bal2.placement_kernel_resolved == "repair"
        assert bal2._sched_fn is schedule_batch_repair
        # constructor overrides env
        bal3 = _mk_balancer(MemoryMessagingProvider(),
                            placement_kernel="scan")
        assert bal3.placement_kernel_resolved == "scan"
        with pytest.raises(ValueError):
            _mk_balancer(MemoryMessagingProvider(), placement_kernel="bogus")

    def test_donation_env_knob_off(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_donateState", "false")
        bal = _mk_balancer(MemoryMessagingProvider())
        assert bal.donate_state is False and bal._donate is False
        # materialize is then a pass-through of the live reference
        assert bal._materialize_state() is bal.state

    @pytest.mark.skipif(jax.default_backend() != "cpu",
                        reason="exercises the CPU-backend donation gate")
    def test_donation_auto_gates_off_on_cpu_backend(self):
        """XLA:CPU cannot alias donated buffers and runs donated programs
        synchronously at dispatch — the default config must auto-gate
        donation off there (knob intent preserved for real devices), while
        an explicit constructor True still pins it for boundary tests."""
        bal = _mk_balancer(MemoryMessagingProvider())
        assert bal.donate_state is True and bal._donate is False
        pinned = _mk_balancer(MemoryMessagingProvider(), donate_state=True)
        assert pinned._donate is True

    def test_prewarm_knob_off_disables_compile_ahead(self, monkeypatch):
        monkeypatch.setenv("CONFIG_whisk_loadBalancer_prewarm", "false")
        bal = _mk_balancer(MemoryMessagingProvider())
        assert bal.prewarm is False
        bal._prewarm_buckets(8, 8, 8)
        assert bal._warm_sigs == set() and bal._warm_queue == []
        # default (env cleared): compile-ahead is on
        monkeypatch.delenv("CONFIG_whisk_loadBalancer_prewarm")
        warm = _mk_balancer(MemoryMessagingProvider())
        assert warm.prewarm is True

    def test_snapshot_mid_flight_under_pipeline_and_donation(self):
        """The satellite regression: snapshot_parts() -> worker-thread
        snapshot() while donated pipelined steps are consuming state
        buffers. Without the materialize boundary the worker reads an
        invalidated buffer and the snapshot dies."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = _mk_balancer(provider, batch_window=0.001, max_batch=8,
                               pipeline_depth=2, donate_state=True)
            assert bal._donate
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=4096,
                                              delay=0.05)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("snapmid", memory=128)

            async def one():
                p = await bal.publish(action, make_msg(action, ident, True))
                await p

            snaps = []

            async def snapshotter():
                # the BalancerSnapshotter pattern: parts on the loop, the
                # heavy transfer on a worker thread, racing live dispatches
                for _ in range(6):
                    parts = bal.snapshot_parts()
                    snaps.append(await asyncio.to_thread(bal.snapshot,
                                                         parts))
                    await asyncio.sleep(0.002)

            await asyncio.gather(snapshotter(),
                                 *[one() for _ in range(48)])
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return snaps

        snaps = asyncio.run(go())
        assert len(snaps) == 6
        for snap in snaps:
            assert len(snap["free_mb"]) == snap["n_pad"]
            # restore round-trips onto a fresh balancer
            fresh = _mk_balancer(MemoryMessagingProvider())
            fresh.restore(snap)
            assert np.asarray(fresh.state.free_mb).tolist() == snap["free_mb"]

    def test_failed_donated_admit_dispatch_reinits_bucket_carry(self):
        """Review regression: the admit step donates (state, buckets) as
        ONE carry, so a dispatch that fails after consuming the donation
        deletes the token-bucket arrays too. Recovery must re-init the
        carry (the _build_packed_fns guard keeps any non-None bucket
        state, deleted or not) or every later dispatch dies on 'Array has
        been deleted' — a permanent placement outage."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = _mk_balancer(provider, donate_state=True,
                               rate_limit_per_minute=600,
                               batch_window=0.001)
            assert bal._donate
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=2048)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("bucketheal", memory=128)
            real = bal._packed_fn
            armed = {"on": True}

            def consume_then_raise(carry, buf, now, r, h, b):
                out = real(carry, buf, now, r, h, b)
                if armed["on"]:
                    armed["on"] = False
                    raise RuntimeError("injected post-consumption failure")
                return out

            bal._packed_fn = consume_then_raise
            # publish awaits the placement future internally, so the
            # injected dispatch failure surfaces right here
            with pytest.raises(Exception, match="dispatch failed"):
                await bal.publish(action, make_msg(action, ident, True))
            # the consumed carry was re-initialized, not kept deleted
            assert bal._bucket_state is None or \
                not bal._bucket_state.tokens.is_deleted()
            # and the next dispatch places normally
            p2 = await bal.publish(action, make_msg(action, ident, True))
            await p2
            assert not bal._bucket_state.tokens.is_deleted()
            await bal.close()
            for inv in invokers:
                await inv.stop()

        asyncio.run(go())

    def test_failed_donated_idle_fold_rebuilds_state(self):
        """Review regression: the IDLE release fold (no pending requests)
        donates the state too — a failure past consumption must rebuild
        the books, or a drain-only balancer wedges forever on 'Array has
        been deleted' (the request-dispatch guard never runs without
        traffic)."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = _mk_balancer(provider, donate_state=True,
                               batch_window=0.001)
            assert bal._donate
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=2048)
            await _ping_all(invokers, producer)
            real = bal._release_packed_fn

            def consume_then_raise(state, rel):
                real(state, rel)
                raise RuntimeError("injected idle-fold failure")

            bal._release_packed_fn = consume_then_raise
            slot = bal._slots.acquire("heal:128")
            bal._queue_release(0, slot, 128, 1, "heal:128")
            await bal._device_step()  # idle: no pending -> release fold
            # the consumed state was rebuilt (and the fold fns with it)
            assert not bal.state.free_mb.is_deleted()
            assert bal._release_packed_fn is not consume_then_raise
            # the balancer still places after the outage
            ident = Identity.generate("guest")
            action = make_action("idleheal", memory=128)
            await (await bal.publish(action, make_msg(action, ident, True)))
            await bal.close()
            for inv in invokers:
                await inv.stop()

        asyncio.run(go())

    def test_occupancy_serves_cached_books_without_device(self):
        """occupancy() must never touch the device: after a placement it
        reflects the held capacity purely from the readback cache (the
        state reference is removed to prove no device read happens)."""
        async def go():
            provider = MemoryMessagingProvider()
            bal = _mk_balancer(provider)
            await bal.start()
            invokers, producer = await _fleet(provider, 2, memory_mb=2048,
                                              delay=0.5)
            await _ping_all(invokers, producer)
            ident = Identity.generate("guest")
            action = make_action("occache", memory=256)
            promise = await bal.publish(action, make_msg(action, ident, True))
            state_ref, bal.state = bal.state, None  # any device read crashes
            try:
                mid = bal.occupancy()
            finally:
                bal.state = state_ref
            await promise
            await bal.close()
            for inv in invokers:
                await inv.stop()
            return mid

        mid = asyncio.run(go())
        assert mid["fleet"]["used_mb"] == 256
        assert bool(TpuBalancer.OCCUPANCY_SYNCS_DEVICE) is False

    def test_scan_depth1_legacy_path_is_bit_exact(self):
        """placement_kernel=scan + pipeline_depth=1 + no donation + legacy
        assembly must place a deterministic request sequence on EXACTLY the
        invokers the default (repair+pipelined+donated+ring) path picks."""
        def run(**cfg):
            async def go():
                provider = MemoryMessagingProvider()
                bal = _mk_balancer(provider, **cfg)
                await bal.start()
                invokers, producer = await _fleet(provider, 3,
                                                  memory_mb=2048)
                await _ping_all(invokers, producer)
                ident = Identity.generate("guest")
                placed = []
                for i in range(24):
                    action = make_action(f"legacy{i % 3}", memory=256)
                    p = await bal.publish(action,
                                          make_msg(action, ident, True))
                    entry = bal.activation_slots[
                        list(bal.activation_slots)[-1]]
                    placed.append(entry.invoker.instance)
                    await p
                await bal.close()
                for inv in invokers:
                    await inv.stop()
                return placed

            return asyncio.run(go())

        modern = run()
        legacy = run(placement_kernel="scan", pipeline_depth=1,
                     donate_state=False, ring_assembly=False)
        assert modern == legacy

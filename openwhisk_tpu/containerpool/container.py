"""The Container abstraction: /init + /run HTTP contract.

Rebuild of common/scala/.../core/containerpool/Container.scala:54-239 — a
container is an opaque sandbox reachable over HTTP: POST /init loads the
code, POST /run executes one activation; suspend/resume implement the pause
grace; `logs` drains stdout/stderr up to the sentinel line the runtime
prints after each activation (Container.scala ACTIVATION_LOG_SENTINEL).
"""
from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import aiohttp

ACTIVATION_LOG_SENTINEL = "XXX_THE_END_OF_A_WHISK_ACTIVATION_XXX"


class ContainerError(Exception):
    pass


class InitializationError(ContainerError):
    def __init__(self, message: str, response: Optional[dict] = None):
        super().__init__(message)
        self.response = response


@dataclass
class RunResult:
    start: float
    end: float
    response: Optional[Dict[str, Any]]
    ok: bool
    timed_out: bool = False
    #: transport-level failure (socket died mid-request): the container's
    #: state is unknown, the proxy must treat it as a whisk error and
    #: destroy — NOT as the user code's own error
    connection_failed: bool = False

    @property
    def interval_ms(self) -> int:
        return int((self.end - self.start) * 1000)


class Container:
    """Abstract container; concrete drivers: process (subprocess sandbox),
    docker (CLI), stubs in tests."""

    def __init__(self, container_id: str, addr: Tuple[str, int]):
        self.container_id = container_id
        self.addr = addr
        self._session: Optional[aiohttp.ClientSession] = None
        self._http_lock = asyncio.Lock()

    # -- lifecycle (driver-specific) ---------------------------------------
    async def suspend(self) -> None:
        raise NotImplementedError

    async def resume(self) -> None:
        raise NotImplementedError

    async def destroy(self) -> None:
        if self._session:
            await self._session.close()
            self._session = None

    async def logs(self, limit_bytes: int = 10 * 1024 * 1024,
                   wait_for_sentinel: bool = True) -> List[str]:
        raise NotImplementedError

    # -- HTTP contract -----------------------------------------------------
    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            # force_close: one fresh connection per request. Keep-alive
            # reuse races the container closing idle sockets (e.g. around
            # pause/resume) and surfaces as ServerDisconnectedError on /run
            # — which must NOT be retried (at-most-once for user code), so
            # the stale-socket case is removed structurally instead
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(force_close=True))
        return self._session

    async def _post(self, path: str, payload: dict, timeout: float,
                    retry_disconnects: bool = False) -> Tuple[int, dict]:
        """POST with connect retries: a cold container's server may not be
        listening yet (the reference's HttpUtils retries until the socket
        opens, bounded only by the caller's timeout). `retry_disconnects`
        additionally retries ServerDisconnectedError — only /init opts in
        (idempotent; ref Container.scala:123 retry=true vs :168 run
        retry=false, NoHttpResponseException handling in
        ApacheBlockingContainerClient.scala:160-163)."""
        url = f"http://{self.addr[0]}:{self.addr[1]}{path}"
        last: Optional[Exception] = None
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                async with self._http().post(
                        url, json=payload,
                        timeout=aiohttp.ClientTimeout(total=remaining)) as resp:
                    try:
                        body = await resp.json(content_type=None)
                    except (json.JSONDecodeError, aiohttp.ContentTypeError):
                        body = {"error": (await resp.text())[:1024]}
                    return resp.status, body if isinstance(body, dict) else {"value": body}
            except (aiohttp.ClientConnectorError, ConnectionRefusedError) as e:
                last = e
                await asyncio.sleep(0.05)
            except aiohttp.ServerDisconnectedError as e:
                if not retry_disconnects:
                    raise ContainerError(
                        f"connection to container {self.container_id} "
                        f"failed: {e!r}") from e
                last = e
                await asyncio.sleep(0.05)
            except asyncio.TimeoutError:
                return 408, {"error": f"request to {path} timed out"}
            except (aiohttp.ClientError, OSError) as e:
                # container died mid-request (OOM kill, crash): not retryable
                raise ContainerError(
                    f"connection to container {self.container_id} failed: {e!r}") from e
        raise ContainerError(f"cannot connect to container {self.container_id}: {last!r}")

    async def initialize(self, init_payload: dict, timeout: float = 60.0) -> int:
        """POST /init; returns init duration in ms. Raises
        InitializationError on non-OK (ref Container.initialize:113-150)."""
        t0 = time.monotonic()
        status, body = await self._post("/init", {"value": init_payload},
                                        timeout, retry_disconnects=True)
        dt = int((time.monotonic() - t0) * 1000)
        if status == 408:
            raise InitializationError(
                f"initialization exceeded its time limit of {timeout} s", body)
        if status != 200:
            raise InitializationError(
                body.get("error", f"initialization failed with status {status}"), body)
        return dt

    async def run(self, args: Dict[str, Any], environment: Dict[str, Any],
                  timeout: float = 60.0) -> RunResult:
        """POST /run (ref Container.run:153-189). Never raises on action
        errors — the response body carries them."""
        start = time.time()
        payload = {"value": args, **environment}
        try:
            status, body = await self._post("/run", payload, timeout)
        except ContainerError as e:
            return RunResult(start, time.time(), {"error": str(e)}, ok=False,
                             connection_failed=True)
        end = time.time()
        if status == 408:
            return RunResult(start, end,
                             {"error": f"action exceeded its time limit of {timeout} s"},
                             ok=False, timed_out=True)
        return RunResult(start, end, body, ok=(status == 200))

    def __repr__(self):
        return f"{type(self).__name__}({self.container_id}@{self.addr[0]}:{self.addr[1]})"

"""Fixed-size ring buffer (ref common/scala/.../utils/RingBuffer.scala).

Used by invoker supervision to keep the last N invocation results
(InvokerSupervision.scala:435-443 keeps 10 with error tolerance 3).
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Generic, List, TypeVar

T = TypeVar("T")


class RingBuffer(Generic[T]):
    def __init__(self, size: int):
        self._buf: Deque[T] = deque(maxlen=size)
        self.size = size

    def add(self, item: T) -> None:
        self._buf.append(item)

    def to_list(self) -> List[T]:
        return list(self._buf)

    def count(self, predicate: Callable[[T], bool]) -> int:
        return sum(1 for x in self._buf if predicate(x))

    def __len__(self) -> int:
        return len(self._buf)

"""S3 attachment store against a fake S3 that RE-VERIFIES every AWS
SigV4 signature server-side — proving the signing implementation from the
spec, not just the happy path. Contract: attach/read/delete-except
(ref S3AttachmentStore.scala), NoSuchKey -> NoDocumentException, wrong
secret -> 403 surfaced."""
import asyncio
import datetime
from urllib.parse import quote, unquote

import pytest
from aiohttp import web

from openwhisk_tpu.database import NoDocumentException
from openwhisk_tpu.database.s3_attachment_store import (S3AttachmentStore,
                                                        S3AttachmentStoreProvider,
                                                        sign_v4)
from openwhisk_tpu.database.store import ArtifactStoreException

ACCESS, SECRET = "AKIDEXAMPLE", "s3cr3t-key"


class FakeS3:
    def __init__(self):
        self.objects = {}  # (bucket, key) -> (content_type, bytes)
        self.runner = None

    async def start(self):
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.dispatch)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def stop(self):
        await self.runner.cleanup()

    def _verify(self, request, payload: bytes) -> bool:
        """Recompute the SigV4 signature with the known secret and compare
        against the Authorization header the client sent."""
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        amz_date = request.headers.get("x-amz-date", "")
        now = datetime.datetime.strptime(
            amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc)
        raw_path = request.rel_url.raw_path.split("?")[0]
        query = sorted((k, v) for k, v in request.rel_url.query.items())
        expect = sign_v4(request.method, request.headers["Host"],
                         unquote(raw_path), query, payload,
                         ACCESS, SECRET, now=now)
        return expect["Authorization"] == auth

    async def dispatch(self, request: web.Request) -> web.Response:
        payload = await request.read()
        if not self._verify(request, payload):
            return web.Response(status=403, text="SignatureDoesNotMatch")
        raw = request.rel_url.raw_path.split("?")[0]
        segs = raw.split("/", 2)  # '', bucket, key
        bucket = segs[1]
        key = unquote(segs[2]) if len(segs) > 2 and segs[2] else ""
        if request.method == "PUT":
            self.objects[(bucket, key)] = (request.content_type, payload)
            return web.Response(status=200)
        if request.method == "GET" and key:
            obj = self.objects.get((bucket, key))
            if obj is None:
                return web.Response(status=404, text="NoSuchKey")
            return web.Response(body=obj[1], content_type=obj[0])
        if request.method == "GET":  # ListObjectsV2
            prefix = request.rel_url.query.get("prefix", "")
            keys = sorted(k for (b, k) in self.objects
                          if b == bucket and k.startswith(prefix))
            xml = ("<?xml version='1.0'?>"
                   "<ListBucketResult xmlns='http://s3.amazonaws.com/doc/"
                   "2006-03-01/'>" +
                   "".join(f"<Contents><Key>{k}</Key></Contents>"
                           for k in keys) +
                   "</ListBucketResult>")
            return web.Response(text=xml, content_type="application/xml")
        if request.method == "DELETE":
            self.objects.pop((bucket, key), None)
            return web.Response(status=204)
        return web.Response(status=405)


def _store(url, secret=SECRET):
    return S3AttachmentStore(url, bucket="whisk", access_key=ACCESS,
                             secret_key=secret)


class TestS3AttachmentStore:
    def test_attach_read_roundtrip_with_verified_signatures(self):
        async def go():
            fake = FakeS3()
            url = await fake.start()
            store = _store(url)
            await store.attach("ns/pkg/act", "codefile-1",
                               "application/zip", b"\x01\x02")
            ct, data = await store.read_attachment("ns/pkg/act", "codefile-1")
            assert (ct, data) == ("application/zip", b"\x01\x02")
            # key layout mirrors the reference: prefix/encoded-docid/name
            assert ("whisk",
                    f"whisk-attachments/{quote('ns/pkg/act', safe='')}"
                    "/codefile-1") in fake.objects
            await store.close()
            await fake.stop()
        asyncio.run(go())

    def test_missing_reads_as_no_document(self):
        async def go():
            fake = FakeS3()
            url = await fake.start()
            store = _store(url)
            with pytest.raises(NoDocumentException):
                await store.read_attachment("ns/a", "ghost")
            await store.close()
            await fake.stop()
        asyncio.run(go())

    def test_delete_attachments_except_current(self):
        async def go():
            fake = FakeS3()
            url = await fake.start()
            store = _store(url)
            for name in ("codefile-old", "codefile-new"):
                await store.attach("ns/a", name, "text/plain", name.encode())
            await store.attach("ns/other", "codefile-x", "text/plain", b"x")
            await store.delete_attachments("ns/a", except_name="codefile-new")
            with pytest.raises(NoDocumentException):
                await store.read_attachment("ns/a", "codefile-old")
            _, kept = await store.read_attachment("ns/a", "codefile-new")
            assert kept == b"codefile-new"
            # other docs' blobs untouched
            _, other = await store.read_attachment("ns/other", "codefile-x")
            assert other == b"x"
            await store.delete_attachments("ns/a")
            with pytest.raises(NoDocumentException):
                await store.read_attachment("ns/a", "codefile-new")
            await store.close()
            await fake.stop()
        asyncio.run(go())

    def test_wrong_secret_rejected(self):
        async def go():
            fake = FakeS3()
            url = await fake.start()
            store = _store(url, secret="wrong")
            with pytest.raises(ArtifactStoreException, match="403"):
                await store.attach("ns/a", "c", "text/plain", b"x")
            await store.close()
            await fake.stop()
        asyncio.run(go())

    def test_delegated_from_artifact_store(self):
        """The with_attachment_store seam: entity code blobs land in S3
        while documents stay in the doc store (ref CouchDbRestStore's
        attachmentStore slot)."""
        async def go():
            from openwhisk_tpu.core.entity import (CodeExec, EntityName,
                                                   EntityPath, WhiskAction)
            from openwhisk_tpu.database import EntityStore, MemoryArtifactStore
            fake = FakeS3()
            url = await fake.start()
            s3 = _store(url)
            store = MemoryArtifactStore().with_attachment_store(s3)
            es = EntityStore(store)
            big = "def main(a): return {}\n" + "#" * 70000
            a = WhiskAction(EntityPath("guest"), EntityName("big"),
                            CodeExec(kind="python:3", code=big))
            await es.put(a)
            got = await es.get_action("guest/big")
            assert got.exec.code == big
            assert any(b == "whisk" for (b, _k) in fake.objects), \
                "code blob must land in the S3 bucket"
            await store.close()
            await fake.stop()
        asyncio.run(go())


class TestSigV4:
    def test_known_vector_shape(self):
        """Deterministic signing: same inputs -> same signature; differing
        payload/secret/path each change it."""
        now = datetime.datetime(2026, 7, 30, 12, 0, 0,
                                tzinfo=datetime.timezone.utc)
        a = sign_v4("PUT", "s3.local", "/b/k", [], b"x", "AK", "SK", now=now)
        b = sign_v4("PUT", "s3.local", "/b/k", [], b"x", "AK", "SK", now=now)
        assert a == b
        assert a["x-amz-date"] == "20260730T120000Z"
        for variant in (
                sign_v4("PUT", "s3.local", "/b/k", [], b"y", "AK", "SK", now=now),
                sign_v4("PUT", "s3.local", "/b/k2", [], b"x", "AK", "SK", now=now),
                sign_v4("PUT", "s3.local", "/b/k", [], b"x", "AK", "S2", now=now)):
            assert variant["Authorization"] != a["Authorization"]
